"""Closed- and open-loop load generator for the scoring service.

    python scripts/serve_loadgen.py --url http://127.0.0.1:8000 \\
        [--mode closed|open|both] [--duration 10] [--workers 4] \\
        [--rows 8] [--qps 200] [--endpoint /v1/score]

Two loop disciplines, because they answer different questions:

  * **closed** — N workers fire back-to-back requests (a new request
    the moment the previous response lands).  Measures the service's
    throughput ceiling; latency under closed load is a function of the
    worker count, not of the service alone.
  * **open** — requests fire on a fixed schedule at ``--qps``
    regardless of responses (the Poisson-ish arrival pattern real
    traffic has).  Measures latency at a given offered load and how
    the 429 backpressure behaves past saturation; a closed loop can
    never see those, because it slows itself down.

Payloads are random uint8 images shaped from the server's own
``/healthz`` (``image_shape``), sent as ``{"b64", "shape"}`` — the
efficient wire path.  Output: ONE JSON line per mode with achieved
qps/ips, p50/p99 latency (nearest-rank, the server's convention), and
status counts.  Stdlib only; keep-alive via one http.client connection
per worker.
"""

from __future__ import annotations

import argparse
import base64
import concurrent.futures
import http.client
import json
import sys
import threading
import time
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

import numpy as np


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    # Same nearest-rank convention as serve/metrics.py.
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return float(sorted_vals[idx])


def fetch_health(url: str, timeout: float = 10.0) -> Dict:
    with urllib.request.urlopen(f"{url}/healthz", timeout=timeout) as r:
        return json.loads(r.read().decode())


def make_payload(image_shape, rows: int, seed: int = 0) -> bytes:
    h, w, c = image_shape
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(rows, h, w, c), dtype=np.uint8)
    return json.dumps({
        "b64": base64.b64encode(images.tobytes()).decode(),
        "shape": [rows, h, w, c],
    }).encode()


class _Worker:
    """One keep-alive connection; returns (status, latency_s) per post."""

    def __init__(self, url: str, timeout: float = 30.0):
        p = urllib.parse.urlparse(url)
        self._host, self._port = p.hostname, p.port or 80
        self._timeout = timeout
        self._conn: Optional[http.client.HTTPConnection] = None

    def post(self, path: str, body: bytes):
        t0 = time.perf_counter()
        for attempt in (0, 1):  # one reconnect on a dropped keep-alive
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=self._timeout)
            try:
                self._conn.request(
                    "POST", path, body=body,
                    headers={"Content-Type": "application/json"})
                resp = self._conn.getresponse()
                resp.read()
                if resp.getheader("Connection", "").lower() == "close":
                    self._conn.close()
                    self._conn = None
                return resp.status, time.perf_counter() - t0
            except (http.client.HTTPException, OSError):
                self._conn = None
                if attempt:
                    raise
        raise AssertionError("unreachable")


def _summarize(mode: str, statuses: List[int], lats: List[float],
               wall: float, rows_per_req: int, offered_qps=None) -> Dict:
    lats = sorted(lats)
    n_ok = sum(1 for s in statuses if s == 200)
    out = {
        "mode": mode,
        "wall_s": round(wall, 2),
        "n_requests": len(statuses),
        "n_ok": n_ok,
        "n_429": sum(1 for s in statuses if s == 429),
        "n_err": sum(1 for s in statuses if s not in (200, 429)),
        "rows_per_request": rows_per_req,
        "qps": round(n_ok / wall, 2) if wall > 0 else 0.0,
        "ips": round(n_ok * rows_per_req / wall, 1) if wall > 0 else 0.0,
        "p50_ms": _ms(_percentile(lats, 0.50)),
        "p99_ms": _ms(_percentile(lats, 0.99)),
    }
    if offered_qps is not None:
        out["offered_qps"] = offered_qps
    return out


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1000.0, 3)


def run_closed(url: str, duration_s: float, workers: int, rows: int,
               image_shape, endpoint: str = "/v1/score",
               warmup_requests: int = 2) -> Dict:
    """Closed loop: ``workers`` threads, back-to-back requests."""
    body = make_payload(image_shape, rows)
    # inf until the window opens: a worker racing past the barrier ahead
    # of the main thread's deadline write must keep looping, not exit.
    stop_at = [float("inf")]
    # Workers warm their connection + the service's first batches OFF
    # the clock, rendezvous at the barrier, and only then does the main
    # thread open the measurement window.
    barrier = threading.Barrier(workers + 1)
    lock = threading.Lock()
    statuses: List[int] = []
    lats: List[float] = []

    def loop(seed: int):
        w = _Worker(url)
        for _ in range(warmup_requests):  # connection + first-batch warm
            w.post(endpoint, body)
        barrier.wait()
        local_s, local_l = [], []
        while time.perf_counter() < stop_at[0]:
            s, dt = w.post(endpoint, body)
            local_s.append(s)
            local_l.append(dt)
        with lock:
            statuses.extend(local_s)
            lats.extend(local_l)

    threads = [threading.Thread(target=loop, args=(i,), daemon=True)
               for i in range(workers)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    stop_at[0] = t0 + duration_s
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    out = _summarize("closed", statuses, lats, wall, rows)
    out["workers"] = workers
    return out


def run_open(url: str, duration_s: float, qps: float, rows: int,
             image_shape, endpoint: str = "/v1/score",
             max_inflight: int = 256) -> Dict:
    """Open loop: fire at ``qps`` on schedule, independent of responses.
    Requests the schedule could not launch (pool exhausted) count as
    errors — offered load is part of the measurement."""
    body = make_payload(image_shape, rows)
    lock = threading.Lock()
    statuses: List[int] = []
    lats: List[float] = []
    local = threading.local()

    def one():
        w = getattr(local, "w", None)
        if w is None:
            w = local.w = _Worker(url)
        try:
            s, dt = w.post(endpoint, body)
        except OSError:
            s, dt = -1, None
        with lock:
            statuses.append(s)
            if dt is not None and s == 200:
                lats.append(dt)

    n = max(1, int(duration_s * qps))
    interval = 1.0 / qps
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(max_inflight) as pool:
        futures = []
        for i in range(n):
            target = t0 + i * interval
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            futures.append(pool.submit(one))
        for f in futures:
            f.result()
    wall = time.perf_counter() - t0
    return _summarize("open", statuses, lats, wall, rows, offered_qps=qps)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--url", default="http://127.0.0.1:8000")
    ap.add_argument("--mode", default="both",
                    choices=["closed", "open", "both"])
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--workers", type=int, default=4,
                    help="closed-loop concurrency")
    ap.add_argument("--rows", type=int, default=8,
                    help="images per request")
    ap.add_argument("--qps", type=float, default=None,
                    help="open-loop offered load (default: 70%% of the "
                         "closed loop's measured qps)")
    ap.add_argument("--endpoint", default="/v1/score",
                    choices=["/v1/score", "/v1/predict"])
    args = ap.parse_args(argv)

    health = fetch_health(args.url)
    shape = health["image_shape"]
    results = []
    if args.mode in ("closed", "both"):
        results.append(run_closed(args.url, args.duration, args.workers,
                                  args.rows, shape, args.endpoint))
        print(json.dumps(results[-1]), flush=True)
    if args.mode in ("open", "both"):
        qps = args.qps
        if qps is None:
            # Probe at 70% of the measured ceiling: open-loop latency is
            # only meaningful below saturation.
            base = results[0]["qps"] if results else 20.0
            qps = max(1.0, 0.7 * base)
        results.append(run_open(args.url, args.duration, qps, args.rows,
                                shape, args.endpoint))
        print(json.dumps(results[-1]), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
