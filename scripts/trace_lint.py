#!/usr/bin/env python
"""trace_lint: spans and phase metrics must be ONE measurement.

The telemetry design (DESIGN.md §7) hangs on a single invariant: every
phase timer routes through the span tracer, so the Chrome trace and the
``rd_{name}`` metrics can never silently fork — a phase that appears in
metrics.jsonl but not in trace.json (or with a different duration)
would make the trace unusable as evidence.  This lint enforces the
routing statically, invoked from tier-1 (tests/test_telemetry.py):

  1. ``utils/tracing.phase_timer`` itself must open a tracer span and
     derive its reported seconds FROM that span (not a second clock).
  2. Nobody else may define a ``phase_timer`` (a fork would bypass the
     tracer while keeping the metric name).
  3. Every module calling ``phase_timer(`` must import it from
     ``utils.tracing`` — no copies, no local re-implementations.
  4. ``jax.profiler.TraceAnnotation`` stays behind ``tracing.annotate``
     (one device-naming convention; the whitelist is the device-truth
     layer, telemetry/profiler.py, which tracing.annotate delegates to).

It also enforces the trainer's ZERO-HOST-COPY feed invariant (the
resident-gather train feed, DESIGN.md §2a):

  5. ``train/trainer.py`` must define every function in
     ``RESIDENT_FEED_FNS``, and none of them may materialize image data
     on the host — no ``np.*`` usage, no ``.gather(`` call, no
     ``.asarray``/``.concatenate`` — so "train batches never touch the
     host" is a statically-checked property, not just a benched one.

... and the sharded pool's SCALE-OUT invariant (row-sharded selection,
DESIGN.md §2b):

  6. ``strategies/kcenter.py`` must define every function in its
     ``SHARDED_SELECTION_FNS``, and none of them may defeat the
     sharding: no full-pool host materialization (``np.*`` references,
     ``jax.device_get``, ``.asarray``) and no replication of a
     row-sharded array (``replicate(`` / ``replicated_sharding(``
     calls) — a 10.5 GB factor matrix pulled whole onto one host or
     chip is exactly the ceiling the sharded backend exists to break.

... and the pipelined round's NEVER-SYNC-THE-TRAIN-STREAM invariant
(speculative scoring, DESIGN.md §8):

  7. ``experiment/pipeline.py`` must define every function in
     ``PIPELINE_COORDINATOR_FNS``, and none of them may call
     ``block_until_ready`` or ``device_get`` — the speculative scorer
     overlaps the fit's patience tail, and a coordinator-level device
     sync would serialize the very streams the module exists to
     overlap.  (The scorer may wait on its OWN chunk outputs inside
     collect_pool's host fetch — that blocks only its thread — and the
     DispatchGate's CPU-only execution drain lives in parallel/mesh.py,
     deliberately outside the lint's reach: it is the backend
     workaround, not the coordinator.)

... and the failure model's CLOSED-REGISTRY invariant (fault injection,
DESIGN.md §10):

  8. Every ``faults.site()`` call site names a string-literal site that
     is registered in ``faults/registry.py``'s ``SITES`` tuple, each
     registered name appears there exactly once AND is wired at ≥1 call
     site (a typo'd or orphaned site would make chaos coverage silently
     vacuous), and every ``RetryPolicy(...)`` construction passes an
     explicit ``classify=`` keyword — the "no bare ``except Exception:
     retry``" rule: what a call site considers transient is always
     written at the call site.

... and the gradient path's PROVEN-BACKWARD invariant (custom VJPs +
the fused optimizer, DESIGN.md §4):

  9. Every ``jax.custom_vjp`` in the package lives in
     ``ops/backward.py`` (a hand-written backward anywhere else would
     dodge the registry), its public name appears in that module's
     ``TRAIN_PATH_VJPS`` tuple, and ``tests/test_backward.py``'s
     ``PARITY_TESTED_VJPS`` tuple matches it exactly — a closed
     registry like check 8: a custom backward without a registered
     gradient-parity test can never land.  The fused optimizer-update
     functions (``train/optim.py``'s ``FUSED_UPDATE_FNS``) run inside
     the donated train step and are forbidden host materialization
     (``np.*`` references, ``.asarray``/``device_get``/
     ``block_until_ready`` calls).

... and the device-truth layer's ONE-GATE invariant (bounded profiler
capture windows, DESIGN.md §11):

  10. ``jax.profiler`` may only be imported or invoked inside
      ``telemetry/profiler.py`` — no ``import jax.profiler`` /
      ``from jax import profiler``, no ``jax.profiler`` attribute
      access, and no ``start_trace``/``stop_trace`` call (under ANY
      alias) anywhere else.  Every capture window goes through the
      gated API (``capture_window``/``start_capture``/
      ``finish_capture``), which is what makes "one capture at a time,
      always stopped on failure, always merged and classified" a
      property of the system instead of a convention — and the gate
      module itself must define those entry points and actually touch
      jax.profiler (a renamed-away gate would make the check vacuous).
      A closed registry like checks 8 and 9.

Stdlib only; exits 0 clean / 1 with findings on stderr.
"""

from __future__ import annotations

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "active_learning_tpu")
TRACING = os.path.join(PKG, "utils", "tracing.py")
PROFILER = os.path.join(PKG, "telemetry", "profiler.py")

# The one module allowed to touch jax.profiler (TraceAnnotation included):
# the device-truth layer.  tracing.annotate delegates here.
ANNOTATION_WHITELIST = {PROFILER}

# Capture-window entry points: calling either outside the gate module —
# under any alias — dodges the one-capture-at-a-time/always-stopped/
# always-merged contract.
_CAPTURE_CALLS = {"start_trace", "stop_trace"}
# The gated API the gate module must define (a renamed-away gate would
# make check 10 vacuous).
_PROFILER_GATE_FNS = ("start_capture", "finish_capture", "capture_window",
                      "trace_annotation")

TRAINER = os.path.join(PKG, "train", "trainer.py")
# The trainer functions that ARE the resident-gather feed path: each must
# exist (renaming one away would silently drop the enforcement) and must
# never materialize image arrays on the host.
RESIDENT_FEED_FNS = ("_resident_feed_arrays", "_build_resident_batch_step")
# Host-materialization markers forbidden inside those functions.
_HOST_COPY_CALLS = {"gather", "asarray", "concatenate", "ascontiguousarray",
                    "stack", "copy"}

KCENTER = os.path.join(PKG, "strategies", "kcenter.py")
# The kcenter functions that ARE the row-sharded selection backend (the
# module's own SHARDED_SELECTION_FNS names the device builder; this
# mirror exists so the lint works without importing jax).  Each must
# exist, and none may defeat the sharding.  Two rule sets:
#   device tier (_build_sharded_fns — everything traced onto the mesh):
#     no np.* at all, no jax.device_get/.asarray host fetches, no
#     replicate/replicated_sharding calls;
#   orchestrator tier (_kcenter_greedy_sharded — owns the HOST copy of
#     the factors by design, so np index math is fine): no
#     jax.device_get and no replicate/replicated_sharding — the device
#     pool must never round-trip to host or be replicated per chip.
# NOTE: lax.all_gather of the O(N) weight VECTOR is allowed (the
# randomized D^2 draw needs the global weights); what is forbidden is
# pulling the [N, D] factor matrix whole.
SHARDED_DEVICE_FNS = ("_build_sharded_fns",)
SHARDED_ORCHESTRATOR_FNS = ("_kcenter_greedy_sharded",)
_SHARDED_HOST_CALLS = {"device_get", "asarray"}
_SHARDED_REPLICATE_CALLS = {"replicate", "replicated_sharding"}

PIPELINE = os.path.join(PKG, "experiment", "pipeline.py")
# Mirror of experiment/pipeline.PIPELINE_COORDINATOR_FNS (kept in both
# places so the lint works without importing jax): the coordinator tier
# of the speculative scorer.  Each must exist; none may device-sync.
PIPELINE_COORDINATOR_FNS = ("_worker", "_worker_loop", "_score_slice",
                            "_score_chunk", "publish_best", "finalize",
                            "consume")
_PIPELINE_SYNC_CALLS = {"block_until_ready", "device_get"}

FAULTS_REGISTRY = os.path.join(PKG, "faults", "registry.py")

OPS_BACKWARD = os.path.join(PKG, "ops", "backward.py")
OPTIM = os.path.join(PKG, "train", "optim.py")
BACKWARD_TESTS = os.path.join(REPO, "tests", "test_backward.py")
# Host-materialization markers forbidden inside the fused optimizer
# update functions (they trace inside the donated train step).
_FUSED_HOST_CALLS = {"asarray", "device_get", "block_until_ready",
                     "gather"}


def _py_files():
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in files:
            if name.endswith(".py"):
                yield os.path.join(root, name)
    yield os.path.join(REPO, "bench.py")
    scripts = os.path.join(REPO, "scripts")
    if os.path.isdir(scripts):
        for name in os.listdir(scripts):
            if name.endswith(".py") and name != "trace_lint.py":
                yield os.path.join(scripts, name)


def _imports_phase_timer_from_tracing(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.endswith("tracing") and any(
                    a.name == "phase_timer" for a in node.names):
                return True
    return False


def check() -> list:
    problems = []

    # 1. The shim itself routes through the tracer.
    with open(TRACING) as fh:
        tracing_src = fh.read()
    timer_body = tracing_src.split("def phase_timer", 1)
    if len(timer_body) != 2:
        problems.append(f"{TRACING}: phase_timer not found")
        timer_src = ""
    else:
        # Up to the next top-level def.
        timer_src = re.split(r"\n@|\ndef ", timer_body[1], maxsplit=1)[0]
    if ".span(" not in timer_src:
        problems.append(
            f"{TRACING}: phase_timer does not open a tracer span — "
            "phase metrics would fork from the trace")
    if "duration_s" not in timer_src:
        problems.append(
            f"{TRACING}: phase_timer does not take its seconds from the "
            "span (two clocks = metric/trace drift)")

    for path in _py_files():
        rel = os.path.relpath(path, REPO)
        with open(path) as fh:
            src = fh.read()
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            problems.append(f"{rel}: unparseable ({e})")
            continue

        # 2. No competing phase_timer definitions.
        if path != TRACING:
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node.name == "phase_timer":
                    problems.append(
                        f"{rel}:{node.lineno}: defines its own "
                        "phase_timer — route through utils.tracing")

        # 3. Call sites import the shim.
        calls = [n for n in ast.walk(tree)
                 if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Name)
                 and n.func.id == "phase_timer"]
        if calls and path != TRACING \
                and not _imports_phase_timer_from_tracing(tree):
            problems.append(
                f"{rel}:{calls[0].lineno}: calls phase_timer without "
                "importing it from utils.tracing")

        # 4. Device annotations stay behind tracing.annotate (AST-level:
        # docstring mentions are fine, attribute uses are not).
        if path not in ANNOTATION_WHITELIST:
            for node in ast.walk(tree):
                if isinstance(node, ast.Attribute) \
                        and node.attr == "TraceAnnotation":
                    problems.append(
                        f"{rel}:{node.lineno}: uses jax.profiler."
                        "TraceAnnotation directly — use utils.tracing."
                        "annotate so device spans keep one naming "
                        "convention")

    # 5. The resident-gather train feed stays zero-host-copy.
    problems.extend(check_resident_feed())

    # 6. The sharded selection backend never un-shards the pool.
    problems.extend(check_sharded_selection())

    # 7. The speculative-scoring coordinator never syncs the train
    # stream.
    problems.extend(check_pipeline_coordinator())

    # 8. The fault-injection registry is closed, fully wired, and every
    # retry call site classifies.
    problems.extend(check_fault_sites())

    # 9. Every custom VJP is registered and parity-tested; the fused
    # optimizer update never touches the host.
    problems.extend(check_backward_registry())

    # 10. jax.profiler stays confined to the device-truth layer and
    # every capture window goes through its gated API.
    problems.extend(check_profiler_confinement())

    return problems


def _str_tuple(tree: ast.AST, name: str, rel: str, problems: list):
    """Parse a module-level ``NAME = ("a", "b", ...)`` tuple of string
    literals; returns None (with a finding) when absent/non-literal."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                break
            names = []
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                                str):
                    names.append(elt.value)
                else:
                    problems.append(
                        f"{rel}:{elt.lineno}: {name} holds a non-literal "
                        "entry — the registry must be statically "
                        "checkable")
            return names
    problems.append(f"{rel}: {name} tuple not found — the backward "
                    "registry has nothing to check against")
    return None


def check_backward_registry(files=None, ops_path: str = OPS_BACKWARD,
                            optim_path: str = OPTIM,
                            tests_path: str = BACKWARD_TESTS) -> list:
    """The gradient path's proven-backward invariant, statically
    (check 9): custom VJPs only in ops/backward.py, every one named in
    its ``TRAIN_PATH_VJPS`` and matched by ``PARITY_TESTED_VJPS`` in
    tests/test_backward.py, and the fused optimizer-update functions
    free of host materialization.  ``files`` given = a negative-case
    unit test on a fragment (the custom_vjp location scan only)."""
    problems = []

    # a) custom_vjp usage is confined to ops/backward.py.
    full_tree = files is None
    paths = list(_py_files()) if full_tree else list(files)
    for path in paths:
        if os.path.abspath(path) == os.path.abspath(ops_path):
            continue
        rel = os.path.relpath(path, REPO)
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError) as e:
            problems.append(f"{rel}: unreadable for the backward-registry "
                            f"check ({e})")
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr == "custom_vjp":
                problems.append(
                    f"{rel}:{node.lineno}: jax.custom_vjp outside "
                    "ops/backward.py — hand-written backwards live in "
                    "the closed registry (TRAIN_PATH_VJPS) so each one "
                    "carries a gradient-parity test")
    if not full_tree:
        return problems

    # b) the registry itself: TRAIN_PATH_VJPS names exist as defs and
    # the module really uses custom_vjp.
    rel_ops = os.path.relpath(ops_path, REPO)
    try:
        with open(ops_path) as fh:
            ops_tree = ast.parse(fh.read())
    except (OSError, SyntaxError) as e:
        return problems + [f"{rel_ops}: unreadable for the "
                           f"backward-registry check ({e})"]
    registered = _str_tuple(ops_tree, "TRAIN_PATH_VJPS", rel_ops, problems)
    if registered is None:
        return problems
    defs = {n.name for n in ast.walk(ops_tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in registered:
        if name not in defs:
            problems.append(
                f"{rel_ops}: TRAIN_PATH_VJPS names {name!r} but no such "
                "function is defined — the registry drifted from the "
                "module")
    if not any(isinstance(n, ast.Attribute) and n.attr == "custom_vjp"
               for n in ast.walk(ops_tree)):
        problems.append(
            f"{rel_ops}: no jax.custom_vjp usage found — TRAIN_PATH_VJPS "
            "registers backwards that do not exist")

    # c) every registered VJP has a registered parity test.
    rel_tests = os.path.relpath(tests_path, REPO)
    try:
        with open(tests_path) as fh:
            tests_tree = ast.parse(fh.read())
    except (OSError, SyntaxError) as e:
        return problems + [f"{rel_tests}: unreadable — every custom VJP "
                           f"must carry a parity test ({e})"]
    tested = _str_tuple(tests_tree, "PARITY_TESTED_VJPS", rel_tests,
                        problems)
    if tested is not None and set(tested) != set(registered):
        problems.append(
            f"{rel_tests}: PARITY_TESTED_VJPS {sorted(tested)} != "
            f"TRAIN_PATH_VJPS {sorted(registered)} — a custom backward "
            "without a registered gradient-parity test (or a stale test "
            "registration) can never land")

    # d) the fused update functions never touch the host.
    rel_optim = os.path.relpath(optim_path, REPO)
    try:
        with open(optim_path) as fh:
            optim_tree = ast.parse(fh.read())
    except (OSError, SyntaxError) as e:
        return problems + [f"{rel_optim}: unreadable for the fused-update "
                           f"check ({e})"]
    fused = _str_tuple(optim_tree, "FUSED_UPDATE_FNS", rel_optim, problems)
    if fused is None:
        return problems
    fns = {n.name: n for n in ast.walk(optim_tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in fused:
        fn = fns.get(name)
        if fn is None:
            problems.append(
                f"{rel_optim}: FUSED_UPDATE_FNS names {name!r} but no "
                "such function is defined")
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == "np":
                problems.append(
                    f"{rel_optim}:{node.lineno}: {name} references np — "
                    "the fused update traces inside the donated train "
                    "step and must never materialize state on the host")
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _FUSED_HOST_CALLS:
                problems.append(
                    f"{rel_optim}:{node.lineno}: {name} calls "
                    f".{node.func.attr}() — host materialization inside "
                    "the fused optimizer update")
    return problems


def check_profiler_confinement(files=None,
                               profiler_path: str = PROFILER) -> list:
    """The device-truth layer's one-gate invariant, statically
    (check 10): ``jax.profiler`` imports/attribute access and
    ``start_trace``/``stop_trace`` calls are confined to
    telemetry/profiler.py, and that module really defines the gated API
    and touches jax.profiler.  ``files`` given = a negative-case unit
    test on a fragment (the confinement scan only)."""
    problems = []
    full_tree = files is None
    paths = list(_py_files()) if full_tree else list(files)
    for path in paths:
        if os.path.abspath(path) == os.path.abspath(profiler_path):
            continue
        rel = os.path.relpath(path, REPO)
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError) as e:
            problems.append(f"{rel}: unreadable for the profiler-"
                            f"confinement check ({e})")
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax.profiler" \
                            or alias.name.startswith("jax.profiler."):
                        problems.append(
                            f"{rel}:{node.lineno}: imports jax.profiler "
                            "outside telemetry/profiler.py — capture "
                            "windows and device annotations go through "
                            "the gated API (DESIGN.md §11)")
            if isinstance(node, ast.ImportFrom) and node.module:
                if (node.module == "jax"
                        and any(a.name == "profiler"
                                for a in node.names)) \
                        or node.module.startswith("jax.profiler"):
                    problems.append(
                        f"{rel}:{node.lineno}: imports jax's profiler "
                        "outside telemetry/profiler.py — use the gated "
                        "API")
            if isinstance(node, ast.Attribute) \
                    and node.attr == "profiler" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "jax":
                problems.append(
                    f"{rel}:{node.lineno}: touches jax.profiler outside "
                    "telemetry/profiler.py — the device-truth layer is "
                    "the one gate")
            if isinstance(node, ast.Call):
                fn = node.func
                called = (fn.attr if isinstance(fn, ast.Attribute)
                          else fn.id if isinstance(fn, ast.Name) else "")
                if called in _CAPTURE_CALLS:
                    problems.append(
                        f"{rel}:{node.lineno}: calls {called}() outside "
                        "telemetry/profiler.py — every capture window "
                        "goes through the gated API (capture_window/"
                        "start_capture/finish_capture)")
    if not full_tree:
        return problems

    # The gate module itself: the API exists and jax.profiler is really
    # touched (otherwise the confinement above confines nothing).
    rel = os.path.relpath(profiler_path, REPO)
    try:
        with open(profiler_path) as fh:
            gate_tree = ast.parse(fh.read())
    except (OSError, SyntaxError) as e:
        return problems + [f"{rel}: unreadable for the profiler-gate "
                           f"check ({e})"]
    defs = {n.name for n in ast.walk(gate_tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in _PROFILER_GATE_FNS:
        if name not in defs:
            problems.append(
                f"{rel}: gated API function {name} not found — the "
                "capture-window enforcement has nothing to point at")
    touches = any(
        isinstance(n, ast.Import) and any(
            a.name == "jax.profiler" for a in n.names)
        for n in ast.walk(gate_tree))
    if not touches:
        problems.append(
            f"{rel}: never imports jax.profiler — the gate module is "
            "not actually the gate")
    return problems


def _registered_fault_sites(registry_path: str, problems: list):
    """Parse faults/registry.py's ``SITES`` tuple; duplicate names are a
    finding (each site registered EXACTLY once)."""
    rel = os.path.relpath(registry_path, REPO)
    try:
        with open(registry_path) as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError) as e:
        problems.append(f"{rel}: unreadable for the fault-site check ({e})")
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "SITES"
                for t in node.targets):
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                break
            names = []
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                                str):
                    names.append(elt.value)
                else:
                    problems.append(
                        f"{rel}:{elt.lineno}: SITES holds a non-literal "
                        "entry — the registry must be statically "
                        "checkable")
            for name in set(names):
                if names.count(name) > 1:
                    problems.append(
                        f"{rel}: site {name!r} registered more than once "
                        "in SITES — each site is registered exactly once")
            return names
    problems.append(f"{rel}: SITES tuple not found — the fault-site "
                    "registry has nothing to check against")
    return None


def check_fault_sites(files=None,
                      registry_path: str = FAULTS_REGISTRY) -> list:
    """The failure model's closed-registry invariant, statically
    (check 8): every ``faults.site()``/``site()`` call names a
    registered site as a string literal, every registered site is wired
    at ≥1 call site (full-tree mode only — ``files`` given means a
    negative-case unit test on a fragment), and every ``RetryPolicy``
    construction passes ``classify=`` explicitly."""
    problems = []
    registered = _registered_fault_sites(registry_path, problems)
    if registered is None:
        return problems
    full_tree = files is None
    paths = list(_py_files()) if full_tree else list(files)
    wired = set()
    for path in paths:
        if os.path.abspath(path) == os.path.abspath(registry_path):
            continue  # the definition site, not a call site
        rel = os.path.relpath(path, REPO)
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError) as e:
            problems.append(f"{rel}: unreadable for the fault-site "
                            f"check ({e})")
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_site = (
                (isinstance(fn, ast.Attribute) and fn.attr == "site"
                 and isinstance(fn.value, ast.Name)
                 and fn.value.id == "faults")
                or (isinstance(fn, ast.Name) and fn.id == "site"))
            is_retry = ((isinstance(fn, ast.Attribute)
                         and fn.attr == "RetryPolicy")
                        or (isinstance(fn, ast.Name)
                            and fn.id == "RetryPolicy"))
            if is_site:
                arg = node.args[0] if node.args else None
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    problems.append(
                        f"{rel}:{node.lineno}: faults.site() with a "
                        "non-literal site name — the closed registry "
                        "cannot be checked")
                elif arg.value not in registered:
                    problems.append(
                        f"{rel}:{node.lineno}: faults.site({arg.value!r}) "
                        "names an unregistered site (registry: "
                        "faults/registry.py SITES)")
                else:
                    wired.add(arg.value)
            if is_retry and not any(kw.arg == "classify"
                                    for kw in node.keywords):
                problems.append(
                    f"{rel}:{node.lineno}: RetryPolicy(...) without an "
                    "explicit classify= — every retry call site states "
                    "its transient-vs-fatal rule (no bare retries)")
    if full_tree:
        for name in registered:
            if name not in wired:
                problems.append(
                    f"faults/registry.py: site {name!r} is registered "
                    "but wired at no call site — chaos coverage for it "
                    "is vacuous")
    return problems


def check_resident_feed(trainer_path: str = TRAINER) -> list:
    """The zero-host-copy invariant, statically: the trainer functions in
    RESIDENT_FEED_FNS may look up the shared device cache and do index
    math, but any ``np.`` reference or host-materializing call
    (``.gather``/``.asarray``/``.concatenate``/...) inside them means an
    image array crossed back to the host on the resident feed path."""
    problems = []
    rel = os.path.relpath(trainer_path, REPO)
    try:
        with open(trainer_path) as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError) as e:
        return [f"{rel}: unreadable for the resident-feed check ({e})"]
    fns = {node.name: node for node in ast.walk(tree)
           if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in RESIDENT_FEED_FNS:
        fn = fns.get(name)
        if fn is None:
            problems.append(
                f"{rel}: resident-feed function {name} not found — the "
                "zero-host-copy enforcement has nothing to check")
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == "np":
                problems.append(
                    f"{rel}:{node.lineno}: {name} references np — the "
                    "resident train feed must never materialize image "
                    "arrays on the host")
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _HOST_COPY_CALLS:
                problems.append(
                    f"{rel}:{node.lineno}: {name} calls "
                    f".{node.func.attr}() — host materialization on the "
                    "resident train feed path")
    return problems


def check_sharded_selection(kcenter_path: str = KCENTER) -> list:
    """The sharded pool's scale-out invariant, statically (check 6): the
    row-sharded selection backend may move O(N) vectors and O(q) rows,
    but a ``jax.device_get``/``np.asarray`` of the pool, an ``np.``
    reference in the device tier, or a ``replicate``/
    ``replicated_sharding`` call means the [N, D] factor matrix came
    back whole onto one host or chip — the exact ceiling the backend
    exists to break."""
    problems = []
    rel = os.path.relpath(kcenter_path, REPO)
    try:
        with open(kcenter_path) as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError) as e:
        return [f"{rel}: unreadable for the sharded-selection check ({e})"]
    fns = {node.name: node for node in ast.walk(tree)
           if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def call_name(node) -> str:
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                return node.func.attr
            if isinstance(node.func, ast.Name):
                return node.func.id
        return ""

    for name in SHARDED_DEVICE_FNS + SHARDED_ORCHESTRATOR_FNS:
        fn = fns.get(name)
        if fn is None:
            problems.append(
                f"{rel}: sharded-selection function {name} not found — "
                "the scale-out enforcement has nothing to check")
            continue
        device_tier = name in SHARDED_DEVICE_FNS
        for node in ast.walk(fn):
            if device_tier and isinstance(node, ast.Name) \
                    and node.id == "np":
                problems.append(
                    f"{rel}:{node.lineno}: {name} references np — the "
                    "sharded selection backend must never materialize "
                    "pool state on the host")
            called = call_name(node)
            if device_tier and called in _SHARDED_HOST_CALLS:
                problems.append(
                    f"{rel}:{node.lineno}: {name} calls .{called}() — "
                    "host materialization inside the sharded selection "
                    "backend")
            if not device_tier and called == "device_get":
                problems.append(
                    f"{rel}:{node.lineno}: {name} calls device_get — "
                    "the sharded pool must never round-trip to host")
            if called in _SHARDED_REPLICATE_CALLS:
                problems.append(
                    f"{rel}:{node.lineno}: {name} calls {called}() — "
                    "replicating a row-sharded array rebuilds the "
                    "single-chip ceiling the sharded pool removes")
    return problems


def check_pipeline_coordinator(pipeline_path: str = PIPELINE) -> list:
    """The pipelined round's overlap invariant, statically (check 7):
    the speculative-scoring coordinator functions may enqueue device
    work and wait on host-side conditions, but a ``block_until_ready``
    or ``device_get`` call inside them would sync the train stream's
    arrays — serializing the two streams the pipeline exists to
    overlap.  Chunk-output fetches live inside collect_pool (scoring
    tier), and the CPU-only execution drain lives in
    mesh_lib.DispatchGate; neither is a coordinator function."""
    problems = []
    rel = os.path.relpath(pipeline_path, REPO)
    try:
        with open(pipeline_path) as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError) as e:
        return [f"{rel}: unreadable for the pipeline-coordinator "
                f"check ({e})"]
    fns = {node.name: node for node in ast.walk(tree)
           if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in PIPELINE_COORDINATOR_FNS:
        fn = fns.get(name)
        if fn is None:
            problems.append(
                f"{rel}: pipeline coordinator function {name} not found "
                "— the never-sync enforcement has nothing to check")
            continue
        for node in ast.walk(fn):
            called = ""
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute):
                    called = node.func.attr
                elif isinstance(node.func, ast.Name):
                    called = node.func.id
            if called in _PIPELINE_SYNC_CALLS:
                problems.append(
                    f"{rel}:{node.lineno}: {name} calls {called} — the "
                    "speculative-scoring coordinator must never sync "
                    "the train stream (DESIGN.md §8)")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"trace_lint: {p}", file=sys.stderr)
    if problems:
        return 1
    print("trace_lint: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
