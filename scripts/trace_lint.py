#!/usr/bin/env python
"""trace_lint: compatibility shim over the analysis engine.

The 10 checks this script used to implement as a 773-line monolith now
live in ``active_learning_tpu/analysis/checks/legacy.py``, ported
verbatim onto the shared-parse engine (DESIGN.md §12) — same verdicts,
same messages, one ``ast.parse`` per file instead of one per check.
This shim keeps the historical import surface alive so every existing
entry point (tests/test_telemetry.py's fragment tests, the tier-1
subprocess run, monkeypatched ``_py_files``) works unchanged:

  1  phase_timer derives its seconds from ONE tracer span
  2  nobody else defines a phase_timer
  3  call sites import phase_timer from utils.tracing
  4  jax.profiler.TraceAnnotation stays behind tracing.annotate
  5  the resident train feed never materializes images on host
  6  the row-sharded selection backend never un-shards the pool
  7  the speculative-scoring coordinator never syncs the train stream
  8  the fault-site registry is closed, wired, and classify='d
  9  custom VJPs are registered in ops/backward.py and parity-tested
  10 jax.profiler stays confined to telemetry/profiler.py

The four NEW checkers (lock-discipline, donation-safety,
recompile-hazard, collective-axis) are deliberately NOT run here — this
shim's contract is "identical verdicts to the legacy monolith";
``scripts/al_lint.py`` is the full 18-check CLI.

Stdlib + the (jax-free) analysis package only; exits 0 clean / 1 with
findings on stderr.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from active_learning_tpu.analysis.checks import legacy as _legacy  # noqa: E402
from active_learning_tpu.analysis.engine import AstCache  # noqa: E402

PKG = os.path.join(REPO, "active_learning_tpu")

# Historical constants, re-exported for callers that introspect them
# (tests assert the FN tuples stay in lockstep with the modules).
TRACING = _legacy.TRACING
PROFILER = _legacy.PROFILER
ANNOTATION_WHITELIST = _legacy.ANNOTATION_WHITELIST
TRAINER = _legacy.TRAINER
RESIDENT_FEED_FNS = _legacy.RESIDENT_FEED_FNS
KCENTER = _legacy.KCENTER
SHARDED_DEVICE_FNS = _legacy.SHARDED_DEVICE_FNS
SHARDED_ORCHESTRATOR_FNS = _legacy.SHARDED_ORCHESTRATOR_FNS
PIPELINE = _legacy.PIPELINE
PIPELINE_COORDINATOR_FNS = _legacy.PIPELINE_COORDINATOR_FNS
FAULTS_REGISTRY = _legacy.FAULTS_REGISTRY
OPS_BACKWARD = _legacy.OPS_BACKWARD
OPTIM = _legacy.OPTIM
BACKWARD_TESTS = _legacy.BACKWARD_TESTS


def _py_files():
    """The package walk (monkeypatched by tests to point the whole lint
    at fixture fragments — every package-wide check below resolves its
    file set through THIS module-level function)."""
    from active_learning_tpu.analysis.engine import default_files
    return default_files(REPO)


def _render(findings) -> list:
    return [f.render() for f in findings]


def check() -> list:
    """All 10 legacy checks over the tree, one shared parse per file —
    identical verdicts to the monolithic implementation."""
    cache = AstCache()
    files = list(_py_files())
    problems = []
    problems += _legacy.check_phase_timer_span(cache=cache)
    problems += _legacy.check_phase_timer_fork(files=files, cache=cache)
    problems += _legacy.check_phase_timer_import(files=files, cache=cache)
    problems += _legacy.check_trace_annotation(files=files, cache=cache)
    problems += _legacy.check_resident_feed(cache=cache)
    problems += _legacy.check_sharded_selection(cache=cache)
    problems += _legacy.check_pipeline_coordinator(cache=cache)
    problems += _legacy.check_fault_sites(files=files, cache=cache,
                                          full_tree=True)
    problems += _legacy.check_backward_registry(files=files, cache=cache,
                                                full_tree=True)
    problems += _legacy.check_profiler_confinement(files=files,
                                                   cache=cache,
                                                   full_tree=True)
    return _render(problems)


def check_resident_feed(trainer_path: str = None) -> list:
    return _render(_legacy.check_resident_feed(
        trainer_path if trainer_path is not None else TRAINER))


def check_sharded_selection(kcenter_path: str = None) -> list:
    return _render(_legacy.check_sharded_selection(
        kcenter_path if kcenter_path is not None else KCENTER))


def check_pipeline_coordinator(pipeline_path: str = None) -> list:
    return _render(_legacy.check_pipeline_coordinator(
        pipeline_path if pipeline_path is not None else PIPELINE))


def check_fault_sites(files=None, registry_path: str = None) -> list:
    full_tree = files is None
    return _render(_legacy.check_fault_sites(
        files=files if files is not None else list(_py_files()),
        registry_path=(registry_path if registry_path is not None
                       else FAULTS_REGISTRY),
        full_tree=full_tree))


def check_backward_registry(files=None, ops_path: str = None,
                            optim_path: str = None,
                            tests_path: str = None) -> list:
    full_tree = files is None
    return _render(_legacy.check_backward_registry(
        files=files if files is not None else list(_py_files()),
        ops_path=ops_path if ops_path is not None else OPS_BACKWARD,
        optim_path=optim_path if optim_path is not None else OPTIM,
        tests_path=tests_path if tests_path is not None else BACKWARD_TESTS,
        full_tree=full_tree))


def check_profiler_confinement(files=None, profiler_path: str = None
                               ) -> list:
    full_tree = files is None
    return _render(_legacy.check_profiler_confinement(
        files=files if files is not None else list(_py_files()),
        profiler_path=(profiler_path if profiler_path is not None
                       else PROFILER),
        full_tree=full_tree))


def _registered_fault_sites(registry_path: str, problems: list):
    """Legacy helper: parse the SITES tuple, appending rendered problem
    strings into the caller's list."""
    inner = []
    names = _legacy.registered_fault_sites(registry_path, inner)
    problems.extend(_render(inner))
    return names


def main() -> int:
    problems = check()
    for p in problems:
        print(f"trace_lint: {p}", file=sys.stderr)
    if problems:
        return 1
    print("trace_lint: ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
