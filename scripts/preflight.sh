#!/usr/bin/env bash
# preflight.sh — the merge gate, reproduced locally with one command.
#
#   bash scripts/preflight.sh
#
# Chains the six gates a change must clear, fail-fast, in cost order:
#
#   1. al_lint         the 18-check static analysis (seconds, no jax)
#   2. tier-1 tests    the ROADMAP.md tier-1 recipe (CPU 8-device mesh)
#   3. bench smoke     the degraded-mode contract: bench.py with the
#                      wall-clock budget pre-exhausted and a redirected
#                      state dir must still emit its strict-parseable
#                      final JSON line (the driver-parseable guarantee)
#   4. stream smoke    the streaming loop end to end: a real
#                      StreamService on loopback ingests synthetic rows
#                      over HTTP, the watermark trigger fires, a full
#                      AL round completes over the grown pool (the
#                      bench stream_round phase in smoke mode)
#   5. run_report      scripts/run_report.py --selftest (the reporting
#                      layer renders synthetic runs end to end)
#   6. fleet smoke     the fleet controller end to end: a 2-worker
#                      localhost fleet runs a 2-run sweep, one child is
#                      SIGKILL'd after its round-0 checkpoint, the
#                      controller reschedules it with --resume_training
#                      and both runs finish (the bench fleet_smoke
#                      phase)
#
# Exit codes: 0 = every gate green; otherwise the exit code of the
# FIRST failing gate (1 = lint findings or test/selftest failures,
# 2 = usage/collection errors, >=124 = a timeout) — `set -e` stops at
# the first red, so the last line printed names the failing gate.
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

echo "== preflight 1/6: al_lint (static analysis) =="
python scripts/al_lint.py

echo "== preflight 2/6: tier-1 tests =="
# The tier-1 recipe (ROADMAP.md): CPU backend, virtual 8-device mesh
# via tests/conftest.py, slow tier excluded.
set -o pipefail
rm -f /tmp/_preflight_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_preflight_t1.log

echo "== preflight 3/6: bench degraded-mode smoke =="
# Budget pre-exhausted + redirected state dir (the repo's captured
# evidence must never be clobbered): the final stdout line must still
# be strict JSON with the headline schema — the same contract
# tests/test_bench_json.py pins, checked here without pytest.
BENCH_STATE="$(mktemp -d)"
trap 'rm -rf "$BENCH_STATE"' EXIT
env -u XLA_FLAGS JAX_PLATFORMS=cpu AL_BENCH_STATE_DIR="$BENCH_STATE" \
    AL_BENCH_BUDGET_S=0 python bench.py > "$BENCH_STATE/out.txt"
python - "$BENCH_STATE/out.txt" <<'EOF'
import json, sys
lines = [l for l in open(sys.argv[1]).read().splitlines() if l.strip()]
assert lines, "bench printed nothing to stdout"
out = json.loads(lines[-1])  # strict: NaN/Inf tokens would raise
for key in ("metric", "value", "unit", "phases", "evidence"):
    assert key in out, f"bench line missing {key!r}"
print("bench degraded-mode line: ok")
EOF

echo "== preflight 4/6: stream_round smoke (ingest -> trigger -> round) =="
# The streaming loop's end-to-end gate: the bench child in smoke mode
# must ingest rows over HTTP, fire the watermark trigger, and complete
# a full AL round — its JSON line is checked for the trigger evidence.
timeout -k 10 420 env -u XLA_FLAGS JAX_PLATFORMS=cpu \
    AL_BENCH_STREAM_SMOKE=1 python bench.py --phase stream_round \
    --iters 2 --per-chip-batch 32 > "$BENCH_STATE/stream.txt"
python - "$BENCH_STATE/stream.txt" <<'EOF'
import json, sys
lines = [l for l in open(sys.argv[1]).read().splitlines() if l.strip()]
assert lines, "stream_round printed nothing to stdout"
out = json.loads(lines[-1])
assert out.get("phase") == "stream_round", out
assert out.get("rounds_run", 0) >= 2, f"no triggered round: {out}"
assert out.get("trigger_cause") == "watermark", out
assert out.get("ips"), "no ingest rate recorded"
print("stream_round smoke: ok "
      f"({out['ips']} rows/s acked, ack p99 {out.get('ack_p99_ms')} ms)")
EOF

echo "== preflight 5/6: run_report selftest =="
python scripts/run_report.py --selftest

echo "== preflight 6/6: fleet smoke (2-worker controller, kill -> resume) =="
# The fleet layer's end-to-end gate: the bench fleet_smoke phase runs
# a 2-run sweep on two localhost workers, SIGKILLs one child after its
# round-0 checkpoint, and the controller must reschedule it with
# --resume_training and finish everything — the JSON line is checked
# for the resume evidence.
timeout -k 10 900 env -u XLA_FLAGS JAX_PLATFORMS=cpu \
    python bench.py --phase fleet_smoke \
    --iters 2 --per-chip-batch 32 > "$BENCH_STATE/fleet.txt"
python - "$BENCH_STATE/fleet.txt" <<'EOF2'
import json, sys
lines = [l for l in open(sys.argv[1]).read().splitlines() if l.strip()]
assert lines, "fleet_smoke printed nothing to stdout"
out = json.loads(lines[-1])
assert out.get("phase") == "fleet_smoke", out
assert out.get("runs_finished") == 2, f"fleet did not finish: {out}"
assert out.get("runs_failed") == 0, out
assert out.get("runs_resumed", 0) >= 1, f"no resume exercised: {out}"
assert out.get("comparison_rendered") is True, out
print("fleet smoke: ok "
      f"({out['runs_finished']} runs finished, "
      f"{out['runs_resumed']} resumed after the kill, "
      f"{out['total_sec']} s wall)")
EOF2

echo "preflight: ALL GATES GREEN"
