#!/usr/bin/env python
"""run_report: render per-run label-efficiency reports and cross-run
strategy comparisons at matched label budgets (DESIGN.md §13).

    python scripts/run_report.py <log_dir>              # one run's curve
    python scripts/run_report.py <dir_a> <dir_b> ...    # comparison table
    python scripts/run_report.py --selftest             # preflight link
    python scripts/run_report.py <dir> --json           # machine-readable

Thin CLI over active_learning_tpu/telemetry/report.py (the ``report``
verb of the main CLI), kept as a script so the preflight gate and ops
shells can run it with no package install.  Stdlib only, no jax import
— safe against a wedged or backend-less tree.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from active_learning_tpu.telemetry.report import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
