"""CIFAR-10 protocol evidence run (VERDICT r4 Missing #1/#4).

One command reproduces the reference's shortened CIFAR-10 protocol —
5 rounds x 1,000 budget, MarginSampler vs RandomSampler, seeds 98/99 —
through the PRODUCTION path end to end: fetch -> md5 -> extract ->
python-batch load -> driver round loop (reference gen_jobs.py:89-112,
main_al.py:145-184).

On a networked machine this uses the REAL cifar-10-python.tar.gz (the
fetch is attempted first, md5-verified).  In the zero-egress sandbox the
fetch fails fast and the run falls back to a byte-layout-faithful
facsimile archive (active_learning_tpu/data/facsimile.py) served over
file:// — every line of the real-data path still executes; only the
pixel content differs, and the output records which source was used.

    python scripts/cifar10_evidence.py [--model SSLResNet18] \
        [--rounds 5] [--budget 1000] [--epochs 8] [--out EVIDENCE_cifar10.json]

``--imbalanced`` switches to the reference's imbalanced-CIFAR protocol
(exp imbalance 0.1, class-weighted loss, reference gen_jobs.py:99-100):
class-aware samplers (Balancing/BASE) vs random — the setting where
strategy separation is expected even on template data, because the
*pool composition* (not per-example noise) is what the strategies
exploit.  ``--seeds N`` runs N independent replicas per strategy.

The default model is SSLResNet18 when an accelerator backend is present,
else a linear probe sized for the single-CPU sandbox (recorded in the
output; pass --model to override).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def provision(workdir: str, model_name: str = "probe") -> dict:
    """Real fetch first; facsimile fallback.  Returns provenance info."""
    from active_learning_tpu.data import cifar10 as c10

    data_dir = os.path.join(workdir, "data")
    try:
        c10.fetch_cifar10(data_dir, timeout=30.0)
        return {"source": "real", "url": c10.CIFAR10_URL,
                "md5": c10.CIFAR10_TGZ_MD5}
    except OSError as e:
        fetch_err = repr(e)
    from active_learning_tpu.data.facsimile import write_cifar10_facsimile
    # Difficulty defaults are model-dependent, each calibrated ON ITS OWN
    # MODEL so the learning curve is informative (rises without pinning
    # at chance or saturating round 0): the linear probe at 0.06/60
    # (sklearn ceiling ~45-50% at 1k labels), the from-scratch ResNet at
    # 0.10/60 (TPU-calibrated: round0 67%, rising — 0.08/65 left
    # training bistable, 0.25+/50 saturated to ~100% immediately).
    default_contrast = "0.06" if model_name == "probe" else "0.10"
    noise = float(os.environ.get("AL_EVIDENCE_NOISE", "60"))
    contrast = float(os.environ.get("AL_EVIDENCE_CONTRAST",
                                    default_contrast))
    path, md5 = write_cifar10_facsimile(
        os.path.join(workdir, "cifar-10-python.tar.gz"),
        noise_sigma=noise, contrast=contrast)
    c10.fetch_cifar10(data_dir, url=f"file://{path}", expected_md5=md5)
    return {"source": "facsimile", "fetch_error": fetch_err,
            "facsimile_md5": md5, "noise_sigma": noise,
            "contrast": contrast,
            "note": "zero-egress environment; byte-layout-faithful "
                    "archive with synthetic template images — the full "
                    "real-data code path ran, only pixels differ. "
                    "Difficulty calibrated so accuracy is sample-limited "
                    "(~40% at 1k labels), making the learning curve "
                    "informative."}


def make_probe():
    import flax.linen as nn
    import jax.numpy as jnp

    class LinearProbe(nn.Module):
        """Single-CPU stand-in with the SSLClassifier interface: a PURE
        linear softmax on flattened pixels — SGD on it is logistic
        regression, the model the facsimile difficulty was calibrated
        with."""

        num_classes: int = 10
        freeze_feature: bool = False

        @nn.compact
        def __call__(self, x, train: bool = True,
                     return_features: bool = False):
            emb = x.reshape((x.shape[0], -1)).astype(jnp.float32)
            logits = nn.Dense(self.num_classes, name="linear")(emb)
            return (logits, emb) if return_features else logits

    return LinearProbe()


# Collapse detection (VERDICT r5 #3): from-scratch re-init training at
# small label counts is bistable — a round can sit at chance for its
# whole fit while an identical re-init escapes (the r5 TPU capture shows
# seed 1 / Margin / round 1 at 9.71%).  A headline separation curve must
# never ride through such a dead round, so each round's fit is guarded:
# if the fit's BEST validation accuracy (i.e. including every
# post-warmup epoch — a healthy run is well past chance by then) is
# still at chance, log it, re-initialize the network, and retrain,
# bounded at MAX_COLLAPSE_RETRIES.  Retries are recorded per round in
# the evidence JSON so a recovered round is visible, not silent.
MAX_COLLAPSE_RETRIES = 2
# "At chance" with margin: best validation accuracy <= 1.25x the uniform
# rate.  A training run that learned ANYTHING clears this by the first
# post-warmup epoch; 9.71% on CIFAR-10 (chance 10%) sits inside it.
COLLAPSE_CHANCE_FACTOR = 1.25


def _collapse_guarded(name: str):
    """Register (once) and return a subclass of strategy ``name`` whose
    train() re-inits and retrains collapsed rounds."""
    from active_learning_tpu.registry import STRATEGIES
    from active_learning_tpu.strategies import get_strategy
    from active_learning_tpu.strategies.base import register_strategy

    guarded_name = name + "CollapseGuard"
    if guarded_name in STRATEGIES:
        return guarded_name
    base = get_strategy(name)

    @register_strategy(guarded_name)
    class CollapseGuard(base):
        def _round_perf(self) -> float:
            """The fit's best validation accuracy when the fit actually
            validated; otherwise (the protocol's early_stop_patience=0
            DISABLES per-epoch validation — trainer.fit's use_es gate —
            leaving best_perf at 0.0) an explicit final-weights pass
            over the eval split.  Without this fallback the guard would
            read every es=0 round as collapsed and re-train the whole
            protocol 3x."""
            if self.cfg.early_stop_patience > 0 and self.best_perf > 0:
                return float(self.best_perf)
            if len(self.pool.eval_idxs) == 0:
                return 1.0  # nothing to measure against; never retry
            perf = self.trainer.evaluate(self.state, self.al_set,
                                         self.pool.eval_idxs)
            return float(perf["accuracy"])

        def train(self):
            chance = 1.0 / self.num_classes
            retries = 0
            while True:
                super().train()
                self.best_perf = self._round_perf()
                if (self.best_perf > chance * COLLAPSE_CHANCE_FACTOR
                        or retries >= MAX_COLLAPSE_RETRIES):
                    break
                retries += 1
                self.logger.warning(
                    f"round {self.round}: best validation accuracy "
                    f"{self.best_perf:.4f} is at chance "
                    f"({chance:.2f}) — collapsed fit; re-initializing "
                    f"and retraining (retry {retries}/"
                    f"{MAX_COLLAPSE_RETRIES})")
                self.init_network_weights()
            if not hasattr(self, "collapse_retries"):
                self.collapse_retries = {}
            if retries:
                self.collapse_retries[int(self.round)] = retries
                if self.best_perf <= chance * COLLAPSE_CHANCE_FACTOR:
                    self.logger.warning(
                        f"round {self.round}: still at chance after "
                        f"{retries} retries — recorded, giving up")

    return guarded_name


def run_strategy(name: str, data, model_name: str, args, workdir: str,
                 run_seed: int = 0, imbalance=None) -> dict:
    import dataclasses

    import jax

    from active_learning_tpu.config import (ExperimentConfig,
                                            ImbalanceConfig)
    from active_learning_tpu.experiment.arg_pools import get_train_config
    from active_learning_tpu.experiment.driver import run_experiment
    from active_learning_tpu.utils.metrics import NullSink

    class CurveSink(NullSink):
        experiment_key = f"evidence_{name}"

        def __init__(self):
            self.curve = {}

        def log_metrics(self, metrics, step=None):
            for k, v in metrics.items():
                if k == "rd_test_accuracy":
                    self.curve[int(step)] = round(float(v), 4)

    dataset = "imbalanced_cifar10" if imbalance else "cifar10"
    tmp = os.path.join(workdir, f"exp_{name}_s{run_seed}")
    cfg = ExperimentConfig(
        dataset=dataset, dataset_dir=os.path.join(workdir, "data"),
        strategy=_collapse_guarded(name), rounds=args.rounds,
        round_budget=args.budget,
        init_pool_size=args.budget, model=model_name, n_epoch=args.epochs,
        early_stop_patience=0, exp_hash=f"evidence_{name}_s{run_seed}",
        run_seed=run_seed,
        imbalance=imbalance or ImbalanceConfig(),
        log_dir=os.path.join(tmp, "logs"),
        ckpt_path=os.path.join(tmp, "ckpt"))
    # The registered default pool for the dataset: its imbalanced entry
    # already carries the reference's class-weighted loss
    # (strategy.py:444-457) — no local re-derivation.
    train_cfg = get_train_config("default", dataset)
    model = None
    if model_name != "probe" and args.epochs < 100:
        # Shortened protocol: the pool's StepLR(160) never decays inside
        # a short run, leaving lr at 0.1 for every step — from-scratch
        # ResNet-18 then sits at chance for the few epochs it gets
        # (observed on the TPU capture at 8 epochs).  Cosine over exactly
        # the run's epochs is the standard shortened-schedule adaptation,
        # and the peak lr drops to 0.05 (AL_EVIDENCE_LR to override): the
        # reference's 0.1 is tuned for 50k-image epochs, and at 1-2k
        # labels it leaves from-scratch training bistable — observed on
        # TPU as runs that sit at chance while an identical seed escapes
        # to 52%.  The full 200-epoch reference protocol (epochs >= 100)
        # keeps the reference's StepLR and lr untouched.
        from active_learning_tpu.config import SchedulerConfig
        lr = float(os.environ.get("AL_EVIDENCE_LR", "0.05"))
        train_cfg = dataclasses.replace(
            train_cfg,
            optimizer=dataclasses.replace(train_cfg.optimizer, lr=lr),
            scheduler=SchedulerConfig(
                name="cosine", t_max=args.epochs,
                # Clamped so a smoke-length run still reaches peak lr and
                # executes a cosine phase (3 warmup epochs in a 2-epoch
                # run would never leave the ramp).  No max(1, ...) floor:
                # a 1-epoch smoke run must fall back to plain cosine
                # (warmup 0) — warmup_epochs == t_max == 1 makes
                # _cosine_lr raise at trainer build.
                warmup_epochs=min(3, args.epochs // 2)))
    if model_name == "probe":
        # Calibrated for the pure-linear probe (matches the sklearn
        # logistic-regression settings the facsimile difficulty was
        # tuned with): gentler lr than the ResNet arg pool + weight
        # decay + cosine over exactly the run's epochs.  Pinned by
        # tests/test_cifar10_protocol.py.
        from active_learning_tpu.config import (OptimizerConfig,
                                                SchedulerConfig)
        train_cfg = dataclasses.replace(
            train_cfg,
            optimizer=OptimizerConfig(name="sgd", lr=0.05, momentum=0.9,
                                      weight_decay=1e-4),
            scheduler=SchedulerConfig(name="cosine", t_max=args.epochs))
        model = make_probe()
    sink = CurveSink()
    t0 = time.perf_counter()
    strategy = run_experiment(cfg, sink=sink, data=data,
                              train_cfg=train_cfg, model=model)
    # {round: retry count} for rounds that collapsed and were re-run
    # (empty = no dead rounds): the curve's provenance, in the JSON.
    retries = {str(k): v for k, v in
               getattr(strategy, "collapse_retries", {}).items()}
    return {"strategy": name, "model": model_name, "run_seed": run_seed,
            "test_accuracy_by_round": sink.curve,
            "collapse_retries": retries,
            "collapse_guard": {"max_retries": MAX_COLLAPSE_RETRIES,
                               "chance_factor": COLLAPSE_CHANCE_FACTOR},
            "wall_sec": round(time.perf_counter() - t0, 1),
            "n_devices": len(jax.devices())}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None,
                    help="SSLResNet18 | probe (default by backend)")
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--budget", type=int, default=1000)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--out", default=None)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--imbalanced", action="store_true",
                    help="the reference's imbalanced-CIFAR protocol "
                         "(exp imbalance 0.1, class-weighted loss, "
                         "class-aware samplers vs random) — the setting "
                         "where strategy separation is expected")
    ap.add_argument("--seeds", type=int, default=1,
                    help="independent run_seed replicas per strategy")
    args = ap.parse_args()
    if args.out is None:
        args.out = os.path.join(
            REPO, "EVIDENCE_cifar10_imbalanced.json" if args.imbalanced
            else "EVIDENCE_cifar10.json")

    import jax

    platform = jax.devices()[0].platform
    model_name = args.model or ("SSLResNet18" if platform != "cpu"
                                else "probe")
    workdir = args.workdir or tempfile.mkdtemp(prefix="cifar10_evidence_")
    provenance = provision(workdir, model_name)
    print(f"data source: {provenance['source']} ({platform}, "
          f"model {model_name})", flush=True)

    from active_learning_tpu.config import ImbalanceConfig
    from active_learning_tpu.data import get_data

    imbalance = None
    if args.imbalanced:
        # ONE protocol constant, shared by the data build and every
        # run's recorded ExperimentConfig — a drift between the two
        # would make resume metadata disagree with the loaded pool.
        imbalance = ImbalanceConfig(imbalance_type="exp",
                                    imbalance_factor=0.1, imbalance_seed=0)
        data = get_data("imbalanced_cifar10",
                        data_path=os.path.join(workdir, "data"),
                        imbalance_args=imbalance)
        strategies = ("BalancingSampler", "BASESampler", "RandomSampler")
        protocol_ref = ("gen_jobs.py:99-100 imbalanced sweep (shortened); "
                        "exp imbalance 0.1, class-weighted loss")
    else:
        data = get_data("cifar10", data_path=os.path.join(workdir, "data"))
        strategies = ("MarginSampler", "RandomSampler")
        protocol_ref = "gen_jobs.py:89-112 (shortened)"

    out = {
        "protocol": {"rounds": args.rounds, "round_budget": args.budget,
                     "init_pool_size": args.budget, "n_epoch": args.epochs,
                     "imbalanced": args.imbalanced, "seeds": args.seeds,
                     "reference": protocol_ref,
                     # Mirrors run_strategy's actual branch choice: the
                     # probe branch ALWAYS installs its own cosine; the
                     # CNN path adapts only shortened (<100-epoch) runs.
                     "schedule": (
                         "probe branch: cosine over the run's epochs, "
                         "lr 0.05, no warmup" if model_name == "probe"
                         else "reference StepLR" if args.epochs >= 100
                         else "shortened-protocol adaptation: cosine over "
                              "the run's epochs, <=3-epoch warmup, lr "
                              + os.environ.get("AL_EVIDENCE_LR", "0.05"))},
        "data": provenance,
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "runs": [],
    }
    for seed in range(args.seeds):
        for strategy in strategies:
            print(f"running {strategy} (seed {seed}) ...", flush=True)
            out["runs"].append(run_strategy(strategy, data, model_name,
                                            args, workdir, run_seed=seed,
                                            imbalance=imbalance))
            with open(args.out, "w") as fh:
                json.dump(out, fh, indent=1)
    print(json.dumps({f"{r['strategy']}_s{r['run_seed']}":
                      r["test_accuracy_by_round"] for r in out["runs"]}))
    print(f"evidence written to {args.out}")


if __name__ == "__main__":
    main()
