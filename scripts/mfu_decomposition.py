"""Measurement-backed decomposition of the ResNet-50 train-step MFU gap.

VERDICT r4 #5 asks for train MFU >= 0.45 *or a profile-backed written
explanation of the ceiling*.  The tunneled backend cannot serve
tensorboard traces, so this script decomposes the gap by measurement
instead: it times, on the SAME live chip with the SAME timing discipline
as bench.py (untimed warmup, data-dependent host fetch),

  1. the full production train step (fwd + loss + bwd + SGD, BN
     batch-stats mutation) — the number behind bench.py's mfu;
  2. the same step with train_bn=False (BN in inference mode:
     identical matmul/conv work minus the batch-stat reductions and
     their layer-serialized dependency chain);
  3. the forward pass alone under training BN semantics;
  4. the scoring forward (eval BN) — bench.py's resnet50_imagenet_score;
  5. the two measured-ceiling responses, decomposed the same way:
     fused bf16 BN statistics alone (train_full_bf16stats — the −23%
     BN-stats cost reclaimed without touching the stem), the
     space-to-depth stem alone (score_fwd_s2d), and the production
     combination (train_full_s2d_bf16stats — bench.py's new
     resnet50_imagenet_train configuration).

Each timing is converted to achieved TFLOP/s with the phase's own
XLA-reported flop count (cost_analysis via CPU lowering, the same
source bench.py uses), so the deltas attribute the MFU gap to (a) the
backward pass's lower-occupancy conv gradients and (b) BN's cross-layer
reduction serialization.  Writes one JSON evidence file.

Run on the live chip:  python scripts/mfu_decomposition.py --out FILE
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _time_loop(step_once, sync, iters: int, warmup: int = 3) -> float:
    for _ in range(warmup):
        step_once()
    sync()
    t0 = time.perf_counter()
    for _ in range(iters):
        step_once()
    sync()
    return time.perf_counter() - t0


def measure(batch_per_chip: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from active_learning_tpu.data.core import IMAGENET_NORM, ViewSpec
    from active_learning_tpu.models.resnet import resnet50
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.strategies import scoring
    from active_learning_tpu.data.augment import apply_view
    from active_learning_tpu.train.trainer import weighted_cross_entropy

    mesh = mesh_lib.make_mesh(-1)
    n_chips = int(mesh.devices.size)
    batch = batch_per_chip * n_chips
    model = resnet50(num_classes=1000, dtype=jnp.bfloat16)
    # Variant models for the ceiling responses: fused bf16 BN statistics
    # (same topology, different stats path) and the space-to-depth stem
    # (exact conv refactoring — random init is fine for THROUGHPUT; the
    # logits-equivalence question lives in tests/test_s2d_stem.py).
    MODELS = {
        "base": model,
        "bnfused": resnet50(num_classes=1000, dtype=jnp.bfloat16,
                            bn_stats_dtype=jnp.bfloat16),
        "s2d": resnet50(num_classes=1000, dtype=jnp.bfloat16, stem="s2d",
                        bn_stats_dtype=jnp.bfloat16),
    }
    train_view = ViewSpec(IMAGENET_NORM, augment=True, pad=0)
    score_view = ViewSpec(IMAGENET_NORM, augment=False)

    rng = np.random.default_rng(0)
    host = {
        "image": rng.integers(0, 256, (batch, 224, 224, 3), dtype=np.uint8),
        "label": rng.integers(0, 1000, batch).astype(np.int32),
        "mask": np.ones(batch, np.float32),
    }
    sharded = mesh_lib.shard_batch(host, mesh)
    VARS = {}
    for vname, m in MODELS.items():
        v = m.init(jax.random.PRNGKey(0), jnp.asarray(host["image"][:8]),
                   train=False)
        VARS[vname] = mesh_lib.replicate(v, mesh)
    # Same convention as the production optimizer (train/optim.py): the
    # transform returns RAW momentum-traced grads and the step applies
    # ``-lr`` itself — optax.sgd would already negate, and a second
    # negation below would ascend the loss.  Optimizer STATE is built
    # per-variant inside build_train (a shared ResNet-50 momentum tree
    # would pin ~100 MB of HBM across every timed variant).
    tx = optax.trace(decay=0.9)
    cw = jnp.ones(1000, jnp.float32)

    def loss_fn(params, batch_stats, x, labels, weights, train_bn,
                variant):
        m = MODELS[variant]
        v = {"params": params, "batch_stats": batch_stats}
        if train_bn:
            logits, mut = m.apply(v, x, train=True,
                                  mutable=["batch_stats"])
            return (weighted_cross_entropy(logits, labels, weights),
                    mut["batch_stats"])
        logits = m.apply(v, x, train=False)
        return weighted_cross_entropy(logits, labels, weights), batch_stats

    @functools.partial(jax.jit, static_argnames=("train_bn", "variant"),
                       donate_argnums=(0, 1, 2))
    def train_step(params, batch_stats, opt_state, key, batch, train_bn,
                   variant):
        x = apply_view(batch["image"], train_view, key=key, train=True)
        w = cw[batch["label"]] * batch["mask"]
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, x, batch["label"],
                                   w, train_bn, variant)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(
            params, jax.tree.map(lambda u: -0.1 * u, updates))
        return params, new_stats, opt_state, loss

    @functools.partial(jax.jit, static_argnames=("train_bn", "variant"))
    def fwd_step(params, batch_stats, key, batch, carry, train_bn,
                 variant):
        x = apply_view(batch["image"], train_view, key=key, train=True)
        loss, _ = loss_fn(params, batch_stats, x, batch["label"],
                          cw[batch["label"]] * batch["mask"], train_bn,
                          variant)
        return carry + loss

    SCORE_STEPS = {vname: scoring.make_prob_stats_step(m, score_view)
                   for vname, m in MODELS.items()}

    @functools.partial(jax.jit, static_argnames=("variant",))
    def score_chained(variables, batch, carry, variant):
        return carry + SCORE_STEPS[variant](variables, batch)["margin"][0]

    device_kind = jax.devices()[0].device_kind
    out = {"device_kind": device_kind, "n_chips": n_chips,
           "batch_per_chip": batch_per_chip, "iters": iters,
           "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
           "timings": {}}

    def run(name, build):
        step_once, sync = build()
        dt = _time_loop(step_once, sync, iters)
        ips = batch * iters / dt
        out["timings"][name] = {"sec": round(dt, 3),
                                "ips_per_chip": round(ips / n_chips, 1)}
        print(f"[{name}] {ips / n_chips:,.0f} img/s/chip", file=sys.stderr)

    def build_train(train_bn, variant="base"):
        # Fresh device copies: train_step donates its state trees, and
        # both train variants (plus the fwd/score runs) must start from
        # live buffers — donating the shared originals would poison the
        # next build.
        v = VARS[variant]
        h = {"p": jax.tree.map(jnp.copy, v["params"]),
             "bs": jax.tree.map(jnp.copy, v["batch_stats"]),
             "o": mesh_lib.replicate(tx.init(
                 jax.tree.map(np.asarray, v["params"])), mesh),
             "k": jax.random.PRNGKey(1), "loss": None}

        def once():
            h["k"], sub = jax.random.split(h["k"])
            h["p"], h["bs"], h["o"], h["loss"] = train_step(
                h["p"], h["bs"], h["o"], sub, sharded, train_bn=train_bn,
                variant=variant)

        return once, lambda: float(h["loss"])

    def build_fwd(train_bn, variant="base"):
        v = VARS[variant]
        h = {"carry": jnp.float32(0.0), "k": jax.random.PRNGKey(2)}

        def once():
            h["k"], sub = jax.random.split(h["k"])
            h["carry"] = fwd_step(v["params"], v["batch_stats"], sub,
                                  sharded, h["carry"], train_bn=train_bn,
                                  variant=variant)

        return once, lambda: float(h["carry"])

    def build_score(variant="base"):
        sbatch = {"image": sharded["image"], "mask": sharded["mask"]}
        h = {"carry": jnp.float32(0.0)}

        def once():
            h["carry"] = score_chained(VARS[variant], sbatch, h["carry"],
                                       variant=variant)

        return once, lambda: float(h["carry"])

    run("score_fwd_eval_bn", build_score)
    run("fwd_only_train_bn", lambda: build_fwd(True))
    run("fwd_only_frozen_bn", lambda: build_fwd(False))
    run("train_frozen_bn", lambda: build_train(False))
    run("train_full", lambda: build_train(True))
    # The measured-ceiling responses, isolated then combined: bf16 BN
    # statistics reclaim the stats tax with the stem untouched; the s2d
    # stem re-shapes the 7x7/s2 conv for the MXU; the combination is the
    # production bench configuration (bench.py resnet50_imagenet_train).
    run("fwd_only_train_bn_bf16stats", lambda: build_fwd(True, "bnfused"))
    run("train_full_bf16stats", lambda: build_train(True, "bnfused"))
    run("score_fwd_s2d", lambda: build_score("s2d"))
    run("train_full_s2d_bf16stats", lambda: build_train(True, "s2d"))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-per-chip", type=int, default=128)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--out", default=os.path.join(
        REPO, "mfu_decomposition.json"))
    args = ap.parse_args()
    out = measure(args.batch_per_chip, args.iters)
    # Per-image GF from bench.py's device-cost-analysis captures: the
    # train step (fwd+bwd+SGD) and the scoring forward.  The fwd-only
    # variants share the scoring conv/matmul structure plus the loss.
    GF = {"train_full": 23.91, "train_frozen_bn": 23.91,
          "fwd_only_train_bn": 7.97, "fwd_only_frozen_bn": 7.97,
          "score_fwd_eval_bn": 7.97,
          # bf16 BN statistics change the stats path's memory traffic,
          # not its flop count.
          "fwd_only_train_bn_bf16stats": 7.97,
          "train_full_bf16stats": 23.91,
          # The s2d stem's folded 4x4x12 kernel carries 192 taps where
          # the 7x7x3 had 147 (the pad row/col is structural zeros XLA
          # still multiplies): +0.07 GF/img forward, +0.22 on the train
          # step (analytic; MFU over these counts the zero taps as work,
          # so the s2d MFU figures are conservative for useful flops).
          "score_fwd_s2d": 8.04,
          "train_full_s2d_bf16stats": 24.13}
    # Explicit device-kind match: a bare "v5" substring also matches v5p
    # (bf16 peak ~459 TFLOP/s), which would inflate reported MFU ~2.3x.
    # Unknown kinds leave mfu unset rather than guess a peak.
    kind = out["device_kind"].lower()
    peak = 197.0 if ("v5e" in kind or "v5 lite" in kind) else None
    for name, entry in out["timings"].items():
        tf = entry["ips_per_chip"] * GF[name] / 1000.0
        entry["tflops_per_sec_per_chip"] = round(tf, 1)
        if peak:
            entry["mfu"] = round(tf / peak, 3)
    out["gf_per_image_source"] = "bench.py device-cost-analysis (r5)"
    out["gf_note"] = ("train_frozen_bn reuses the full-BN 23.91 GF/img "
                      "(no separate cost-analysis capture); its achieved "
                      "TFLOP/s is therefore a slight overcount")
    with open(args.out, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps({k: v for k, v in out["timings"].items()}))


if __name__ == "__main__":
    main()
