"""Measurement-backed decomposition of the ResNet-50 train-step MFU gap.

VERDICT r4 #5 asks for train MFU >= 0.45 *or a profile-backed written
explanation of the ceiling*.  The tunneled backend cannot serve
tensorboard traces, so this script decomposes the gap by measurement
instead: it times, on the SAME live chip with the SAME timing discipline
as bench.py (untimed warmup, data-dependent host fetch),

  1. the full production train step (fwd + loss + bwd + SGD, BN
     batch-stats mutation) — the number behind bench.py's mfu;
  2. the same step with train_bn=False (BN in inference mode:
     identical matmul/conv work minus the batch-stat reductions and
     their layer-serialized dependency chain);
  3. the forward pass alone under training BN semantics;
  4. the scoring forward (eval BN) — bench.py's resnet50_imagenet_score;
  5. the two measured-ceiling responses, decomposed the same way:
     fused bf16 BN statistics alone (train_full_bf16stats — the −23%
     BN-stats cost reclaimed without touching the stem), the
     space-to-depth stem alone (score_fwd_s2d), and the production
     combination (train_full_s2d_bf16stats — bench.py's new
     resnet50_imagenet_train configuration);
  6. the BACKWARD decomposition (the gradient path, DESIGN.md §4):
     ``bwd_only`` (fwd+bwd, every gradient consumed, no optimizer),
     ``bwd_frozen_bn`` (the same under frozen BN), and
     ``optimizer_update`` (the fused SGD+momentum+wd update alone over
     a ResNet-50 state) — so the decomposition finally NAMES where the
     backward time goes instead of implying it.  The script asserts the
     decomposition is self-consistent (bwd_only + optimizer_update
     within tolerance of train_full) and derives ``bwd_mfu`` (the
     backward pass's isolated MFU) and ``bwd_frac``.

Each timing is converted to achieved TFLOP/s with the phase's own
XLA-reported flop count (cost_analysis via CPU lowering, the same
source bench.py uses), so the deltas attribute the MFU gap to (a) the
backward pass's lower-occupancy conv gradients and (b) BN's cross-layer
reduction serialization.  Writes one JSON evidence file.

Run on the live chip:  python scripts/mfu_decomposition.py --out FILE
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _time_loop(step_once, sync, iters: int, warmup: int = 3) -> float:
    for _ in range(warmup):
        step_once()
    sync()
    t0 = time.perf_counter()
    for _ in range(iters):
        step_once()
    sync()
    return time.perf_counter() - t0


def measure(batch_per_chip: int, iters: int, warmup: int = 3) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from active_learning_tpu.data.core import IMAGENET_NORM, ViewSpec
    from active_learning_tpu.models.resnet import resnet50
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.strategies import scoring
    from active_learning_tpu.data.augment import apply_view
    from active_learning_tpu.train import optim as optim_lib
    from active_learning_tpu.train.trainer import weighted_cross_entropy

    mesh = mesh_lib.make_mesh(-1)
    n_chips = int(mesh.devices.size)
    batch = batch_per_chip * n_chips
    model = resnet50(num_classes=1000, dtype=jnp.bfloat16)
    # Variant models for the ceiling responses: fused bf16 BN statistics
    # (same topology, different stats path) and the space-to-depth stem
    # (exact conv refactoring — random init is fine for THROUGHPUT; the
    # logits-equivalence question lives in tests/test_s2d_stem.py).
    MODELS = {
        "base": model,
        "bnfused": resnet50(num_classes=1000, dtype=jnp.bfloat16,
                            bn_stats_dtype=jnp.bfloat16),
        "s2d": resnet50(num_classes=1000, dtype=jnp.bfloat16, stem="s2d",
                        bn_stats_dtype=jnp.bfloat16),
    }
    train_view = ViewSpec(IMAGENET_NORM, augment=True, pad=0)
    score_view = ViewSpec(IMAGENET_NORM, augment=False)

    rng = np.random.default_rng(0)
    host = {
        "image": rng.integers(0, 256, (batch, 224, 224, 3), dtype=np.uint8),
        "label": rng.integers(0, 1000, batch).astype(np.int32),
        "mask": np.ones(batch, np.float32),
    }
    sharded = mesh_lib.shard_batch(host, mesh)
    VARS = {}
    for vname, m in MODELS.items():
        v = m.init(jax.random.PRNGKey(0), jnp.asarray(host["image"][:8]),
                   train=False)
        VARS[vname] = mesh_lib.replicate(v, mesh)
    # Same convention as the production optimizer (train/optim.py): the
    # transform returns RAW momentum-traced grads and the step applies
    # ``-lr`` itself — optax.sgd would already negate, and a second
    # negation below would ascend the loss.  Optimizer STATE is built
    # per-variant inside build_train (a shared ResNet-50 momentum tree
    # would pin ~100 MB of HBM across every timed variant).
    tx = optax.trace(decay=0.9)
    cw = jnp.ones(1000, jnp.float32)

    def loss_fn(params, batch_stats, x, labels, weights, train_bn,
                variant):
        m = MODELS[variant]
        v = {"params": params, "batch_stats": batch_stats}
        if train_bn:
            logits, mut = m.apply(v, x, train=True,
                                  mutable=["batch_stats"])
            return (weighted_cross_entropy(logits, labels, weights),
                    mut["batch_stats"])
        logits = m.apply(v, x, train=False)
        return weighted_cross_entropy(logits, labels, weights), batch_stats

    @functools.partial(jax.jit, static_argnames=("train_bn", "variant"),
                       donate_argnums=(0, 1, 2))
    def train_step(params, batch_stats, opt_state, key, batch, train_bn,
                   variant):
        x = apply_view(batch["image"], train_view, key=key, train=True)
        w = cw[batch["label"]] * batch["mask"]
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, x, batch["label"],
                                   w, train_bn, variant)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(
            params, jax.tree.map(lambda u: -0.1 * u, updates))
        return params, new_stats, opt_state, loss

    @functools.partial(jax.jit, static_argnames=("train_bn", "variant"))
    def fwd_step(params, batch_stats, key, batch, carry, train_bn,
                 variant):
        x = apply_view(batch["image"], train_view, key=key, train=True)
        loss, _ = loss_fn(params, batch_stats, x, batch["label"],
                          cw[batch["label"]] * batch["mask"], train_bn,
                          variant)
        return carry + loss

    SCORE_STEPS = {vname: scoring.make_prob_stats_step(m, score_view)
                   for vname, m in MODELS.items()}

    @functools.partial(jax.jit, static_argnames=("variant",))
    def score_chained(variables, batch, carry, variant):
        return carry + SCORE_STEPS[variant](variables, batch)["margin"][0]

    device_kind = jax.devices()[0].device_kind
    out = {"device_kind": device_kind, "n_chips": n_chips,
           "batch_per_chip": batch_per_chip, "iters": iters,
           "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
           "timings": {}}

    def run(name, build, per_image=True):
        step_once, sync = build()
        dt = _time_loop(step_once, sync, iters, warmup=warmup)
        entry = {"sec": round(dt, 3)}
        if per_image:
            ips = batch * iters / dt
            entry["ips_per_chip"] = round(ips / n_chips, 1)
            print(f"[{name}] {ips / n_chips:,.0f} img/s/chip",
                  file=sys.stderr)
        else:
            entry["ms_per_update"] = round(dt / iters * 1000.0, 3)
            print(f"[{name}] {entry['ms_per_update']} ms/update",
                  file=sys.stderr)
        out["timings"][name] = entry

    def build_train(train_bn, variant="base"):
        # Fresh device copies: train_step donates its state trees, and
        # both train variants (plus the fwd/score runs) must start from
        # live buffers — donating the shared originals would poison the
        # next build.
        v = VARS[variant]
        h = {"p": jax.tree.map(jnp.copy, v["params"]),
             "bs": jax.tree.map(jnp.copy, v["batch_stats"]),
             "o": mesh_lib.replicate(tx.init(
                 jax.tree.map(np.asarray, v["params"])), mesh),
             "k": jax.random.PRNGKey(1), "loss": None}

        def once():
            h["k"], sub = jax.random.split(h["k"])
            h["p"], h["bs"], h["o"], h["loss"] = train_step(
                h["p"], h["bs"], h["o"], sub, sharded, train_bn=train_bn,
                variant=variant)

        return once, lambda: float(h["loss"])

    def build_fwd(train_bn, variant="base"):
        v = VARS[variant]
        h = {"carry": jnp.float32(0.0), "k": jax.random.PRNGKey(2)}

        def once():
            h["k"], sub = jax.random.split(h["k"])
            h["carry"] = fwd_step(v["params"], v["batch_stats"], sub,
                                  sharded, h["carry"], train_bn=train_bn,
                                  variant=variant)

        return once, lambda: float(h["carry"])

    def build_score(variant="base"):
        sbatch = {"image": sharded["image"], "mask": sharded["mask"]}
        h = {"carry": jnp.float32(0.0)}

        def once():
            h["carry"] = score_chained(VARS[variant], sbatch, h["carry"],
                                       variant=variant)

        return once, lambda: float(h["carry"])

    # The backward decomposition (point 6 of the module docstring): the
    # gradient computation isolated from the optimizer.  The grads tree
    # is RETURNED (not reduced to a scalar): outputs can't be
    # dead-code-eliminated, so the whole backward runs — and funneling
    # ~25M gradients into one scalar was measured to push XLA:CPU into
    # a ~5x-slower schedule, which would have failed the consistency
    # check against the grads-returning train step it decomposes.
    @functools.partial(jax.jit, static_argnames=("train_bn", "variant"))
    def bwd_step(params, batch_stats, key, batch, carry, train_bn,
                 variant):
        x = apply_view(batch["image"], train_view, key=key, train=True)
        w = cw[batch["label"]] * batch["mask"]
        (loss, _), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, x, batch["label"],
                                   w, train_bn, variant)
        return carry + loss, grads

    def build_bwd(train_bn, variant="base"):
        v = VARS[variant]
        h = {"carry": jnp.float32(0.0), "k": jax.random.PRNGKey(3),
             "grads": None}

        def once():
            h["k"], sub = jax.random.split(h["k"])
            h["carry"], h["grads"] = bwd_step(
                v["params"], v["batch_stats"], sub, sharded, h["carry"],
                train_bn=train_bn, variant=variant)

        return once, lambda: float(h["carry"])

    # The optimizer update alone: the production FUSED path
    # (train/optim.fused_sgd_update — SGD+momentum+wd in one tree pass,
    # state donated) over a ResNet-50-shaped state, with a fixed grads
    # tree so the timing is pure update.
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def opt_step(params, trace, grads, lr):
        new_params, new_state = optim_lib.fused_sgd_update(
            grads, {"trace": trace}, params, lr, 0.9, 5e-4, jnp.float32)
        return new_params, new_state["trace"]

    def build_opt_update():
        v = VARS["base"]
        grads = jax.tree.map(lambda p: jnp.full(p.shape, 1e-4, p.dtype),
                             v["params"])
        h = {"p": jax.tree.map(jnp.copy, v["params"]),
             "t": jax.tree.map(lambda p: jnp.zeros_like(p), v["params"])}

        def once():
            h["p"], h["t"] = opt_step(h["p"], h["t"], grads,
                                      jnp.float32(0.1))

        def sync():
            return float(jax.tree.leaves(h["p"])[0].reshape(-1)[0])

        return once, sync

    run("score_fwd_eval_bn", build_score)
    run("fwd_only_train_bn", lambda: build_fwd(True))
    run("fwd_only_frozen_bn", lambda: build_fwd(False))
    run("train_frozen_bn", lambda: build_train(False))
    run("train_full", lambda: build_train(True))
    # The measured-ceiling responses, isolated then combined: bf16 BN
    # statistics reclaim the stats tax with the stem untouched; the s2d
    # stem re-shapes the 7x7/s2 conv for the MXU; the combination is the
    # production bench configuration (bench.py resnet50_imagenet_train).
    run("fwd_only_train_bn_bf16stats", lambda: build_fwd(True, "bnfused"))
    run("train_full_bf16stats", lambda: build_train(True, "bnfused"))
    run("score_fwd_s2d", lambda: build_score("s2d"))
    run("train_full_s2d_bf16stats", lambda: build_train(True, "s2d"))
    # The backward decomposition (gradient path, DESIGN.md §4).
    run("bwd_only", lambda: build_bwd(True))
    run("bwd_frozen_bn", lambda: build_bwd(False))
    run("optimizer_update", build_opt_update, per_image=False)
    return out


# Consistency tolerance for (bwd_only + optimizer_update) vs train_full:
# bwd_only already contains the forward, so the two sides time the same
# computation split at the optimizer boundary.  Generous because the
# split runs lose the step's cross-phase fusion and CPU schema runs are
# noisy; a decomposition outside this band is measuring the wrong thing
# and must fail loudly rather than publish.
CONSISTENCY_TOL = 0.35


def check_consistency(out: dict, tol: float = CONSISTENCY_TOL) -> dict:
    """fwd + bwd + optimizer must reassemble into the full step: asserts
    |(bwd_only + optimizer_update) − train_full| <= tol·train_full and
    records the arithmetic in the evidence JSON."""
    t = out["timings"]
    lhs = t["bwd_only"]["sec"] + t["optimizer_update"]["sec"]
    full = t["train_full"]["sec"]
    consistency = {
        "bwd_only_plus_optimizer_sec": round(lhs, 3),
        "train_full_sec": full,
        "ratio": round(lhs / full, 3) if full else None,
        "tol": tol,
        "ok": bool(full and abs(lhs - full) <= tol * full),
    }
    out["consistency"] = consistency
    assert consistency["ok"], (
        f"decomposition inconsistent: bwd_only + optimizer_update = "
        f"{lhs:.3f}s vs train_full = {full:.3f}s (tol {tol:.0%}) — the "
        "variants are not timing the computation they claim")
    return consistency


def device_truth_crosscheck(out: dict, profile_path: str) -> dict:
    """The device-truth cross-check column (ISSUE 11): fold a driver
    capture summary (telemetry/profiler.py's device_profile_rd{n}.json)
    into the decomposition evidence.  The decomposition's host timings
    say how long each variant TOOK; the capture says what the device
    DID during a real round — busy fraction, collective share, measured
    collective bytes.  A host-derived mfu far above device_busy_frac
    means the host timer flattered the device (dispatch gaps hidden by
    async); far below means the device idled on host stalls the
    decomposition never sees.  Stored verbatim + derived deltas, never
    merged into the host numbers."""
    with open(profile_path) as fh:
        capture = json.load(fh)
    cross = {
        "source": profile_path,
        "round": capture.get("round"),
        "device_busy_frac": capture.get("device_busy_frac"),
        "collective_frac": capture.get("collective_frac"),
        "transfer_frac": capture.get("transfer_frac"),
        "collective_bytes_total": capture.get("collective_bytes_total"),
    }
    train = out.get("timings", {}).get("train_full", {})
    host_mfu = train.get("mfu")
    busy = capture.get("device_busy_frac")
    if host_mfu is not None and busy:
        # MFU <= busy always (you cannot achieve flops while idle); the
        # gap busy − mfu is the device-side inefficiency (low-occupancy
        # kernels, collectives), while 1 − busy is the HOST-side gap.
        cross["host_mfu_train_full"] = host_mfu
        cross["device_side_gap"] = round(busy - host_mfu, 3)
        cross["host_side_gap"] = round(1.0 - busy, 3)
    out["device_truth"] = cross
    return cross


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-per-chip", type=int, default=128)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3,
                    help="untimed warmup iterations per variant (lower "
                         "for CPU schema-regeneration runs)")
    ap.add_argument("--consistency-tol", type=float,
                    default=CONSISTENCY_TOL)
    ap.add_argument("--device_profile", type=str, default=None,
                    help="a device_profile_rd{n}.json from a "
                         "--profile_rounds driver run: folded in as the "
                         "device-truth cross-check column "
                         "(device_busy_frac vs host-derived mfu)")
    ap.add_argument("--out", default=os.path.join(
        REPO, "mfu_decomposition.json"))
    args = ap.parse_args()
    prior = None
    try:
        with open(args.out) as fh:
            prior = json.load(fh)
    except (OSError, ValueError):
        pass
    out = measure(args.batch_per_chip, args.iters, warmup=args.warmup)
    # Per-image GF from bench.py's device-cost-analysis captures: the
    # train step (fwd+bwd+SGD) and the scoring forward.  The fwd-only
    # variants share the scoring conv/matmul structure plus the loss.
    GF = {"train_full": 23.91, "train_frozen_bn": 23.91,
          "fwd_only_train_bn": 7.97, "fwd_only_frozen_bn": 7.97,
          "score_fwd_eval_bn": 7.97,
          # bf16 BN statistics change the stats path's memory traffic,
          # not its flop count.
          "fwd_only_train_bn_bf16stats": 7.97,
          "train_full_bf16stats": 23.91,
          # The s2d stem's folded 4x4x12 kernel carries 192 taps where
          # the 7x7x3 had 147 (the pad row/col is structural zeros XLA
          # still multiplies): +0.07 GF/img forward, +0.22 on the train
          # step (analytic; MFU over these counts the zero taps as work,
          # so the s2d MFU figures are conservative for useful flops).
          "score_fwd_s2d": 8.04,
          "train_full_s2d_bf16stats": 24.13,
          # bwd_only = fwd + bwd, no optimizer (the SGD update's flops
          # are ~2 per param — noise at 23.91 GF/img).
          "bwd_only": 23.91, "bwd_frozen_bn": 23.91}
    # Explicit device-kind match: a bare "v5" substring also matches v5p
    # (bf16 peak ~459 TFLOP/s), which would inflate reported MFU ~2.3x.
    # Unknown kinds leave mfu unset rather than guess a peak.
    kind = out["device_kind"].lower()
    peak = 197.0 if ("v5e" in kind or "v5 lite" in kind) else None
    for name, entry in out["timings"].items():
        gf = GF.get(name)
        if gf is None or "ips_per_chip" not in entry:
            continue  # optimizer_update: ms/update, not img/s
        tf = entry["ips_per_chip"] * gf / 1000.0
        entry["tflops_per_sec_per_chip"] = round(tf, 1)
        if peak:
            entry["mfu"] = round(tf / peak, 3)
    # Derived backward figures (the numbers ROADMAP item 4 asks the
    # decomposition to name): the backward pass isolated by subtracting
    # the same-BN forward from bwd_only, its share of the full step, and
    # its own MFU over the 23.91 − 7.97 GF/img it computes.
    t = out["timings"]
    bwd_sec = t["bwd_only"]["sec"] - t["fwd_only_train_bn"]["sec"]
    batch = out["batch_per_chip"] * out["n_chips"]
    if bwd_sec > 0:
        ips_bwd = batch * args.iters / bwd_sec / out["n_chips"]
        tf_bwd = ips_bwd * (GF["bwd_only"] - GF["fwd_only_train_bn"]) \
            / 1000.0
        out["bwd_sec"] = round(bwd_sec, 3)
        out["bwd_frac"] = round(bwd_sec / t["train_full"]["sec"], 3)
        out["bwd_tflops_per_sec_per_chip"] = round(tf_bwd, 1)
        if peak:
            out["bwd_mfu"] = round(tf_bwd / peak, 3)
    out["opt_update_ms"] = t["optimizer_update"]["ms_per_update"]
    check_consistency(out, tol=args.consistency_tol)
    if args.device_profile:
        try:
            cross = device_truth_crosscheck(out, args.device_profile)
            print(f"[device_truth] busy={cross.get('device_busy_frac')} "
                  f"collective={cross.get('collective_frac')} "
                  f"bytes={cross.get('collective_bytes_total')}",
                  file=sys.stderr)
        except (OSError, ValueError) as e:
            print(f"[device_truth] cross-check unavailable: {e!r}",
                  file=sys.stderr)
    out["gf_per_image_source"] = "bench.py device-cost-analysis (r5)"
    out["gf_note"] = ("train_frozen_bn reuses the full-BN 23.91 GF/img "
                      "(no separate cost-analysis capture); its achieved "
                      "TFLOP/s is therefore a slight overcount")
    # CPU device only: an unknown ACCELERATOR kind (v4/v5p/...) leaves
    # mfu unset because the peak table doesn't know it — that capture
    # is still hardware truth and must not be labeled otherwise.
    if "cpu" in kind:
        out["schema_note"] = (
            "schema-validation capture (no accelerator reachable): the "
            "backward-decomposition variants ran end-to-end but the "
            "rates are not hardware truth; live-TPU capture queued for "
            "the next hardware window")
    # Never discard the last HARDWARE capture when regenerating: the
    # file keeps ONE prior_capture slot, filled with the most valuable
    # non-current capture available — hardware beats CPU schema runs,
    # and the more recent of two hardware captures wins.  So the v5e
    # truth survives any number of CPU schema regens (CPU over
    # CPU-with-nested-v5e keeps v5e), and a fresh TPU capture keeps the
    # previous TPU one as its prior.
    def _strip(cap):
        return {k: cap[k]
                for k in ("device_kind", "captured_utc", "timings")
                if k in cap}

    candidates = []
    if prior:
        candidates.append(_strip(prior))  # most recent first
        if isinstance(prior.get("prior_capture"), dict):
            candidates.append(_strip(prior["prior_capture"]))
    hardware = [c for c in candidates
                if "cpu" not in str(c.get("device_kind", "")).lower()]
    keep = (hardware or candidates)[:1]
    if keep:
        out["prior_capture"] = keep[0]
    with open(args.out, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps({k: v for k, v in out["timings"].items()}))


if __name__ == "__main__":
    main()
