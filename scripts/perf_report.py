#!/usr/bin/env python
"""perf_report: the per-phase performance trend table + regression gate.

Reads the bench trajectory (``BENCH_r*.json`` — the harness records of
every bench round — plus, optionally, a fresh ``bench_evidence.json``)
and renders one table per tracked metric across rounds, then exits
NONZERO when the latest capture regressed a pinned metric by more than
the threshold against the best-known value in the series:

  * ``al_round_*``   warm round seconds must not exceed best-known
                     x (1 + threshold) — the end-to-end number a
                     protocol run amortizes to;
  * ``*_train``      images/sec/chip must not fall below best-known
                     x (1 - threshold) — the step-time ceiling.

The gate turns ROADMAP item 5's hardware windows into a machine-checked
verdict: ``python bench.py --assert_no_regression`` (bench's opt-in
wiring) fails CI instead of queueing another by-hand Perfetto read.

Exit codes: 0 no pinned regression / 1 regression(s) / 2 no series
files at all / 3 a ``--current`` file was given but carried no usable
phase data (the gate was asked to judge a run that produced no
evidence — neither "ok" nor a history-vs-itself verdict would be
honest).

The trajectory is hostile input by construction and every shape ships
in this repo's history: BENCH_r01 has an empty tail (no backend),
BENCH_r02's tail is a traceback, r03 died rc=124 mid-line, r04's tail
truncates a phase fragment past parseability, r05 carries a parsed
compact line, and full evidence files rename keys across rounds
(``ips_warm`` -> ``warm_memmap_ips``, ``round_sec_warm`` -> the compact
``warm_s``).  Every shape must degrade to a skip-with-note or an alias
hit — never a KeyError on the trajectory.  Device-truth fields
(``device_busy_frac``, ``collective_frac``, ``collective_bytes_total``
— telemetry/profiler.py) ride the table whenever a capture carried
them.

Stdlib only; no jax import (this runs on hosts that could never
initialize the bench backend).

    python scripts/perf_report.py                    # BENCH_r*.json
    python scripts/perf_report.py A.json B.json      # explicit series
    python scripts/perf_report.py --current bench_evidence.json
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Pinned regression contract (the gate's whole surface, so a reviewer
# can see exactly what trips CI): metric, phase-match, direction.
REGRESSION_THRESHOLD = 0.10
GATED_METRICS = (
    # (metric, phase predicate, "lower"|"higher" is better)
    ("warm_s", lambda name: name.startswith("al_round"), "lower"),
    ("ips_per_chip", lambda name: name.endswith("_train"), "higher"),
    # The disk tier (ISSUE 16): the demand-paged backend's in-loop
    # train rate — a pager regression (cache thrash, stall growth)
    # lands here even when the in-memory phases stay flat.
    ("ips_per_chip", lambda name: name == "disk_pool_feed", "higher"),
)

# Alias chains, newest spelling first — schema drift across bench
# rounds resolves here instead of KeyError-ing on the trajectory.
_ALIASES = {
    "ips_per_chip": ("ips_per_chip",),
    "mfu": ("mfu",),
    "warm_s": ("warm_s", "round_sec_warm"),
    "cold_s": ("cold_s", "round_sec_cold"),
    "warm_ips": ("warm_memmap_ips", "warm_ips", "ips_warm"),
    "acc": ("test_accuracy_rd1", "acc"),
    "overlap_frac": ("overlap_frac", "overlap"),
    "device_busy_frac": ("device_busy_frac",),
    "collective_frac": ("collective_frac",),
    "collective_bytes_total": ("collective_bytes_total",),
}


def _num(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v) if math.isfinite(v) else None


def _normalize_phase(entry: Dict[str, Any]) -> Dict[str, Any]:
    """One phase record (full-evidence OR compact-line shape) -> the
    canonical metric dict.  The compact line's ``ips`` is already
    per-chip (bench._compact_line writes ips_per_chip there); the full
    evidence's ``ips`` is the TOTAL rate — disambiguated by the
    presence of ``n_chips``, which only full entries carry."""
    out: Dict[str, Any] = {}
    for canon, aliases in _ALIASES.items():
        for alias in aliases:
            val = _num(entry.get(alias))
            if val is not None:
                out[canon] = val
                break
    if "ips_per_chip" not in out:
        ips = _num(entry.get("ips"))
        if ips is not None:
            if "n_chips" in entry:
                n = _num(entry.get("n_chips")) or 1.0
                out["ips_per_chip"] = ips / max(n, 1.0)
            else:
                out["ips_per_chip"] = ips
    if entry.get("cached"):
        out["cached"] = True
    return out


def _phases_from_dict(obj: Dict[str, Any]) -> Optional[Dict[str, Dict]]:
    """Phase records out of any dict that carries them: a full evidence
    / parsed compact line ({"phases": {name: {...}}}), or a bare child
    phase line ({"phase": name, ...})."""
    phases = obj.get("phases")
    if isinstance(phases, dict) and phases:
        out = {}
        for name, entry in phases.items():
            if isinstance(entry, dict):
                out[name] = _normalize_phase(entry)
            elif _num(entry) is not None:
                # The deepest compact truncation stage: {name: ips}.
                out[name] = {"ips_per_chip": _num(entry)}
        return out or None
    if isinstance(obj.get("phase"), str):
        return {obj["phase"]: _normalize_phase(obj)}
    return None


def _phases_from_tail(tail: str) -> Optional[Dict[str, Dict]]:
    """Salvage phase records from a stdout tail: the LAST parseable
    JSON line carrying phases wins (the compact-line contract); child
    phase lines merge as a fallback."""
    merged: Dict[str, Dict] = {}
    for line in reversed((tail or "").strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(obj, dict):
            continue
        found = _phases_from_dict(obj)
        if found and "phases" in obj:
            return found           # one full line beats any fragments
        if found:
            for name, entry in found.items():
                merged.setdefault(name, entry)
    return merged or None


def extract_phases(obj: Any) -> Tuple[Optional[Dict[str, Dict]], str]:
    """(phases, note) from ANY of the trajectory's file shapes.  None
    phases = nothing salvageable; the note says why (rendered in the
    table header so a skipped round is visible, not silent)."""
    if not isinstance(obj, dict):
        return None, "not a JSON object"
    direct = _phases_from_dict(obj)
    if direct:
        return direct, "ok"
    parsed = obj.get("parsed")
    if isinstance(parsed, dict):
        found = _phases_from_dict(parsed)
        if found:
            return found, "ok (parsed line)"
    tail = obj.get("tail")
    if isinstance(tail, str) and tail.strip():
        found = _phases_from_tail(tail)
        if found:
            return found, "ok (salvaged from tail)"
        low = tail.lower()
        if "traceback" in low or "error" in low:
            return None, "no data (run died: traceback in tail)"
        return None, "no data (tail holds no parseable result)"
    if obj.get("rc") not in (0, None):
        return None, f"no data (rc={obj.get('rc')})"
    return None, "no data (empty record)"


def load_series(paths: List[str]) -> List[Dict[str, Any]]:
    out = []
    for path in paths:
        label = re.sub(r"^BENCH_|\.json$", "",
                       os.path.basename(path)) or os.path.basename(path)
        try:
            with open(path) as fh:
                obj = json.load(fh)
        except (OSError, ValueError) as e:
            out.append({"path": path, "label": label, "phases": None,
                        "note": f"unreadable ({e.__class__.__name__})"})
            continue
        phases, note = extract_phases(obj)
        out.append({"path": path, "label": label, "phases": phases,
                    "note": note})
    return out


# ---------------------------------------------------------------------------
# Rendering.
# ---------------------------------------------------------------------------

# metric -> (row suffix, format)
_ROW_METRICS = (
    ("ips_per_chip", "ips/chip", "{:,.1f}"),
    ("mfu", "mfu", "{:.3f}"),
    ("warm_s", "warm_s", "{:,.2f}"),
    ("cold_s", "cold_s", "{:,.2f}"),
    ("warm_ips", "warm_ips", "{:,.1f}"),
    ("overlap_frac", "overlap", "{:.3f}"),
    ("device_busy_frac", "dev_busy", "{:.3f}"),
    ("collective_frac", "coll_frac", "{:.3f}"),
    ("collective_bytes_total", "coll_bytes", "{:,.0f}"),
)


def _phase_order(series) -> List[str]:
    order: List[str] = []
    for entry in series:
        for name in (entry["phases"] or {}):
            if name not in order:
                order.append(name)
    return order


def render_table(series) -> str:
    lines = ["perf trend (columns = bench rounds; '-' = not captured)"]
    for entry in series:
        if entry["phases"] is None:
            lines.append(f"  [{entry['label']}] skipped: {entry['note']}")
    with_data = [e for e in series if e["phases"]]
    if not with_data:
        lines.append("  (no round in the series carried phase data)")
        return "\n".join(lines)
    labels = [e["label"] for e in with_data]
    width = max(10, max(len(lb) for lb in labels) + 2)
    name_w = 40
    header = " " * name_w + "".join(f"{lb:>{width}}" for lb in labels)
    lines.append(header)
    for phase in _phase_order(with_data):
        for metric, suffix, fmt in _ROW_METRICS:
            vals = [(e["phases"].get(phase) or {}).get(metric)
                    for e in with_data]
            if all(v is None for v in vals):
                continue
            row = f"{phase} {suffix}"
            cells = "".join(
                f"{fmt.format(v) if v is not None else '-':>{width}}"
                for v in vals)
            lines.append(f"{row:<{name_w}}{cells}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# The regression gate.
# ---------------------------------------------------------------------------

def check_regressions(series, threshold: float = REGRESSION_THRESHOLD
                      ) -> List[str]:
    """Latest capture vs best-known across the PRIOR rounds, per pinned
    metric.  A phase with no prior data cannot regress (first capture
    IS the baseline); a latest round missing the phase is not a
    regression either (a flaky tunnel must not fail the gate — absence
    already shows in the table)."""
    with_data = [e for e in series if e["phases"]]
    if len(with_data) < 2:
        return []
    latest = with_data[-1]
    prior = with_data[:-1]
    problems = []
    for metric, match, direction in GATED_METRICS:
        for phase, entry in latest["phases"].items():
            if not match(phase):
                continue
            value = entry.get(metric)
            if value is None:
                continue
            best = None
            for e in prior:
                v = (e["phases"].get(phase) or {}).get(metric)
                if v is None:
                    continue
                best = v if best is None else (
                    min(best, v) if direction == "lower" else max(best, v))
            if best is None or best <= 0:
                continue
            if direction == "lower" and value > best * (1 + threshold):
                problems.append(
                    f"{phase} {metric}: {value:,.2f} vs best-known "
                    f"{best:,.2f} (>{threshold:.0%} slower) "
                    f"[latest={latest['label']}]")
            if direction == "higher" and value < best * (1 - threshold):
                problems.append(
                    f"{phase} {metric}: {value:,.2f} vs best-known "
                    f"{best:,.2f} (>{threshold:.0%} below) "
                    f"[latest={latest['label']}]")
    return problems


def default_series_paths() -> List[str]:
    return sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python scripts/perf_report.py",
        description="Render the bench perf trend table and gate on "
                    "pinned regressions")
    ap.add_argument("files", nargs="*",
                    help="series files in chronological order "
                         "(default: BENCH_r*.json in the repo root)")
    ap.add_argument("--current", type=str, default=None,
                    help="a fresh evidence/compact JSON appended as the "
                         "latest point (what bench --assert_no_regression "
                         "passes)")
    ap.add_argument("--threshold", type=float,
                    default=REGRESSION_THRESHOLD,
                    help="regression tolerance vs best-known "
                         "(default 0.10)")
    args = ap.parse_args(argv)
    paths = list(args.files) or default_series_paths()
    if args.current:
        paths.append(args.current)
    if not paths:
        print("perf_report: no series files found", file=sys.stderr)
        return 2
    series = load_series(paths)
    print(render_table(series))
    if args.current and series[-1]["phases"] is None:
        # The gate was asked to judge THIS run and this run produced no
        # usable evidence: neither a silent "ok" (nothing was checked)
        # nor a regression verdict against history-vs-itself is honest
        # — a distinct exit code, loudly.
        print("perf_report: NO-EVIDENCE — the --current file carried no "
              f"usable phase data ({series[-1]['note']}); the "
              "regression gate did not run", file=sys.stderr)
        return 3
    problems = check_regressions(series, threshold=args.threshold)
    for p in problems:
        print(f"perf_report: REGRESSION {p}", file=sys.stderr)
    if problems:
        return 1
    with_data = sum(1 for e in series if e["phases"])
    print(f"perf_report: ok ({with_data}/{len(series)} rounds carried "
          f"data; no pinned regression past "
          f"{args.threshold:.0%})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
