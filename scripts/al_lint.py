#!/usr/bin/env python
"""al_lint: the whole-package static-analysis CLI (DESIGN.md §12).

Runs the 18-check registry (10 legacy trace_lint invariants + the
lock-discipline / donation-safety / recompile-hazard /
collective-axis / diagnostics-inert / wal-before-ack
deep checkers) over active_learning_tpu/, bench.py, and scripts/
through ONE shared-parse AST cache.

    python scripts/al_lint.py                 # run everything
    python scripts/al_lint.py --list          # show the registry
    python scripts/al_lint.py --check lock-discipline --check fault-sites
    python scripts/al_lint.py --json          # machine-readable report

Exit codes: 0 clean (suppressed findings allowed — they are counted in
the report), 1 unsuppressed findings, 2 usage error.  Stdlib only; safe
to run against a wedged or backend-less tree.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from active_learning_tpu.analysis import run_package_analysis  # noqa: E402
from active_learning_tpu.analysis.checks import CHECKERS  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="al_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--check", action="append", default=None,
                        metavar="ID",
                        help="run only this check id (repeatable)")
    parser.add_argument("--list", action="store_true",
                        help="list the check registry and exit")
    parser.add_argument("--json", action="store_true",
                        help="emit the findings report as JSON on stdout")
    args = parser.parse_args(argv)

    if args.list:
        width = max(len(c.id) for c in CHECKERS)
        for c in CHECKERS:
            tok = f"  [# al-lint: {c.suppress_token}]" \
                if c.suppress_token else ""
            print(f"{c.id:<{width}}  {c.title}{tok}")
        return 0

    try:
        report = run_package_analysis(check_ids=args.check)
    except ValueError as exc:
        print(f"al_lint: {exc}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(report.to_json(), indent=1))
    else:
        for f in report.unsuppressed:
            print(f"al_lint: {f.check}: {f.render()}", file=sys.stderr)
        for f in report.suppressed:
            print(f"al_lint: suppressed [{f.check}] {f.render()} "
                  f"(reason: {f.suppress_reason})", file=sys.stderr)
        if not report.unsuppressed:
            n = len(report.checks_run)
            s = len(report.suppressed)
            sup = f", {s} suppressed finding(s)" if s else ""
            print(f"al_lint: ok — {n} check(s) over "
                  f"{report.files_scanned} files in "
                  f"{report.elapsed_s:.2f}s{sup}")
    return 1 if report.unsuppressed else 0


if __name__ == "__main__":
    raise SystemExit(main())
