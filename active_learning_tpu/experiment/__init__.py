"""Experiment layer: arg-pool presets, driver (round loop), resume, CLI.

Reference counterparts: src/arg_pools/*.py, src/main_al.py,
src/utils/resume_training.py, src/utils/parser.py.
"""

from . import arg_pools  # noqa: F401  (registers the presets)
from .driver import build_experiment, run_experiment  # noqa: F401
from .resume import (has_saved_experiment, load_experiment,  # noqa: F401
                     save_experiment)
