"""Registered per-dataset training presets ("arg pools").

The reference ships five arg-pool modules, each a ``{dataset: dict}`` of
training hyperparameters selected with ``--arg_pool`` and imported via
``exec()`` (src/main_al.py:48).  Here each pool is a plain
``{dataset: TrainConfig}`` mapping registered under the same name in the
ARG_POOLS registry — same data, no dynamic import.

Sources:
  * "default"                — src/arg_pools/default.py:5-46
  * "ssp_finetuning"         — src/arg_pools/ssp_finetuning.py:4-39
  * "ssp_linear_evaluation"  — src/arg_pools/ssp_linear_evaluation.py:4-26
  * "ssp_finetuning_imbalanced_cifar10_imb_0_1"  /  "..._0_01"
                             — src/arg_pools/ssp_finetuning_imbalanced_*.py

Pretrained checkpoint paths are configurable (the reference hardcodes
relative paths into ``../pretrained_ckpt``); pass ``pretrained_root`` to
``get_train_config`` to rebase them, or leave the default relative layout.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from ..config import (LoaderConfig, OptimizerConfig, PretrainedConfig,
                      SchedulerConfig, TrainConfig)
from ..registry import ARG_POOLS

# Loader presets.  The reference uses 12 torch DataLoader workers for
# ImageNet (default.py:29-38); here num_workers counts decode threads in the
# host pipeline (data/pipeline.py) — same role, same knob.
_CIFAR_TR = LoaderConfig(batch_size=128, num_workers=0)
_CIFAR_TE = LoaderConfig(batch_size=100, num_workers=0)
_IMAGENET_TR = LoaderConfig(batch_size=128, num_workers=12, prefetch=2)
_IMAGENET_TE = LoaderConfig(batch_size=128, num_workers=12, prefetch=2)

_SIMCLR_CIFAR = PretrainedConfig(
    path="pretrained_ckpt/cifar10/simclr.pth.tar",
    required_key=("encoder",), skip_key=("linear",))
# MoCo-v2 checkpoints store the backbone as ``encoder_q``; the surgery keeps
# only those keys, renames them to ``encoder``, and drops the MoCo fc head
# (ssp_finetuning.py:34-37).
_MOCO_IMAGENET = PretrainedConfig(
    path="pretrained_ckpt/imagenet/moco_v2_800ep_pretrain.pth.tar",
    required_key=("encoder_q",), skip_key=("fc",),
    replace_key=(("encoder_q", "encoder"),))


DEFAULT_POOL: Dict[str, TrainConfig] = {
    "cifar10": TrainConfig(
        eval_split=0.01, loader_tr=_CIFAR_TR, loader_te=_CIFAR_TE,
        optimizer=OptimizerConfig("sgd", lr=0.1, weight_decay=5e-4,
                                  momentum=0.9),
        scheduler=SchedulerConfig("cosine", t_max=200)),
    "imbalanced_cifar10": TrainConfig(
        eval_split=0.01, loader_tr=_CIFAR_TR, loader_te=_CIFAR_TE,
        optimizer=OptimizerConfig("sgd", lr=0.1, weight_decay=5e-4,
                                  momentum=0.9),
        scheduler=SchedulerConfig("cosine", t_max=200),
        imbalanced_training=True),
    "imagenet": TrainConfig(
        eval_split=0.01, loader_tr=_IMAGENET_TR, loader_te=_IMAGENET_TE,
        optimizer=OptimizerConfig("sgd", lr=0.1, weight_decay=1e-4,
                                  momentum=0.9),
        scheduler=SchedulerConfig("step", step_size=60, gamma=0.1)),
    # Extension beyond the reference (whose default pool has no
    # imbalanced_imagenet entry, so the dataset can't run at all there):
    # the ImageNet recipe + class-weighted loss.
    "imbalanced_imagenet": TrainConfig(
        eval_split=0.01, loader_tr=_IMAGENET_TR, loader_te=_IMAGENET_TE,
        optimizer=OptimizerConfig("sgd", lr=0.1, weight_decay=1e-4,
                                  momentum=0.9),
        scheduler=SchedulerConfig("step", step_size=60, gamma=0.1),
        imbalanced_training=True),
}

SSP_FINETUNING_POOL: Dict[str, TrainConfig] = {
    "cifar10": TrainConfig(
        eval_split=0.1, loader_tr=_CIFAR_TR, loader_te=_CIFAR_TE,
        optimizer=OptimizerConfig("sgd", lr=0.001, weight_decay=5e-4,
                                  momentum=0.9),
        scheduler=SchedulerConfig("cosine", t_max=200),
        pretrained=_SIMCLR_CIFAR),
    "imagenet": TrainConfig(
        eval_split=0.01, loader_tr=_IMAGENET_TR, loader_te=_IMAGENET_TE,
        optimizer=OptimizerConfig("sgd", lr=0.001, weight_decay=0.0,
                                  momentum=0.9),
        scheduler=SchedulerConfig("step", step_size=10, gamma=0.1),
        pretrained=_MOCO_IMAGENET),
}

SSP_LINEAR_EVALUATION_POOL: Dict[str, TrainConfig] = {
    "imagenet": TrainConfig(
        eval_split=0.01,
        loader_tr=LoaderConfig(batch_size=128, num_workers=8, prefetch=2),
        loader_te=LoaderConfig(batch_size=128, num_workers=8, prefetch=2),
        optimizer=OptimizerConfig("sgd", lr=15.0, weight_decay=1e-4,
                                  momentum=0.9),
        scheduler=SchedulerConfig("step", step_size=20, gamma=0.1),
        pretrained=_MOCO_IMAGENET),
}


def _imb_cifar_pool(ckpt: str) -> Dict[str, TrainConfig]:
    return {
        "imbalanced_cifar10": TrainConfig(
            eval_split=0.1, loader_tr=_CIFAR_TR, loader_te=_CIFAR_TE,
            optimizer=OptimizerConfig("sgd", lr=0.002, weight_decay=0.0,
                                      momentum=0.9),
            scheduler=SchedulerConfig("cosine", t_max=200),
            pretrained=PretrainedConfig(
                path=ckpt, required_key=("encoder",), skip_key=("linear",)),
            imbalanced_training=True),
    }


ARG_POOLS.register("default", DEFAULT_POOL)
ARG_POOLS.register("ssp_finetuning", SSP_FINETUNING_POOL)
ARG_POOLS.register("ssp_linear_evaluation", SSP_LINEAR_EVALUATION_POOL)
ARG_POOLS.register(
    "ssp_finetuning_imbalanced_cifar10_imb_0_1",
    _imb_cifar_pool("pretrained_ckpt/cifar10/simclr_imb_pretrain0_1.tar"))
ARG_POOLS.register(
    "ssp_finetuning_imbalanced_cifar10_imb_0_01",
    _imb_cifar_pool("pretrained_ckpt/cifar10/simclr_imb_pretrain0_01.tar"))

# Synthetic dataset (no reference counterpart; used by tests/benchmarks and
# egress-free e2e runs) trains fine with the CIFAR default recipe.
ARG_POOLS.register("synthetic", {
    "synthetic": TrainConfig(
        eval_split=0.1, loader_tr=_CIFAR_TR, loader_te=_CIFAR_TE,
        optimizer=OptimizerConfig("sgd", lr=0.05, weight_decay=5e-4,
                                  momentum=0.9),
        scheduler=SchedulerConfig("cosine", t_max=200)),
})


def get_train_config(arg_pool: str, dataset: str,
                     pretrained_root: Optional[str] = None) -> TrainConfig:
    """Resolve ``(arg_pool, dataset) -> TrainConfig``; rebases any relative
    pretrained path onto ``pretrained_root`` when given (the reference's
    hardcoded ``../pretrained_ckpt`` layout, ssp_finetuning.py:13)."""
    pool = ARG_POOLS.get(arg_pool)
    try:
        cfg = pool[dataset]
    except KeyError:
        known = ", ".join(sorted(pool))
        raise KeyError(
            f"arg pool '{arg_pool}' has no entry for dataset '{dataset}' "
            f"(has: {known})") from None
    if (pretrained_root and cfg.pretrained.path
            and not os.path.isabs(cfg.pretrained.path)):
        import dataclasses
        new_pre = dataclasses.replace(
            cfg.pretrained,
            path=os.path.join(pretrained_root, cfg.pretrained.path))
        cfg = dataclasses.replace(cfg, pretrained=new_pre)
    return cfg
