"""Command-line entry point.

The reference's 30 argparse flags (src/utils/parser.py:7-92) mapped onto
``ExperimentConfig``.  Run as:

    python -m active_learning_tpu --dataset cifar10 --strategy MarginSampler \
        --rounds 30 --round_budget 1000 --n_epoch 200 --early_stop_patience 50

Flag names match the reference so published commands (README.md:53,
src/gen_jobs.py) translate directly; comet-specific flags are replaced by
the JSONL metrics sink (--disable_metrics).
"""

from __future__ import annotations

import argparse
from typing import List, Optional

from ..config import (ExperimentConfig, ImbalanceConfig, TelemetryConfig,
                      VAALConfig)


def get_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native active learning (parity with "
                    "zeyademam/active_learning)")
    # Experiment identity / logging (parser.py:9-25)
    p.add_argument("--project_name", type=str, default="active-learning")
    p.add_argument("--exp_name", type=str, default="active_learning")
    p.add_argument("--exp_hash", type=str, default=None)
    p.add_argument("--log_dir", type=str, default="./logs")
    p.add_argument("--ckpt_path", type=str, default="./checkpoint")
    p.add_argument("--disable_metrics", action="store_true",
                   help="replaces --enable_comet (metrics on by default)")
    p.add_argument("--metrics_backend", type=str, default="jsonl",
                   help="comma-separated sinks: jsonl, csv, tensorboard")
    p.add_argument("--metrics_rotate_bytes", type=int, default=0,
                   help="rotate metrics.jsonl to metrics.jsonl.1 past "
                        "this many bytes (atomic, no lost lines); 0 = "
                        "unbounded (default)")
    # Dataset (parser.py:27-39)
    p.add_argument("--dataset", type=str, default="cifar10",
                   choices=["cifar10", "imbalanced_cifar10", "imagenet",
                            "imbalanced_imagenet", "synthetic"])
    p.add_argument("--dataset_dir", type=str, default=None)
    p.add_argument("--arg_pool", type=str, default="default")
    p.add_argument("--pretrained_root", type=str, default=None,
                   help="rebase an arg pool's relative pretrained-ckpt path")
    p.add_argument("--imbalance_type", type=str, default=None,
                   choices=[None, "exp", "step"])
    p.add_argument("--imbalance_factor", type=float, default=0.1)
    p.add_argument("--imbalance_seed", type=int, default=0)
    # AL globals (parser.py:41-58)
    p.add_argument("--strategy", type=str, default="RandomSampler")
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--round_budget", type=int, default=5000)
    p.add_argument("--freeze_feature", action="store_true")
    p.add_argument("--init_pool_size", type=int, default=-1,
                   help="-1 => round_budget; 0 => query at round 0")
    p.add_argument("--init_pool_type", type=str, default="random",
                   choices=["random", "random_balance"])
    # Training (parser.py:60-69)
    p.add_argument("--model", type=str, default="SSLResNet18",
                   choices=["SSLResNet18", "SSLResNet50"])
    p.add_argument("--resume_training", action="store_true")
    p.add_argument("--n_epoch", type=int, default=60)
    p.add_argument("--early_stop_patience", type=int, default=30,
                   help="0 disables early stopping")
    p.add_argument("--download_data", action="store_true",
                   help="fetch CIFAR-10 (md5-verified) when absent — the "
                        "reference's torchvision download=True")
    # Debug (parser.py:70-71)
    p.add_argument("--debug_mode", action="store_true")
    p.add_argument("--profile_dir", type=str, default=None,
                   help="device-truth profiling (DESIGN.md §11): bounded "
                        "XLA profiler capture windows land their trace "
                        "artifacts + device_profile_rd{n}.json summaries "
                        "here (set alone: the default warm-round window)")
    p.add_argument("--profile_rounds", type=str, default=None,
                   help="which AL rounds get a capture window: a comma-"
                        "separated list or 'warm' (default: round 1, the "
                        "first warm round).  Round 0 never captures — it "
                        "pays the cold compile tax.  Device ops splice "
                        "into the --export_trace timeline and the "
                        "device_busy_frac / collective_bytes_total "
                        "metrics ride the sink + Prometheus")
    # Run-wide telemetry (active_learning_tpu/telemetry/, DESIGN.md §7).
    # Default ON: per-step/per-epoch metrics through the sink + the
    # heartbeat file; trace export and the watchdog are opt-in.
    p.add_argument("--disable_telemetry", action="store_true",
                   help="turn off per-step metrics, heartbeat, and the "
                        "compile counter (trace/watchdog imply nothing "
                        "when this is set)")
    p.add_argument("--heartbeat_every_s", type=float, default=5.0,
                   help="heartbeat.json rewrite cadence floor (phase "
                        "transitions always force a write)")
    p.add_argument("--export_trace", action="store_true",
                   help="export nested host spans as Chrome trace-event "
                        "JSON to <log_dir>/trace.json (Perfetto / "
                        "chrome://tracing)")
    p.add_argument("--watchdog", action="store_true",
                   help="in-process stall watchdog: log + emit a "
                        "stall_suspected metric when progress halts past "
                        "--stall_deadline_s")
    p.add_argument("--stall_deadline_s", type=float, default=600.0,
                   help="stall deadline for the watchdog AND the "
                        "staleness threshold embedded in heartbeat.json "
                        "(the `status` verb reads it)")
    p.add_argument("--prometheus_file", type=str, default=None,
                   help="atomically rewrite this Prometheus textfile-"
                        "collector scrape file with run gauges")
    p.add_argument("--disable_diagnostics", action="store_true",
                   help="turn off the experiment-truth diagnostics "
                        "layer (score histograms + rd_score_drift_*, "
                        "selection composition, calibration — "
                        "DESIGN.md §13).  On by default; picks and "
                        "experiment state are bit-identical either way")
    p.add_argument("--watchdog_action", type=str, default="log",
                   choices=["log", "snapshot", "degrade"],
                   help="what a confirmed stall does beyond logging: "
                        "snapshot journals it into round_journal.json; "
                        "degrade also triggers the degradation ladder at "
                        "the next safe point (DESIGN.md §10)")
    p.add_argument("--fault_spec", type=str, default=None,
                   help="deterministic fault injection, e.g. "
                        "'h2d_upload:raise@3,ckpt_write:torn@1' — "
                        "site:action[@arg]; defaults to $AL_FAULT_SPEC; "
                        "unset = every site is a zero-cost no-op "
                        "(DESIGN.md §10)")
    # Compute precision (TPU-specific; the reference is fp32-only,
    # get_networks.py:28-29).  Default defers to the arg pool's
    # TrainConfig.dtype, whose "auto" means bf16 on TPU / f32 elsewhere.
    p.add_argument("--dtype", type=str, default=None,
                   choices=["auto", "bfloat16", "float32"],
                   help="model compute precision (params/BN stay float32)")
    p.add_argument("--bn_stats_dtype", type=str, default=None,
                   choices=["auto", "bfloat16", "float32"],
                   help="BN batch-statistics read precision: auto = fused "
                        "bf16-read/f32-accumulate stats on bf16 models "
                        "(the flax f32 promotion costs ~23%% of ResNet-50 "
                        "forward); float32 forces the flax path")
    p.add_argument("--stem", type=str, default=None,
                   choices=["default", "s2d"],
                   help="ResNet stem layout: s2d folds the 224px 7x7/s2 "
                        "stem conv into an exact 4x4/s1 conv over "
                        "space-to-depth (112x112x12) input — same math, "
                        "MXU-shaped (ignored by CIFAR-stem models)")
    p.add_argument("--resident_scoring_bytes", type=int, default=None,
                   help="device-resident pool budget in bytes.  Default "
                        "(unset) AUTO-sizes from live HBM headroom at "
                        "each round start, so pools that fit the chip pin "
                        "in HBM and later query/eval passes are on-device "
                        "gathers.  Pass an integer to pin the budget, 0 "
                        "to disable residency.")
    p.add_argument("--pool_sharding", type=str, default=None,
                   choices=["auto", "replicated", "row"],
                   help="resident-pool layout over the mesh: row shards "
                        "pool rows (and k-center factor matrices) over "
                        "the data axis so per-chip residency scales "
                        "1/ndev with chip count; replicated pins one "
                        "full copy per chip.  auto (the default) picks "
                        "row on any single-process multi-device mesh.  "
                        "Scores, batches, and k-center picks are "
                        "bit-identical across layouts")
    p.add_argument("--pool_backend", type=str, default=None,
                   choices=["auto", "memory", "disk"],
                   help="pool storage backend (DESIGN.md §16): memory "
                        "holds the whole pool in host RAM; disk pages "
                        "bucket-aligned row blocks from a per-host "
                        "extent file through a bounded host cache, so "
                        "pools bigger than any host's RAM run on the "
                        "same hardware.  auto (the default) takes the "
                        "disk tier only past a host-RAM watermark.  "
                        "Picks and experiment state are bit-identical "
                        "across backends")
    p.add_argument("--train_feed", type=str, default=None,
                   choices=["auto", "resident", "host"],
                   help="train-batch feed: auto picks the top of the "
                        "hierarchy (resident-gather from the pinned pool "
                        "> prefetched-host > serial-host); resident/host "
                        "force a leg.  All feeds are bit-identical at "
                        "the same seeds — throughput only")
    p.add_argument("--feed_workers", type=int, default=None,
                   help="gather/decode worker threads for the host train "
                        "feed (the reference DataLoader's num_workers); "
                        "default defers to the arg pool's train loader")
    p.add_argument("--fused_optimizer", type=str, default=None,
                   choices=["auto", "on", "off"],
                   help="fused SGD+momentum+weight-decay update inside "
                        "the donated train step (one tree pass instead "
                        "of the optax chain's four; bit-identical to "
                        "optax at f32 state).  auto = on for SGD-family "
                        "optimizers")
    p.add_argument("--optim_state_dtype", type=str, default=None,
                   choices=["f32", "bf16"],
                   help="momentum-buffer dtype on the fused optimizer "
                        "path: f32 (default, bit-parity with optax) or "
                        "bf16 (half the optimizer HBM; read bf16, "
                        "accumulate f32, bounded-delta)")
    p.add_argument("--grad_allreduce", type=str, default=None,
                   choices=["f32", "int8", "int8_rs", "auto"],
                   help="gradient sync precision across the mesh: f32 "
                        "(default, bit-exact psum); int8 (EQuARX-style "
                        "block-scaled quantized sync, int8 wire "
                        "payload); int8_rs forces the pod-tier "
                        "reduce-scatter wire form (~2n bytes regardless "
                        "of device count — auto-picked above the "
                        "~8-device crossover anyway); auto = quantized "
                        "on any multi-device mesh.  All quantized modes "
                        "are bounded-delta, off on single-device "
                        "meshes, and gated on the multichip learning "
                        "probe — a failed probe degrades the run to "
                        "f32 loudly")
    p.add_argument("--scale_batch", type=str, default=None,
                   choices=["auto", "off"],
                   help="large-batch scaling rules as the mesh grows "
                        "(DESIGN.md §15): auto multiplies the train "
                        "batch by the device count (the arg pool's "
                        "batch becomes per-chip), scales lr linearly, "
                        "and raises the cosine warmup to a >=5-epoch "
                        "gradual ramp — so a pod-scale global batch "
                        "doesn't silently cost accuracy")
    p.add_argument("--round_pipeline", type=str, default="auto",
                   choices=["auto", "off", "speculative"],
                   help="pipelined AL round: speculative overlaps the "
                        "next query's pool scoring with the fit's "
                        "early-stop patience tail (restarting from any "
                        "later best checkpoint) and prefetches the "
                        "coming fit's feed while selection runs.  auto "
                        "(the default) picks speculative on any "
                        "single-process multi-device mesh.  Picks and "
                        "experiment state are bit-identical to off at "
                        "the same seeds — wall-clock only")
    # Coreset / BADGE scale controls (parser.py:74-79)
    p.add_argument("--subset_labeled", type=int, default=None)
    p.add_argument("--subset_unlabeled", type=int, default=None)
    p.add_argument("--partitions", type=int, default=1)
    p.add_argument("--kcenter_batch", type=int, default=8,
                   help="batched greedy k-center: picks folded per pool "
                        "pass (exact re-check keeps selection identical "
                        "to 1); 1 = sequential scan")
    p.add_argument("--compilation_cache_dir", type=str, default=None,
                   help="persistent XLA compilation cache (default "
                        "~/.cache/al_tpu_xla_cache; '' disables)")
    # VAAL (parser.py:81-92)
    p.add_argument("--vae_latent_dim", type=int, default=64)
    # Reference spelling (parser.py:84); --adversary_param kept as an alias
    # for commands written against earlier versions of this CLI.
    p.add_argument("--vaal_adversary_param", "--adversary_param",
                   dest="vaal_adversary_param", type=float, default=10.0)
    p.add_argument("--lr_vae", type=float, default=5e-5)
    p.add_argument("--lr_discriminator", type=float, default=1e-3)
    # Seeds / mesh (TPU-specific)
    p.add_argument("--run_seed", type=int, default=0)
    p.add_argument("--num_devices", type=int, default=-1,
                   help="-1 = all local devices")
    # Multi-host: jax.distributed over DCN (the reference is single-node
    # only, strategy.py:288; these flags are the pod-scale replacement).
    p.add_argument("--coordinator_address", type=str, default=None,
                   help="host:port of process 0 (TPU pods auto-discover)")
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    return p


def args_to_config(args: argparse.Namespace) -> ExperimentConfig:
    return ExperimentConfig(
        project_name=args.project_name,
        exp_name=args.exp_name,
        exp_hash=args.exp_hash,
        log_dir=args.log_dir,
        ckpt_path=args.ckpt_path,
        enable_metrics=not args.disable_metrics,
        metrics_backend=args.metrics_backend,
        metrics_rotate_bytes=args.metrics_rotate_bytes,
        dataset=args.dataset,
        dataset_dir=args.dataset_dir,
        arg_pool=args.arg_pool,
        pretrained_root=args.pretrained_root,
        imbalance=ImbalanceConfig(
            imbalance_type=args.imbalance_type,
            imbalance_factor=args.imbalance_factor,
            imbalance_seed=args.imbalance_seed),
        strategy=args.strategy,
        rounds=args.rounds,
        round_budget=args.round_budget,
        freeze_feature=args.freeze_feature,
        init_pool_size=args.init_pool_size,
        init_pool_type=args.init_pool_type,
        model=args.model,
        resume_training=args.resume_training,
        n_epoch=args.n_epoch,
        early_stop_patience=args.early_stop_patience,
        download_data=args.download_data,
        debug_mode=args.debug_mode,
        profile_dir=args.profile_dir,
        profile_rounds=args.profile_rounds,
        telemetry=TelemetryConfig(
            enabled=not args.disable_telemetry,
            heartbeat_every_s=args.heartbeat_every_s,
            export_trace=args.export_trace,
            watchdog=args.watchdog,
            stall_deadline_s=args.stall_deadline_s,
            prometheus_file=args.prometheus_file,
            diagnostics=not args.disable_diagnostics,
            watchdog_action=args.watchdog_action),
        fault_spec=args.fault_spec,
        dtype=args.dtype,
        bn_stats_dtype=args.bn_stats_dtype,
        stem=args.stem,
        resident_scoring_bytes=args.resident_scoring_bytes,
        train_feed=args.train_feed,
        pool_sharding=args.pool_sharding,
        feed_workers=args.feed_workers,
        pool_backend=args.pool_backend,
        fused_optimizer=args.fused_optimizer,
        optim_state_dtype=args.optim_state_dtype,
        grad_allreduce=args.grad_allreduce,
        scale_batch=args.scale_batch,
        round_pipeline=args.round_pipeline,
        subset_labeled=args.subset_labeled,
        subset_unlabeled=args.subset_unlabeled,
        partitions=args.partitions,
        kcenter_batch=args.kcenter_batch,
        compilation_cache_dir=args.compilation_cache_dir,
        vaal=VAALConfig(
            vae_latent_dim=args.vae_latent_dim,
            adversary_param=args.vaal_adversary_param,
            lr_vae=args.lr_vae,
            lr_discriminator=args.lr_discriminator),
        run_seed=args.run_seed,
        num_devices=args.num_devices,
        coordinator_address=args.coordinator_address,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )


def main(argv: Optional[List[str]] = None):
    import sys

    argv = list(sys.argv[1:]) if argv is None else list(argv)
    # The verbs this CLI carries beyond the reference's flat flag
    # surface: ``serve`` opens the ONLINE path (predictions +
    # acquisition scores over HTTP from an experiment's best
    # checkpoint — active_learning_tpu/serve/) and ``status`` renders a
    # live run summary (telemetry/status.py).  Flat invocations stay
    # byte-compatible with every published reference command.
    if argv and argv[0] == "serve":
        from ..serve.cli import main as serve_main
        return serve_main(argv[1:])
    # ``stream``: the continual ingest -> score -> select service
    # (active_learning_tpu/stream/, DESIGN.md §14) — serving-side ingest
    # and the AL loop as one long-lived process on one persistent mesh.
    if argv and argv[0] == "stream":
        from ..stream.cli import main as stream_main
        return stream_main(argv[1:])
    # ``status``: render a live run summary from heartbeat + metrics —
    # stdlib only, answers in milliseconds with NO jax import (it must
    # work from any shell against a wedged run).
    if argv and argv[0] == "status":
        from ..telemetry.status import main as status_main
        return status_main(argv[1:])
    # ``report``: render a run's label-efficiency curve — or a
    # cross-run strategy comparison at matched label budgets — from
    # run_report.json / metrics.jsonl (telemetry/report.py; stdlib
    # only, no jax import, same contract as ``status``).
    if argv and argv[0] == "report":
        from ..telemetry.report import main as report_main
        return report_main(argv[1:])
    # ``fleet``: many experiments on preemptible capacity — the sweep
    # controller (active_learning_tpu/fleet/, DESIGN.md §17).  Host-pure
    # like ``status``/``report``: the head node never imports jax.
    if argv and argv[0] == "fleet":
        from ..fleet.cli import main as fleet_main
        return fleet_main(argv[1:])
    from ..faults.preempt import PreemptionRequested
    from .driver import run_experiment
    args = get_parser().parse_args(argv)
    # run_experiment performs the jax.distributed rendezvous itself (a
    # no-op without the multi-host config fields), so programmatic callers
    # get the same behavior as the CLI.
    try:
        return run_experiment(args_to_config(args))
    except PreemptionRequested:
        # Graceful preemption (SIGTERM/SIGINT): the durable state is
        # checkpointed and consistent — exit 0 so orchestrators treat
        # the eviction as clean; --resume_training continues the run
        # bit-identically.
        return 0


if __name__ == "__main__":
    main()
