"""Experiment-sweep job generator.

Prints the shell commands for the paper's experiment grids
(reference: src/gen_jobs.py:3-145) against this package's CLI
(``python -m active_learning_tpu``).  Three sweeps:

  * ImageNet linear evaluation — SSLResNet50, frozen features, 8 rounds x
    10k budget, 30k init pool, 50k/80k subsets, 10 partitions
    (gen_jobs.py:3-42);
  * ImageNet end-to-end SSP finetuning — same protocol, 60 epochs,
    patience 30 (gen_jobs.py:45-86);
  * CIFAR-10 (balanced or imbalanced) — SSLResNet18, 30 rounds x 1k,
    200 epochs, patience 50 (gen_jobs.py:89-138).

Run: ``python -m active_learning_tpu.experiment.gen_jobs [dataset_dir]``.
"""

from __future__ import annotations

import sys
from itertools import product
from typing import List, Sequence

IMAGENET_STRATEGIES = (
    "RandomSampler", "BalancedRandomSampler", "MASESampler",
    "MarginSampler", "ConfidenceSampler", "BASESampler", "VAALSampler",
    "PartitionedCoresetSampler", "PartitionedBADGESampler")

CIFAR_STRATEGIES = (
    "RandomSampler", "BalancedRandomSampler", "MASESampler",
    "MarginSampler", "ConfidenceSampler", "BASESampler",
    "BalancingSampler", "VAALSampler", "CoresetSampler", "BADGESampler")

CLI = "python -m active_learning_tpu"


def _init_pool_flag(strategy: str) -> str:
    pool_type = ("random_balance" if strategy == "BalancedRandomSampler"
                 else "random")
    return f"--init_pool_type {pool_type}"


def imagenet_experiments(dataset_dir: str, arg_pool: str,
                         extra: str = "") -> List[str]:
    jobs = []
    for strategy in IMAGENET_STRATEGIES:
        jobs.append(
            f"{CLI} --dataset_dir {dataset_dir} "
            f"--exp_name {strategy}_arg_{arg_pool}_imagenet_b10000 "
            f"--dataset imagenet --arg_pool {arg_pool} "
            f"--model SSLResNet50 --strategy {strategy} "
            f"--rounds 8 --round_budget 10000 --init_pool_size 30000 "
            f"--subset_labeled 50000 --subset_unlabeled 80000 "
            f"--partitions 10 {extra}{_init_pool_flag(strategy)}")
    return jobs


def linear_evaluation_imagenet_experiments(dataset_dir: str) -> List[str]:
    return imagenet_experiments(dataset_dir, "ssp_linear_evaluation",
                                extra="--freeze_feature ")


def end_to_end_imagenet_experiments_pretrained(dataset_dir: str
                                               ) -> List[str]:
    return imagenet_experiments(
        dataset_dir, "ssp_finetuning",
        extra="--early_stop_patience 30 --n_epoch 60 ")


def cifar10_experiments(dataset_dir: str, number_of_runs: int = 1,
                        n_epoch: int = 200, rounds: int = 30,
                        imbalanced: bool = False,
                        round_budgets: Sequence[int] = (1000,)) -> List[str]:
    if imbalanced:
        dataset = "imbalanced_cifar10"
        arg_pool = "ssp_finetuning_imbalanced_cifar10_imb_0_1"
        imb = "--imbalance_factor 0.1 --imbalance_type exp "
    else:
        dataset = "cifar10"
        arg_pool = "ssp_finetuning"
        imb = ""
    jobs = []
    for _, strategy, budget in product(range(number_of_runs),
                                       CIFAR_STRATEGIES, round_budgets):
        # --download_data makes every CIFAR job one-command on a fresh
        # machine (the reference gets this implicitly from torchvision
        # download=True, custom_cifar10.py:30-33).
        jobs.append(
            f"{CLI} --dataset_dir {dataset_dir} --download_data "
            f"--exp_name {strategy}_arg_{arg_pool}_{dataset}_b{budget} "
            f"--dataset {dataset} --arg_pool {arg_pool} "
            f"--n_epoch {n_epoch} --early_stop_patience 50 "
            f"--model SSLResNet18 --strategy {strategy} "
            f"--rounds {rounds} --round_budget {budget} "
            f"--init_pool_size {budget} {imb}{_init_pool_flag(strategy)}")
    return jobs


def all_jobs(dataset_dir: str = "<YOUR DATASET DIR HERE>") -> List[str]:
    return (linear_evaluation_imagenet_experiments(dataset_dir)
            + end_to_end_imagenet_experiments_pretrained(dataset_dir)
            + cifar10_experiments(dataset_dir)
            + cifar10_experiments(dataset_dir, imbalanced=True))


def main(argv=None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    dataset_dir = argv[0] if argv else "<YOUR DATASET DIR HERE>"
    for job in all_jobs(dataset_dir):
        print(job)


if __name__ == "__main__":
    main()
