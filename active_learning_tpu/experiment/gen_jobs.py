"""Experiment-sweep job generator — shell commands OR fleet sweep specs.

The paper's experiment grids (reference: src/gen_jobs.py:3-145) against
this package's CLI (``python -m active_learning_tpu``), from ONE grid
definition with two renderings:

  * ``--format shell`` (the default, and the reference's behavior):
    print one pasteable command per experiment;
  * ``--format fleet``: emit the same grid as a fleet sweep-spec JSON
    (active_learning_tpu/fleet/spec.py) for
    ``python -m active_learning_tpu fleet run --spec ...`` — the human-
    paste path and the controller path can never drift, because both
    render the same arg dicts through the same ``run_argv`` mapping.

Three sweeps:

  * ImageNet linear evaluation — SSLResNet50, frozen features, 8 rounds x
    10k budget, 30k init pool, 50k/80k subsets, 10 partitions
    (gen_jobs.py:3-42);
  * ImageNet end-to-end SSP finetuning — same protocol, 60 epochs,
    patience 30 (gen_jobs.py:45-86);
  * CIFAR-10 (balanced or imbalanced) — SSLResNet18, 30 rounds x 1k,
    200 epochs, patience 50 (gen_jobs.py:89-138).

Run: ``python -m active_learning_tpu.experiment.gen_jobs [dataset_dir]
[--format shell|fleet] [--sweep NAME]``.
"""

from __future__ import annotations

import argparse
import json
import sys
from itertools import product
from typing import Any, Dict, List, Optional, Sequence

from ..fleet.spec import run_argv

IMAGENET_STRATEGIES = (
    "RandomSampler", "BalancedRandomSampler", "MASESampler",
    "MarginSampler", "ConfidenceSampler", "BASESampler", "VAALSampler",
    "PartitionedCoresetSampler", "PartitionedBADGESampler")

CIFAR_STRATEGIES = (
    "RandomSampler", "BalancedRandomSampler", "MASESampler",
    "MarginSampler", "ConfidenceSampler", "BASESampler",
    "BalancingSampler", "VAALSampler", "CoresetSampler", "BADGESampler")

CLI = "python -m active_learning_tpu"


def _init_pool_type(strategy: str) -> str:
    return ("random_balance" if strategy == "BalancedRandomSampler"
            else "random")


def _render(args: Dict[str, Any]) -> str:
    """One arg dict as the pasteable shell command — the same
    args -> argv mapping the fleet controller launches with."""
    return " ".join([CLI] + run_argv(args))


def imagenet_args(dataset_dir: str, arg_pool: str,
                  extra: Optional[Dict[str, Any]] = None
                  ) -> List[Dict[str, Any]]:
    """The ImageNet protocol's arg dicts, one per strategy.  Key order
    is the flag order the printed commands have always had."""
    jobs = []
    for strategy in IMAGENET_STRATEGIES:
        jobs.append({
            "dataset_dir": dataset_dir,
            "exp_name": f"{strategy}_arg_{arg_pool}_imagenet_b10000",
            "dataset": "imagenet", "arg_pool": arg_pool,
            "model": "SSLResNet50", "strategy": strategy,
            "rounds": 8, "round_budget": 10000,
            "init_pool_size": 30000,
            "subset_labeled": 50000, "subset_unlabeled": 80000,
            "partitions": 10, **(extra or {}),
            "init_pool_type": _init_pool_type(strategy)})
    return jobs


def linear_evaluation_imagenet_args(dataset_dir: str
                                    ) -> List[Dict[str, Any]]:
    return imagenet_args(dataset_dir, "ssp_linear_evaluation",
                         extra={"freeze_feature": True})


def end_to_end_imagenet_args_pretrained(dataset_dir: str
                                        ) -> List[Dict[str, Any]]:
    return imagenet_args(dataset_dir, "ssp_finetuning",
                         extra={"early_stop_patience": 30, "n_epoch": 60})


def cifar10_args(dataset_dir: str, number_of_runs: int = 1,
                 n_epoch: int = 200, rounds: int = 30,
                 imbalanced: bool = False,
                 round_budgets: Sequence[int] = (1000,)
                 ) -> List[Dict[str, Any]]:
    if imbalanced:
        dataset = "imbalanced_cifar10"
        arg_pool = "ssp_finetuning_imbalanced_cifar10_imb_0_1"
        imb: Dict[str, Any] = {"imbalance_factor": 0.1,
                               "imbalance_type": "exp"}
    else:
        dataset = "cifar10"
        arg_pool = "ssp_finetuning"
        imb = {}
    jobs = []
    for _, strategy, budget in product(range(number_of_runs),
                                       CIFAR_STRATEGIES, round_budgets):
        # --download_data makes every CIFAR job one-command on a fresh
        # machine (the reference gets this implicitly from torchvision
        # download=True, custom_cifar10.py:30-33).
        jobs.append({
            "dataset_dir": dataset_dir, "download_data": True,
            "exp_name": f"{strategy}_arg_{arg_pool}_{dataset}_b{budget}",
            "dataset": dataset, "arg_pool": arg_pool,
            "n_epoch": n_epoch, "early_stop_patience": 50,
            "model": "SSLResNet18", "strategy": strategy,
            "rounds": rounds, "round_budget": budget,
            "init_pool_size": budget, **imb,
            "init_pool_type": _init_pool_type(strategy)})
    return jobs


# -- the shell rendering (the reference's surface, byte-stable) --------------

def linear_evaluation_imagenet_experiments(dataset_dir: str) -> List[str]:
    return [_render(a) for a in linear_evaluation_imagenet_args(dataset_dir)]


def end_to_end_imagenet_experiments_pretrained(dataset_dir: str
                                               ) -> List[str]:
    return [_render(a)
            for a in end_to_end_imagenet_args_pretrained(dataset_dir)]


def cifar10_experiments(dataset_dir: str, **kwargs: Any) -> List[str]:
    return [_render(a) for a in cifar10_args(dataset_dir, **kwargs)]


def all_jobs(dataset_dir: str = "<YOUR DATASET DIR HERE>") -> List[str]:
    return (linear_evaluation_imagenet_experiments(dataset_dir)
            + end_to_end_imagenet_experiments_pretrained(dataset_dir)
            + cifar10_experiments(dataset_dir)
            + cifar10_experiments(dataset_dir, imbalanced=True))


# -- the fleet rendering -----------------------------------------------------

# Sweep name -> arg-dict builder.  init_pool_type varies per strategy,
# so each sweep is a defaults + explicit-runs spec, not a pure grid.
SWEEPS = {
    "imagenet_linear": linear_evaluation_imagenet_args,
    "imagenet_finetune": end_to_end_imagenet_args_pretrained,
    "cifar10": lambda d: cifar10_args(d),
    "imbalanced_cifar10": lambda d: cifar10_args(d, imbalanced=True),
}


def fleet_spec(dataset_dir: str, sweep: Optional[str] = None
               ) -> Dict[str, Any]:
    """The sweep(s) as ONE fleet sweep-spec JSON object: ``defaults``
    carries the dataset dir; each job is an explicit ``runs`` entry
    (init_pool_type varies per strategy, so the grid form cannot
    express the paper's protocol).  ``sweep`` narrows to one grid;
    default is all 38 experiments."""
    names = [sweep] if sweep else list(SWEEPS)
    for name in names:
        if name not in SWEEPS:
            raise ValueError(f"unknown sweep {name!r} "
                             f"(one of: {', '.join(SWEEPS)})")
    runs = []
    for name in names:
        for args in SWEEPS[name](dataset_dir):
            rest = dict(args)
            rest.pop("dataset_dir", None)
            runs.append(rest)
    return {"name": sweep or "paper_sweeps",
            "defaults": {"dataset_dir": dataset_dir},
            "runs": runs}


def get_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m active_learning_tpu.experiment.gen_jobs",
        description="Print the paper's experiment sweeps as shell "
                    "commands or a fleet sweep-spec JSON")
    p.add_argument("dataset_dir", nargs="?",
                   default="<YOUR DATASET DIR HERE>")
    p.add_argument("--format", choices=["shell", "fleet"],
                   default="shell", dest="fmt")
    p.add_argument("--sweep", choices=sorted(SWEEPS), default=None,
                   help="narrow --format fleet to one grid "
                        "(default: all three sweeps, 38 runs)")
    return p


def main(argv: Optional[List[str]] = None) -> None:
    args = get_parser().parse_args(
        sys.argv[1:] if argv is None else argv)
    if args.fmt == "fleet":
        print(json.dumps(fleet_spec(args.dataset_dir, args.sweep),
                         indent=1))
        return
    for job in all_jobs(args.dataset_dir):
        print(job)


if __name__ == "__main__":
    main()
