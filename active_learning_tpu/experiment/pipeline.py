"""The pipelined AL round coordinator: speculative scoring + select-time
train prefetch (DESIGN.md §8).

The sequential round loop (experiment/driver.py) runs query -> train ->
test strictly one after another while most of the mesh idles inside each
phase's host segments.  But the next query's pool scores depend ONLY on
the round's frozen best checkpoint — which `Strategy.train` knows long
before the fit ends (the early-stop patience tail trains past the best
epoch by construction) — so the Podracer decoupling (PAPERS.md) applies:

  * **Speculative scoring** — a host-side scoring executor starts
    scoring pool chunks as soon as a new best checkpoint publishes
    during the fit (the in-process leg of the best-ckpt bus:
    Trainer.fit's ``on_best`` callback; the disk leg reuses the serve
    executor's hot-reload pattern via train/checkpoint.BestCkptWatcher)
    and restarts from scratch when a later epoch improves best.  Chunk
    dispatches interleave with train steps under ONE shared enqueue
    lock (Trainer.dispatch_lock) so the two streams share the mesh
    without per-device reordering of collectives.
  * **Correctness contract** — the pipelined round's picks are
    BIT-identical to the sequential loop at the same seeds (pinned in
    tests/test_pipeline.py): speculation consumes NO rng (plans come
    from rng-free pool views), chunk slices splice bit-identically to
    the monolithic pass (scoring.chunk_row_slices), and ``consume``
    serves a chunk only when its source tag equals the FINAL
    (round, best_epoch) — anything else is recomputed inline with the
    query-time weights, so a speculative miss costs wall-clock, never a
    score.
  * **Select-time train prefetch** — the moment scores are handed to
    the sampler, a prefetch thread pre-resolves the coming fit's feed
    and warms what it will touch (Trainer.prepare_next_fit), so `fit`
    starts with zero feed stall at step 0 while k-center/BADGE runs its
    collective scans.

The coordinator functions listed in PIPELINE_COORDINATOR_FNS are
statically forbidden from ``block_until_ready``/``device_get``
(scripts/trace_lint.py check 7): the overlap must never sync the train
stream's arrays — the scorer may wait on its OWN chunk outputs (that
blocks only its thread), but a coordinator-level device sync would
serialize the very streams this module exists to overlap.

Off on multi-process meshes by design: every process of a pod must
enqueue the same collectives in the same order, and a per-process
scorer thread cannot guarantee cross-process interleaving — the same
gate row sharding uses (parallel/resident.resolve_sharding).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import faults
from ..strategies import scoring
from ..telemetry import runtime as tele_runtime
from ..telemetry import spans as tele_spans
from ..telemetry import diagnostics as diag_lib
from ..train import checkpoint as ckpt_lib
from ..utils.logging import get_logger

# Batches per speculative chunk — the scorer's dispatch/restart
# granularity: small enough that a late best-ckpt improvement wastes at
# most one chunk of in-flight compute and that chunk dispatches
# interleave train steps at a fine grain, large enough that the
# per-chunk host fetch amortizes.
SPEC_CHUNK_BATCHES = 8

# Disk-poll cadence while no in-process publish has arrived (the
# BestCkptWatcher leg of the bus — e.g. a Strategy.train override that
# never wires on_best).
WATCH_POLL_S = 2.0

# Mirrored by scripts/trace_lint.py check 7 (the lint works without
# importing jax): the coordinator tier of the speculative scorer.  Each
# must exist, and none may call block_until_ready/device_get.
PIPELINE_COORDINATOR_FNS = ("_worker", "_worker_loop", "_score_slice",
                            "_score_chunk", "publish_best", "finalize",
                            "consume")

# Lock discipline, statically enforced (scripts/al_lint.py
# lock-discipline): the plan/bus state below is shared between the
# driver thread (arm/consume/disarm), the trainer thread (publish_best/
# finalize), and the scorer thread (_worker_loop) — every access goes
# through the one condition's lock.  _next_job_locked is the declared
# under-the-lock helper (the *_locked suffix convention).
_GUARDED_BY = {"_plan": "_cv", "_done": "_cv", "_src": "_cv",
               "_final_tag": "_cv", "_consumed": "_cv",
               "_in_flight": "_cv", "_busy_s": "_cv", "_stop": "_cv",
               "stats": "_cv"}


def resolve_round_pipeline(spec: Optional[str], mesh) -> str:
    """The --round_pipeline auto rule: "speculative" on any
    single-process multi-device mesh, "off" on single-device meshes
    (nothing to share) and on pods (per-process scorer threads cannot
    guarantee one cross-process collective order)."""
    spec = spec or "auto"
    if spec not in ("auto", "off", "speculative"):
        raise ValueError(
            f"round_pipeline={spec!r} is not one of 'auto'/'off'/"
            "'speculative'")
    if spec != "auto":
        return spec
    from ..parallel import mesh as mesh_lib
    if mesh.devices.size > 1 and not mesh_lib.is_multiprocess(mesh):
        return "speculative"
    return "off"


class RoundPipeline:
    """One experiment's pipelined-round coordinator: owns the scorer
    thread, the per-round speculative plan, and the select-time
    prefetch thread.  The driver arms it before each fit, the trainer
    publishes best checkpoints into it, and ``Strategy.collect_scores``
    consumes it at the next query."""

    mode = "speculative"

    def __init__(self, strategy):
        self._strategy = strategy
        self._cv = threading.Condition()
        self._plan: Optional[Dict[str, Any]] = None
        self._done: Dict[int, Tuple[Tuple[int, int], Dict]] = {}
        self._src: Optional[Tuple[Tuple[int, int], Any]] = None
        self._final_tag: Optional[Tuple[int, int]] = None
        self._consumed = True
        self._in_flight: Optional[int] = None
        self._stop = False
        self._busy_s = 0.0
        self._thread: Optional[threading.Thread] = None
        self._prefetch_thread: Optional[threading.Thread] = None
        self._watcher: Optional[ckpt_lib.BestCkptWatcher] = None
        self._last_poll = 0.0
        self.logger = get_logger()
        # Cumulative evidence counters; last_consume summarizes the most
        # recent hand-over for the driver's round metrics.
        self.stats = {"publishes": 0, "chunks_scored": 0,
                      "chunks_invalidated": 0, "chunks_inline": 0,
                      "chunks_hit": 0, "plan_misses": 0,
                      # Chunk executions lost to an exception (the
                      # best-effort contract: speculation dies for the
                      # round, the query recomputes sequentially) —
                      # observable so tests can tell an environmental
                      # failure from a correctness bug.
                      "chunks_failed": 0}
        self.last_consume: Dict[str, Any] = {}

    # -- round lifecycle (driver-facing) ----------------------------------

    def arm(self, round_idx: int) -> bool:
        """Install the speculative plan for round ``round_idx + 1``'s
        query — called by the driver right before ``Strategy.train``.
        The plan is rng-FREE by contract (Strategy.speculative_
        scoring_plan); a sampler whose scoring pass depends on rng state
        returns None and the round runs un-speculated.  Returns whether
        a plan was armed."""
        strategy = self._strategy
        self._join_prefetch()
        with self._cv:
            self._plan, self._done, self._src = None, {}, None
            self._final_tag, self._consumed = None, True
            self._in_flight = None
        try:
            plan0 = strategy.speculative_scoring_plan()
        except Exception:  # noqa: BLE001 - speculation must never kill a run
            self.logger.exception("round pipeline: speculative plan failed; "
                                  "round runs sequential")
            return False
        if not plan0:
            return False
        idxs = np.asarray(plan0["idxs"])
        if idxs.size == 0:
            return False
        batch_size = strategy._score_batch_size()
        # Built on THIS thread so the lazy per-strategy step dict never
        # mutates concurrently.
        step_fn = strategy._get_score_step(plan0["kind"])
        loader = strategy.train_cfg.loader_te
        plan = {
            "round": int(round_idx),
            "kind": plan0["kind"],
            # None = every step output (MASE reads all three); collect_
            # pool treats None the same way, so plan and pass agree.
            "keys": (tuple(plan0["keys"])
                     if plan0.get("keys") is not None else None),
            "idxs": idxs,
            "batch_size": int(batch_size),
            "slices": scoring.chunk_row_slices(len(idxs), batch_size,
                                               SPEC_CHUNK_BATCHES),
            "dataset": strategy.al_set,
            "mesh": strategy.mesh,
            "step_fn": step_fn,
            "num_workers": loader.num_workers,
            "prefetch": loader.prefetch,
        }
        self._watcher = ckpt_lib.BestCkptWatcher(
            strategy.weight_paths()["dir"])
        # The newest file on disk right now is a PREVIOUS round's best
        # (or a resumed attempt's — either way superseded the moment
        # this round's fit publishes): mark it seen so the first disk
        # poll doesn't deserialize a full checkpoint just to discard it
        # by round.  The in-process on_best leg still delivers every
        # new best instantly.
        self._watcher.prime()
        # XLA:CPU reorders execution behind the enqueue order, so for
        # the window the scorer thread shares the mesh every dispatch
        # must COMPLETE before its gate releases (mesh_lib.DispatchGate;
        # observed cross-thread AllReduce deadlock without it).  TPU
        # cores execute enqueued programs FIFO — the enqueue lock alone
        # is the contract there, and the async train stream stays async.
        if plan["mesh"].devices.flat[0].platform == "cpu":
            strategy.trainer.dispatch_lock.drain_mode = True
        with self._cv:
            self._plan = plan
            self._consumed = False
            self._cv.notify_all()
        self._ensure_thread()
        return True

    def publish_best(self, round_idx: int, epoch: int, variables) -> None:
        """Trainer-side publish (Trainer.fit's ``on_best``): a new best
        snapshot exists on device.  The scorer restarts from scratch —
        every previously scored chunk depended on the superseded
        weights.  Cheap and sync-free: one lock, no device work."""
        with self._cv:
            plan = self._plan
            if plan is None or plan["round"] != round_idx or self._consumed:
                return
            self._src = ((int(round_idx), int(epoch)), variables)
            self.stats["publishes"] += 1
            self._cv.notify_all()

    def finalize(self, round_idx: int, best_epoch: int) -> None:
        """The fit ended: pin the FINAL (round, best_epoch) tag.  Chunks
        scored from any other tag are dead; chunks from the final tag
        keep accumulating (the scorer keeps running through
        load_best_ckpt/test/save — more overlap) until ``consume``."""
        with self._cv:
            if self._plan is None or self._plan["round"] != round_idx:
                return
            self._final_tag = (int(round_idx), int(best_epoch))
            self._cv.notify_all()

    def join_prefetch(self) -> None:
        """Wait out the select-time prefetch thread.  Strategy.train
        calls this before EVERY fit: arm() joins it too, but the last
        round never arms, and a prefetch left running into that round's
        fit would race it on the trainer's lazily-built jitted forms
        (both sides seeing None and compiling twice)."""
        self._join_prefetch()

    def take_busy_s(self) -> float:
        """Scorer-thread busy seconds since the last take — the 'score'
        stream's contribution to the driver's overlap_frac."""
        with self._cv:
            busy, self._busy_s = self._busy_s, 0.0
        return busy

    def disarm(self, wait_s: float = 60.0) -> None:
        """Quiesce the scorer for THIS round without killing the thread
        (the degradation ladder's pipeline_off rung: the retried round
        runs sequentially, the NEXT round may re-arm).  Kills the plan,
        waits out any in-flight chunk, releases the CPU-mesh drain, and
        joins the prefetch thread.

        The in-flight wait is BOUNDED: disarm is the recovery path, and
        a scorer wedged mid-chunk (a stuck collective — possibly the
        very stall being healed) would otherwise hang it forever.  On
        expiry the chunk is abandoned loudly — its thread may still
        complete later, but the dead plan means nothing consumes it."""
        deadline = time.monotonic() + wait_s
        with self._cv:
            self._plan = None
            self._consumed = True
            self._cv.notify_all()
            while self._in_flight is not None:
                if self._thread is None or not self._thread.is_alive():
                    self._in_flight = None
                    break
                if time.monotonic() >= deadline:
                    self.logger.warning(
                        "round pipeline: disarm abandoned an in-flight "
                        "speculative chunk still running after "
                        f"{wait_s:.0f}s (wedged scorer); the round "
                        "proceeds sequentially")
                    self._in_flight = None
                    break
                self._cv.wait(timeout=1.0)
        self._strategy.trainer.dispatch_lock.drain_mode = False
        self._join_prefetch()
        try:
            tele_runtime.get_run().tick(spec_phase="idle")
        except Exception:  # noqa: BLE001 - best-effort heartbeat
            pass

    def shutdown(self) -> None:
        with self._cv:
            self._stop = True
            self._consumed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=120.0)
            self._thread = None
        self._join_prefetch()
        self._strategy.trainer.dispatch_lock.drain_mode = False

    # -- query-time hand-over (strategy-facing) ---------------------------

    def consume(self, kind: str, keys, idxs: np.ndarray, batch_size: int,
                variables) -> Optional[Dict[str, np.ndarray]]:
        """Hand the speculative scores to the sampler, completing any
        missing or invalidated chunk INLINE with the query-time weights
        (``variables`` — the final best checkpoint ``load_best_ckpt``
        installed).  Returns None when the request doesn't match the
        armed plan (rng-shuffled idxs, different statistic, no plan):
        the caller then runs the ordinary sequential pass.  Either way
        the scorer stops burning mesh time for this round, and the
        select-time prefetch is kicked off — selection runs next."""
        with self._cv:
            plan = self._plan
            req_keys = tuple(keys) if keys is not None else None
            matched = (
                plan is not None and not self._consumed
                and kind == plan["kind"]
                and req_keys == plan["keys"]
                and int(batch_size) == plan["batch_size"]
                and len(idxs) == len(plan["idxs"])
                and bool(np.array_equal(np.asarray(idxs), plan["idxs"])))
            if plan is not None and not self._consumed and not matched:
                self.stats["plan_misses"] += 1
            self._consumed = True
            self._cv.notify_all()
            # Hit or miss, the scorer takes no NEW jobs now (consumed);
            # wait out any in-flight chunk BEFORE releasing the CPU-mesh
            # execution drain — on a miss the caller dispatches the
            # sequential pass immediately, and doing that concurrently
            # with the chunk's collectives un-drained is exactly the
            # cross-thread deadlock the drain exists to prevent.  A DEAD
            # scorer thread (injected ThreadDeath, a hard crash) can
            # never finish its chunk: its death harness clears
            # _in_flight, and the liveness check below bounds the wait
            # even if the harness itself was killed — a dead thread must
            # cost a recompute, never a hang.
            while self._in_flight is not None:
                if self._thread is None or not self._thread.is_alive():
                    self._in_flight = None
                    break
                self._cv.wait(timeout=1.0)
            # The scorer thread is idle for good now (consumed + no
            # in-flight): single-threaded dispatch no longer needs the
            # execution drain.
            self._strategy.trainer.dispatch_lock.drain_mode = False
            if not matched:
                # The scorer's stream ends un-served: mark its heartbeat
                # track idle (a stale spec_phase=score would otherwise
                # merge into every later heartbeat) and still prefetch —
                # selection runs next either way.
                tele_runtime.get_run().tick(spec_phase="idle")
                self._start_prefetch()
                return None
            final = self._final_tag
            done = {}
            stale = 0
            for i, (tag, out, dt) in self._done.items():
                if final is not None and tag == final:
                    done[i] = (out, dt)
                else:
                    stale += 1
            # Chunks scored under a superseded tag are invalidated no
            # matter WHO notices first: if the scorer thread never woke
            # between the late publish and this consume (it had already
            # finished every chunk under the early tag), the dropped
            # entries would otherwise vanish uncounted —
            # chunks_invalidated read 0 after a forced late-best
            # invalidation, a scheduling-dependent accounting hole
            # (_next_job_locked's cleanup counts the same supersession
            # when the worker DOES wake first; both paths remove what
            # they count, so they can never double-count).
            self.stats["chunks_invalidated"] += stale
            slices = list(plan["slices"])
            self._done = {}
        outs: List[Dict[str, np.ndarray]] = []
        hits = inline = 0
        # Scoring COMPUTE seconds behind this hand-over (served chunks'
        # scorer-thread walls + the inline completions here): what the
        # pool_rows_per_sec the sequential pass would have reported
        # actually cost, even though most of it was hidden in the fit.
        score_s = 0.0
        for i, sl in enumerate(slices):
            if i in done:
                out, dt = done[i]
                outs.append(out)
                score_s += dt
                hits += 1
            else:
                t0 = time.perf_counter()
                outs.append(self._score_slice(plan, sl, variables))
                score_s += time.perf_counter() - t0
                inline += 1
        result = scoring.splice_chunks(outs)
        # The experiment-truth layer's chunked histogram (DESIGN.md
        # §13): per-chunk partials summed HERE, at consume — the merged
        # sum is bit-equal to one add over the spliced result (integer
        # bin counts; pinned in tests/test_diagnostics.py), so the
        # strategy records the histogram without re-walking the scores.
        diag = self._strategy.diagnostics
        score_hist = None
        if diag is not None and outs:
            key = diag_lib.primary_score_key(outs[0])
            if key is not None:
                score_hist = {key: diag_lib.histogram_from_chunks(
                    key, [c[key] for c in outs])}
        # Under the lock like every other stats mutation: the worker's
        # death harness can still increment chunks_failed concurrently
        # with this hand-over (found by the lock-discipline checker —
        # a bare += here is a read-modify-write race with that thread).
        with self._cv:
            self.stats["chunks_hit"] += hits
            self.stats["chunks_inline"] += inline
        self.last_consume = {"chunks": len(slices), "hits": hits,
                             "inline": inline,
                             "hit_frac": round(hits / max(1, len(slices)),
                                               4),
                             "score_s": score_s,
                             "score_hist": score_hist}
        self.logger.info(
            f"round pipeline: speculative scores served "
            f"{hits}/{len(slices)} chunks (inline-completed {inline})")
        # The scorer's stream is over for this round: mark its heartbeat
        # track idle so `status` stops reporting a second active phase.
        tele_runtime.get_run().tick(spec_phase="idle")
        self._start_prefetch()
        return result

    # -- the scorer thread -------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._worker,
                                            name="al-spec-scorer",
                                            daemon=True)
            self._thread.start()

    def _worker(self) -> None:
        """Thread entry: the loop plus the death harness.  An exception
        the loop's own guards don't catch — injected ThreadDeath
        (faults.site("spec_scorer")'s ``die`` action), a MemoryError, a
        bug — must not orphan the round: the plan is killed, any
        in-flight marker cleared (consume()'s wait would otherwise hang
        on a chunk that will never finish), the CPU-mesh execution drain
        released, and the heartbeat's scorer track idled.  The round
        then completes sequentially — a dead scorer costs wall-clock,
        never a score and never a hang."""
        try:
            self._worker_loop()
        except BaseException:  # noqa: BLE001 - thread-death harness
            self.logger.exception(
                "round pipeline: speculative scorer thread died; the "
                "round completes sequentially")
            with self._cv:
                self.stats["chunks_failed"] += 1
                self._in_flight = None
                self._plan = None
                self._cv.notify_all()
            self._strategy.trainer.dispatch_lock.drain_mode = False
            try:
                tele_runtime.get_run().tick(spec_phase="idle")
            except Exception:  # noqa: BLE001 - already on the death path
                pass

    def _worker_loop(self) -> None:
        """The scoring executor loop: take the lowest pending chunk for
        the current source checkpoint, score it, store it under its tag.
        Never touches the train stream's arrays (trace_lint check 7) —
        waiting happens on its OWN chunk outputs inside collect_pool's
        host fetch, which blocks only this thread."""
        tele_spans.get_tracer().name_thread("spec-scorer")
        rt = tele_runtime.get_run()
        while True:
            job = None
            need_poll = False
            with self._cv:
                if self._stop:
                    return
                job = self._next_job_locked()
                if job is None:
                    need_poll = (self._plan is not None
                                 and not self._consumed
                                 and self._src is None
                                 and self._final_tag is None)
                    if not need_poll:
                        # Idle: every state transition (arm, publish,
                        # finalize, consume, shutdown) notifies; the
                        # timeout is only a lost-notify backstop, not a
                        # poll cadence.
                        self._cv.wait(timeout=5.0)
                        continue
            if need_poll:
                self._poll_disk()
                with self._cv:
                    # Sleep the poll period ON the condition, so an
                    # in-process publish still wakes the scorer
                    # instantly instead of after the disk cadence.
                    if (not self._stop and self._plan is not None
                            and not self._consumed and self._src is None
                            and self._final_tag is None):
                        self._cv.wait(timeout=WATCH_POLL_S)
                continue
            chunk_i, sl, tag, variables, plan = job
            try:
                out, dt = self._score_chunk(plan, sl, tag, variables,
                                            chunk_i)
            except Exception:  # noqa: BLE001 - speculation is best-effort
                self.logger.exception(
                    "round pipeline: speculative chunk failed; disabling "
                    "speculation for this round")
                with self._cv:
                    self.stats["chunks_failed"] += 1
                    self._in_flight = None
                    self._plan = None
                    self._cv.notify_all()
                # Dead plan = dead scorer for the round: release the
                # CPU-mesh execution drain (the fit would otherwise pay
                # a sync per dispatch for a sharing window that no
                # longer exists) and clear the heartbeat track so
                # `status` stops reporting a scorer that will never run
                # again this round.
                self._strategy.trainer.dispatch_lock.drain_mode = False
                rt.tick(spec_phase="idle")
                continue
            with self._cv:
                self._busy_s += dt
                self._in_flight = None
                # Store even when consume() flagged the plan consumed
                # while this chunk was in flight: consume waits for
                # in-flight to clear BEFORE snapshotting _done, so a
                # just-finished valid chunk still lands as a hit.
                if self._plan is plan:
                    current = self._src[0] if self._src else None
                    valid = (tag == self._final_tag
                             if self._final_tag is not None
                             else tag == current)
                    if valid:
                        self._done[chunk_i] = (tag, out, dt)
                        self.stats["chunks_scored"] += 1
                    else:
                        self.stats["chunks_invalidated"] += 1
                # Tick INSIDE the lock, and only while the plan is still
                # live: consume()'s spec_phase="idle" tick runs after a
                # _cv section ordered AFTER this one, so a stale "score"
                # tick can never land on top of it and merge-persist a
                # phantom active scorer into every later heartbeat.
                if self._plan is plan and not self._consumed:
                    rt.tick(spec_phase="score", spec_chunk=chunk_i,
                            spec_round=tag[0])
                self._cv.notify_all()

    def _next_job_locked(self):
        plan = self._plan
        if plan is None or self._consumed:
            return None
        src = self._src
        if src is None:
            return None
        tag, variables = src
        if self._final_tag is not None and tag != self._final_tag:
            # The source the scorer holds is NOT the final best (e.g. a
            # publish raced the end of fit, or no final-tag source ever
            # arrived): nothing it could score would survive
            # invalidation, so stop here and let consume() complete
            # inline with the query-time weights.
            return None
        # A newer source invalidates everything scored under older tags
        # — "restart from the changed chunks", which for pool scores
        # (a global function of the checkpoint) is all of them.
        for i in [i for i, (t, _out, _dt) in self._done.items()
                  if t != tag]:
            del self._done[i]
            self.stats["chunks_invalidated"] += 1
        for i in range(len(plan["slices"])):
            if i not in self._done:
                self._in_flight = i
                return i, plan["slices"][i], tag, variables, plan
        return None

    def _poll_disk(self) -> None:
        """The disk leg of the best-ckpt bus (the serve executor's
        hot-reload pattern, shared via BestCkptWatcher): used only while
        no in-process publish has arrived for the armed round."""
        now = time.monotonic()
        if now - self._last_poll < WATCH_POLL_S or self._watcher is None:
            return
        self._last_poll = now
        try:
            polled = self._watcher.poll()
        except Exception as exc:  # noqa: BLE001 - classified below
            # The unified classification (faults.classify_exception)
            # instead of a blanket swallow: a transient FS error (NFS
            # hiccup, racing rename) just waits for the next poll; a
            # non-transient one disables THIS plan's disk leg loudly —
            # the in-process publish leg still delivers every best.
            if faults.classify_exception(exc) == faults.FATAL:
                self.logger.exception(
                    "round pipeline: best-ckpt disk poll failed "
                    "(non-transient); disk leg disabled for this round")
                self._watcher = None
            return
        if polled is None:
            return
        variables, rd, tag = polled
        with self._cv:
            plan = self._plan
            if (plan is None or self._consumed or self._src is not None
                    or tag is None or tag[0] != plan["round"]):
                return
            mesh = plan["mesh"]
        from ..parallel import mesh as mesh_lib
        dev_vars = mesh_lib.replicate(variables, mesh)
        with self._cv:
            if (self._plan is plan and not self._consumed
                    and self._src is None):
                self._src = (tag, dev_vars)
                self.stats["publishes"] += 1
                self._cv.notify_all()

    def _score_slice(self, plan: Dict[str, Any], sl: slice, variables
                     ) -> Dict[str, np.ndarray]:
        """One chunk through the SAME engine the sequential pass uses —
        collect_pool over a batch-aligned row slice is bit-identical to
        the same batches of the monolithic call.  Resident kwargs are
        re-resolved per call (the budget may have been refreshed at a
        round boundary); the dispatch lock is the trainer's, so chunk
        enqueues interleave train/eval steps in one global order."""
        strategy = self._strategy
        return scoring.collect_pool(
            plan["dataset"], plan["idxs"][sl], plan["batch_size"],
            plan["step_fn"], variables, plan["mesh"],
            num_workers=plan["num_workers"], prefetch=plan["prefetch"],
            keys=plan["keys"],
            dispatch_lock=strategy.trainer.dispatch_lock,
            **strategy._resident_kwargs())

    def _score_chunk(self, plan, sl, tag, variables, chunk_i: int):
        # The scorer thread's fault point: `raise` exercises the
        # disable-speculation-for-the-round path, `die` the thread-death
        # harness in _worker — both recover to sequential scoring.
        faults.site("spec_scorer")
        gate = self._strategy.trainer.dispatch_lock
        gate.take_wait_s()  # drop waits accrued outside this chunk
        t0 = time.perf_counter()
        out = self._score_slice(plan, sl, variables)
        t1 = time.perf_counter()
        # Busy = chunk wall minus this thread's time blocked on the
        # dispatch gate (the train stream held it): gate waits are idle,
        # not scoring compute, and counting them would overstate both
        # the overlap accounting and pool_rows_per_sec.
        busy = max(0.0, (t1 - t0) - gate.take_wait_s())
        tele_spans.get_tracer().complete(
            "spec_score_chunk", t0, t1,
            args={"chunk": chunk_i, "round": tag[0], "src_epoch": tag[1],
                  "rows": int(sl.stop - sl.start)})
        return out, busy

    # -- select-time train prefetch ---------------------------------------

    def _start_prefetch(self) -> None:
        self._join_prefetch()
        # Snapshot the pool views on THIS (the query) thread, where the
        # pool is still pre-update: the driver calls strategy.update the
        # moment query returns, and a thread reading num_labeled after
        # that would size the coming fit round_budget rows too large
        # (and read the labeled mask mid-mutation).
        strategy = self._strategy
        try:
            labeled_now = strategy.pool.labeled_idxs()
            expected = strategy.pool.num_labeled + min(
                int(strategy.cfg.round_budget), strategy.pool.num_available)
        except Exception:  # noqa: BLE001 - prefetch is best-effort
            self.logger.exception("round pipeline: train-feed prefetch "
                                  "skipped (pool view failed)")
            return
        t = threading.Thread(target=self._prefetch,
                             args=(labeled_now, expected),
                             name="al-feed-prefetch", daemon=True)
        self._prefetch_thread = t
        t.start()

    def _join_prefetch(self) -> None:
        t = self._prefetch_thread
        if t is not None and t.is_alive():
            t.join(timeout=120.0)
        self._prefetch_thread = None

    def _prefetch(self, labeled_now: np.ndarray, expected: int) -> None:
        """Warm the coming fit's feed while selection runs on the main
        thread (Trainer.prepare_next_fit) — rng-free, best-effort.  The
        pool views arrive as arguments, snapshotted by _start_prefetch
        before the driver's strategy.update can race them."""
        tele_spans.get_tracer().name_thread("feed-prefetch")
        strategy = self._strategy
        t0 = time.perf_counter()
        try:
            feed = strategy.trainer.prepare_next_fit(
                strategy.train_set, labeled_now, expected)
        except Exception:  # noqa: BLE001 - prefetch is best-effort
            self.logger.exception("round pipeline: train-feed prefetch "
                                  "failed (fit resolves from scratch)")
            return
        tele_spans.get_tracer().complete(
            "train_feed_prefetch", t0, time.perf_counter(),
            args={"feed": feed, "expected_labeled": expected})
