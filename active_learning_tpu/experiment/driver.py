"""The experiment driver: build everything from an ExperimentConfig and run
the active-learning round loop.

TPU-native counterpart of ``main(args)`` (src/main_al.py:43-184).  The loop
body is the reference's, verb for verb:

    for rd in start_round..rounds:
        query -> update          [skipped at rd 0 unless init_pool_size==0]
        init_network_weights     (random re-init, then SSL overlay)
        train                    (per-round fit with early stopping)
        load_best_ckpt
        test
        save_experiment

Differences by design: ONE persistent JAX runtime/mesh across all rounds (no
per-round mp.spawn, strategy.py:288-315), typed configs instead of
argparse+exec, and a JSONL metrics sink instead of Comet — with the same
metric names (main_al.py:24-40).
"""

from __future__ import annotations

import copy
import dataclasses
import os
import time
import uuid
import zlib
from datetime import date
from typing import Optional, Tuple

import jax
import numpy as np

from .. import faults
from ..config import ExperimentConfig, TrainConfig, config_to_dict
from ..data import get_data
from ..faults import ladder as ladder_lib
from ..faults import preempt as preempt_lib
from ..initial_pool import generate_eval_idxs, generate_init_lb_idxs
from ..models.factory import get_network
from ..parallel import mesh as mesh_lib
from ..pool import PoolState
from ..strategies import get_strategy
from ..telemetry import diagnostics as diag_lib
from ..telemetry import profiler as tele_profiler
from ..telemetry import runtime as tele_runtime
from ..telemetry import spans as tele_spans
from ..train import checkpoint as ckpt_lib
from ..utils.logging import get_logger, setup_logging
from ..utils.metrics import MetricsSink, make_sink
from ..utils.tracing import phase_timer
from ..train.trainer import Trainer
from . import arg_pools as arg_pools_lib
from . import pipeline as pipeline_lib
from . import resume as resume_lib


def _platform_is_cpu() -> bool:
    """True when the configured JAX platform list names cpu first —
    WITHOUT initializing a backend (this runs before the multi-host
    rendezvous on some call paths).  Unset platform config reads as
    not-CPU: accelerator machines rarely set it, CPU test/smoke
    environments always do (conftest, the tier-1 recipe, bench's CPU
    children)."""
    spec = (os.environ.get("JAX_PLATFORMS") or "")
    try:
        spec = jax.config.jax_platforms or spec
    except AttributeError:  # pragma: no cover - very old jax
        pass
    first = spec.split(",")[0].strip().lower() if spec else ""
    return first == "cpu"


def enable_compilation_cache(cache_dir: Optional[str] = None
                             ) -> Optional[str]:
    """Turn on JAX's persistent (on-disk) compilation cache for the whole
    process, so AL round N+1 — and the next RUN of the same protocol —
    reuse round N's compiled executables instead of re-paying the
    cold-compile tax (measured ~58 s of the cold/warm round gap on the
    CIFAR protocol, BENCH r5).  Shape bucketing (pool.bucket_size in the
    trainer and k-center) keeps the keys stable as the labeled set grows;
    this cache keeps the hits across process restarts.

    ``cache_dir``: None -> $JAX_COMPILATION_CACHE_DIR or
    ~/.cache/al_tpu_xla_cache; "" disables.  Returns the directory in
    use, or None when disabled/unavailable (old jax without the config
    knobs — the run proceeds uncached, never fails).

    CPU backends get NO cache by default: jax 0.4.37's CPU runtime
    corrupts donated buffers when an executable is deserialized from the
    persistent cache (a donate_argnums jit re-jitted in-process dies
    with heap corruption or silently computes on freed memory — the
    root cause of the once-flaky mid-round-resume tests).  Compiles are
    cheap on CPU anyway; an EXPLICIT choice — the cache_dir argument OR
    $JAX_COMPILATION_CACHE_DIR — still enables it (both are deliberate
    operator opt-ins), and accelerators are unaffected.
    """
    if cache_dir == "":
        return None
    # The env var is an explicit operator opt-in, same as the flag — it
    # must be resolved BEFORE the CPU gate, which suppresses only the
    # implicit ~/.cache default.
    cache_dir = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not cache_dir and _platform_is_cpu():
        get_logger().info(
            "persistent compilation cache disabled on the CPU backend "
            "(jax 0.4.37 corrupts donated buffers in cache-deserialized "
            "executables); pass --compilation_cache_dir or set "
            "$JAX_COMPILATION_CACHE_DIR to force it")
        return None
    cache_dir = (cache_dir
                 or os.path.join(os.path.expanduser("~"), ".cache",
                                 "al_tpu_xla_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Sub-second compiles aren't worth a disk entry; everything else
        # is (the round tax is dominated by a handful of large modules).
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # pragma: no cover - jax-version-dependent
        get_logger().warning(
            f"persistent compilation cache unavailable ({e!r}); "
            "continuing without it")
        return None
    return cache_dir


# The int8 gradient sync's pinned accuracy-delta bound: the probe model
# trained through the quantized step must land within this much test
# accuracy of its bit-exact f32 twin (same seeds, same data) or the run
# degrades to f32.  Pinned by tests/test_backward.py.
INT8_PROBE_MAX_ACC_DELTA = 0.05


def run_grad_allreduce_probe(mesh, mode: str = "int8"
                             ) -> Tuple[bool, Optional[float]]:
    """The multichip learning probe gating the quantized gradient sync
    (DESIGN.md §4 + §15): train one tiny probe model twice over the
    live mesh — once through the bit-exact f32 step, once through the
    quantized-sync step EXACTLY as the run would build it (``mode``
    is the run's requested grad_allreduce, so the Trainer resolves the
    same wire form: the all-gather int8 sync on 2-8 device meshes, the
    pod-tier reduce-scatter form above the crossover or under
    ``int8_rs``), same seeds — and compare test accuracy.  The
    same prove-it-learns discipline as ``__graft_entry__``'s dryrun
    gate: a subtly wrong quantized reduction keeps params finite and
    loss moving while computing the wrong numbers; only an accuracy
    comparison catches it.  Returns ``(ok, delta)``; any probe failure
    reads as not-ok (the caller degrades to f32 loudly, never crashes
    the run for an optional optimization)."""
    try:
        # Chaos seam (tests/test_faults.py): an injected failure here is
        # exactly a broken probe — the run must degrade to f32, loudly.
        faults.site("grad_probe")

        import dataclasses as _dc

        import flax.linen as nn
        import jax.numpy as jnp

        from ..config import (LoaderConfig, OptimizerConfig,
                              SchedulerConfig)
        from ..data.synthetic import get_data_synthetic

        class _Probe(nn.Module):
            """Minimal SSLClassifier-interface model for the gate."""

            num_classes: int = 4
            feat_dim: int = 32
            freeze_feature: bool = False

            @nn.compact
            def __call__(self, x, train: bool = True,
                         return_features: bool = False):
                emb = x.reshape((x.shape[0], -1)).astype(jnp.float32)
                emb = nn.tanh(nn.Dense(self.feat_dim, name="proj")(emb))
                logits = nn.Dense(self.num_classes, name="linear")(emb)
                return (logits, emb) if return_features else logits

        data = get_data_synthetic(n_train=96, n_test=128, num_classes=4,
                                  image_size=16, seed=7)
        base_cfg = TrainConfig(
            eval_split=0.1, loader_tr=LoaderConfig(batch_size=16),
            loader_te=LoaderConfig(batch_size=16),
            optimizer=OptimizerConfig(name="sgd", lr=0.3),
            scheduler=SchedulerConfig(name="cosine", t_max=8),
            resident_scoring_bytes=0)

        def fit_acc(ar_mode: str) -> float:
            trainer = Trainer(_Probe(),
                              _dc.replace(base_cfg,
                                          grad_allreduce=ar_mode),
                              mesh, num_classes=4)
            # The probe fits on the DETERMINISTIC (al) view: the int8
            # step decorrelates per-shard augmentation keys, so an
            # augmented view would compare two different data streams
            # and the delta would measure augmentation luck, not
            # quantization.  On the template+noise synthetic both paths
            # saturate (~100%); a broken quantized reduction does not.
            state = trainer.init_state(
                jax.random.PRNGKey(1),
                data[2].gather(np.zeros(1, dtype=np.int64)))
            result = trainer.fit(
                state, data[2], np.arange(len(data[2])), data[2],
                np.array([], dtype=np.int64), n_epoch=8, es_patience=0,
                rng=np.random.default_rng(1))
            metrics = trainer.evaluate(result.state, data[1],
                                       np.arange(len(data[1])))
            return float(metrics["accuracy"])

        delta = round(abs(fit_acc("f32") - fit_acc(mode)), 4)
        return delta <= INT8_PROBE_MAX_ACC_DELTA, delta
    except (Exception, faults.ThreadDeath) as e:  # noqa: BLE001
        # Degrade, never crash: ThreadDeath included deliberately — the
        # probe runs on the MAIN thread, where an injected
        # grad_probe:die would otherwise kill the whole run instead of
        # the f32 fallback this site's contract promises.
        get_logger().warning(f"grad_allreduce probe failed to run: {e!r}")
        return False, None


def build_experiment(
    cfg: ExperimentConfig,
    sink: Optional[MetricsSink] = None,
    data=None,
    mesh=None,
    train_cfg: Optional[TrainConfig] = None,
    model=None,
    skip_init_pool: bool = False,
):
    """Wire the full stack (data -> model -> mesh -> trainer -> pool ->
    strategy) from one config (main_al.py:48-120).

    ``data`` (a (train_set, test_set, al_set) triple), ``mesh``,
    ``train_cfg`` and ``model`` can be injected for tests and benchmarks.
    ``skip_init_pool`` is set on resume: the restored pool replaces the
    init pool, so labeling one here would emit a stale round-0 metric and
    rewrite the round-0 audit asset.
    """
    if train_cfg is None:
        train_cfg = arg_pools_lib.get_train_config(
            cfg.arg_pool, cfg.dataset, pretrained_root=cfg.pretrained_root)
    if data is None:
        # Pass the ImbalanceConfig itself: the dataset factories read it
        # by attribute (a dict here crashed every config-driven
        # imbalanced run with AttributeError).
        data = get_data(cfg.dataset, data_path=cfg.dataset_dir,
                        debug_mode=cfg.debug_mode,
                        imbalance_args=cfg.imbalance,
                        download=cfg.download_data)
    train_set, test_set, al_set = data
    # Disk datasets with deterministic views get the experiment-lifetime
    # decode-once memmap cache: every acquisition round re-scores the full
    # pool and every round re-evaluates the full test set, so decode —
    # ~30x slower than device scoring on ImageNet trees — must be paid
    # once, not per round (data/cache.DecodedPoolCache).
    from ..data.cache import DecodedPoolCache, maybe_wrap_decoded

    # Default under ~/.cache, NOT tempfile.gettempdir(): /tmp is commonly
    # tmpfs, where a multi-GB "disk" memmap would silently consume host
    # RAM past every configured RAM budget.
    cache_dir = (train_cfg.decoded_cache_dir
                 or os.path.join(os.path.expanduser("~"), ".cache",
                                 "al_tpu_decoded"))
    budget = train_cfg.cache_decoded_bytes
    al_set = maybe_wrap_decoded(al_set, cache_dir, budget)
    if isinstance(al_set, DecodedPoolCache):
        # One byte budget bounds the DIRECTORY, not each wrap: the test
        # set only caches into what the al pool left.
        budget -= len(al_set) * int(np.prod(al_set.image_shape))
    if test_set is not None:
        test_set = maybe_wrap_decoded(test_set, cache_dir, budget)
    num_classes = al_set.num_classes

    if model is None:
        # --dtype/--stem/--bn_stats_dtype beat the arg pool's TrainConfig;
        # "auto" dtype lands on bfloat16 when the live backend is TPU, and
        # auto BN stats follow the compute dtype (models/factory.py).
        model = get_network(cfg.dataset, cfg.model,
                            freeze_feature=cfg.freeze_feature,
                            num_classes=num_classes,
                            dtype=cfg.dtype or train_cfg.dtype,
                            stem=cfg.stem or train_cfg.stem,
                            bn_stats_dtype=(cfg.bn_stats_dtype
                                            or train_cfg.bn_stats_dtype))
    if cfg.resident_scoring_bytes is not None:
        # --resident_scoring_bytes beats the arg pool: HBM sizing is a
        # per-chip deployment choice, not a dataset hyperparameter.  (The
        # arg-pool default is None = auto-size from live HBM headroom.)
        train_cfg = dataclasses.replace(
            train_cfg, resident_scoring_bytes=cfg.resident_scoring_bytes)
    if cfg.train_feed is not None:
        # --train_feed beats the arg pool for the same reason: which leg
        # of the feed hierarchy wins is a deployment/HBM question, and
        # every leg is bit-identical at the same seeds anyway.
        train_cfg = dataclasses.replace(train_cfg,
                                        train_feed=cfg.train_feed)
    if cfg.pool_sharding is not None:
        # --pool_sharding beats the arg pool: the resident layout is a
        # mesh/HBM deployment choice, and every layout is bit-identical
        # (scores, batches, picks) anyway.
        train_cfg = dataclasses.replace(train_cfg,
                                        pool_sharding=cfg.pool_sharding)
    if cfg.feed_workers is not None:
        train_cfg = dataclasses.replace(train_cfg,
                                        feed_workers=cfg.feed_workers)
    if cfg.pool_backend is not None:
        # --pool_backend beats the arg pool: which storage tier holds the
        # pool is a host-RAM deployment choice, and the disk backend is
        # bit-identical to memory by contract (DESIGN.md §16).
        train_cfg = dataclasses.replace(train_cfg,
                                        pool_backend=cfg.pool_backend)
    if cfg.fused_optimizer is not None:
        # --fused_optimizer beats the arg pool: bit-identical to optax
        # at f32 state, so this is a throughput/HBM deployment choice.
        train_cfg = dataclasses.replace(train_cfg,
                                        fused_optimizer=cfg.fused_optimizer)
    if cfg.optim_state_dtype is not None:
        train_cfg = dataclasses.replace(
            train_cfg, optim_state_dtype=cfg.optim_state_dtype)
    if cfg.grad_allreduce is not None:
        train_cfg = dataclasses.replace(train_cfg,
                                        grad_allreduce=cfg.grad_allreduce)
    if mesh is None:
        mesh = mesh_lib.make_mesh(cfg.num_devices)
    # Large-batch scaling (--scale_batch auto, DESIGN.md §15): as the
    # global batch grows with the mesh, the large-batch ConvNet rules
    # (train/optim.apply_batch_scaling — batch x ndev so the arg pool's
    # batch becomes per-chip, lr x ndev, >=5-epoch gradual warmup) keep
    # accuracy from silently eroding at pod-scale batch sizes.  Off by
    # default: the arg pool's batch stays the reference's GLOBAL batch.
    scale_mode = getattr(cfg, "scale_batch", None) or "off"
    if scale_mode not in ("auto", "off"):
        raise ValueError(
            f"scale_batch={scale_mode!r} is not one of 'auto'/'off'")
    if scale_mode == "auto":
        from ..train.optim import apply_batch_scaling
        train_cfg, scaled = apply_batch_scaling(train_cfg,
                                                mesh.devices.size)
        if scaled:
            get_logger().info(
                "scale_batch=auto: global batch "
                f"{train_cfg.loader_tr.batch_size} "
                f"({mesh.devices.size} devices x per-chip "
                f"{train_cfg.loader_tr.batch_size // mesh.devices.size}),"
                f" lr {train_cfg.optimizer.lr:g}, warmup "
                f"{train_cfg.scheduler.warmup_epochs} epochs "
                "(large-batch scaling rules)")
    # The quantized gradient sync is GATED, not just flagged
    # (DESIGN.md §4): int8 only engages when the mesh is multi-device
    # (resolve_grad_allreduce) AND the multichip learning probe passes —
    # a tiny probe model trained through the int8 step must match its
    # bit-exact-f32 twin's test accuracy within the pinned bound.  A
    # probe failure (or injected grad_probe fault) degrades the run to
    # f32 LOUDLY: logged here, journaled + metric'd by run_experiment
    # via trainer.grad_allreduce_degraded.
    grad_allreduce_degraded = False
    requested_ar = getattr(train_cfg, "grad_allreduce", "f32") or "f32"
    if mesh_lib.resolve_grad_allreduce(requested_ar, mesh) == "int8":
        wire = mesh_lib.resolve_int8_wire(requested_ar, mesh)
        ok, delta = run_grad_allreduce_probe(mesh, requested_ar)
        if not ok:
            get_logger().warning(
                f"grad_allreduce={requested_ar} ({wire} wire form) "
                "FAILED the multichip learning probe "
                f"(accuracy delta {delta if delta is not None else 'n/a'} "
                f"vs bound {INT8_PROBE_MAX_ACC_DELTA}); degrading this "
                "run to the bit-exact f32 gradient sync")
            train_cfg = dataclasses.replace(train_cfg, grad_allreduce="f32")
            grad_allreduce_degraded = True
        else:
            get_logger().info(
                f"grad_allreduce={requested_ar}: learning probe passed "
                f"on the {wire} wire form "
                f"(accuracy delta {delta} <= {INT8_PROBE_MAX_ACC_DELTA})")
    trainer = Trainer(model, train_cfg, mesh, num_classes)
    trainer.grad_allreduce_degraded = grad_allreduce_degraded

    # The disk tier (data/diskpool.py, DESIGN.md §16): pools bigger than
    # any host's RAM spill to demand-paged disk extents — auto-engaged
    # above the host-RAM watermark, forced with --pool_backend disk.
    # Only fully-decoded in-RAM pools are wrapped: DecodedPoolCache and
    # the stream service's StreamDataset are ALREADY disk/memmap-backed
    # (their ``images`` is an np.memmap — an ndarray subclass — so the
    # isinstance gate below must exclude it), and imperative-view
    # datasets never expose a whole-pool array to spill in the first
    # place.  On a multi-process mesh each host spills ONLY its own
    # mesh.shard_rows row range (process_pool_rows) — the full array
    # never lands on any one host's disk tier.
    from ..data import diskpool as diskpool_lib
    pool_images = getattr(al_set, "images", None)
    if (isinstance(pool_images, np.ndarray)
            and not isinstance(pool_images, np.memmap)):
        pool_bytes = len(al_set) * int(np.prod(al_set.image_shape))
        backend = diskpool_lib.resolve_pool_backend(
            getattr(train_cfg, "pool_backend", "auto") or "auto",
            pool_bytes,
            getattr(train_cfg, "pool_disk_watermark_frac", 0.5))
        if backend == "disk":
            local_rows = (mesh_lib.process_pool_rows(mesh, len(al_set))
                          if mesh_lib.is_multiprocess(mesh) else None)
            train_set, al_set = diskpool_lib.wrap_pool(
                train_set, al_set,
                os.path.join(cfg.log_dir, "disk_pool"),
                page_rows=train_cfg.pool_page_rows,
                host_cache_bytes=train_cfg.pool_host_cache_bytes,
                local_rows=local_rows)
            get_logger().info(
                f"pool_backend=disk: {pool_bytes / 1e9:.2f} GB pool "
                f"demand-paged from {cfg.log_dir}/disk_pool "
                f"(page_rows={train_cfg.pool_page_rows}, host cache "
                f"{train_cfg.pool_host_cache_bytes / 1e9:.2f} GB"
                + (f", local rows {local_rows.start}:{local_rows.stop}"
                   if local_rows is not None else "") + ")")

    targets = train_set.targets[: len(train_set)]
    init_pool_size = cfg.resolved_init_pool_size()
    if cfg.debug_mode:
        # Tiny fixed pools for smoke runs (main_al.py:87-92).
        init_idxs = (np.zeros(0, dtype=np.int64) if init_pool_size == 0
                     else np.arange(5, dtype=np.int64))
        eval_idxs = np.arange(15, 20, dtype=np.int64)
    else:
        eval_idxs = generate_eval_idxs(targets, num_classes,
                                       ratio=train_cfg.eval_split,
                                       random_seed=cfg.eval_split_seed)
        if init_pool_size == 0 or skip_init_pool:
            # On resume the restored pool replaces the init pool — skip the
            # (ImageNet-scale) balanced-index generation entirely.
            init_idxs = np.zeros(0, dtype=np.int64)
        else:
            init_idxs = generate_init_lb_idxs(
                targets, num_classes, eval_idxs, init_pool_size,
                init_pool_type=cfg.init_pool_type,
                random_seed=cfg.init_pool_seed)

    pool = PoolState.create(len(al_set), eval_idxs)
    rng = np.random.default_rng(cfg.run_seed)
    strategy_cls = get_strategy(cfg.strategy)
    strategy = strategy_cls(train_set, al_set, test_set, model, trainer,
                            pool, cfg, train_cfg, sink=sink, rng=rng)
    if not skip_init_pool:
        strategy.update(init_idxs, len(init_idxs))
    return strategy


# Every per-round metric the DRIVER emits through the MetricsSink, by
# name.  The Prometheus scrape file (--prometheus_file) must carry each
# of these as an ``al_run_`` gauge whenever the driver emitted it that
# round — the completeness contract tests/test_profiler.py diffs sink
# names against scrape samples with (the per-epoch trainer/strategy
# series — step_time, imgs_per_sec, spec_hit_frac — are per-EPOCH or
# strategy-owned and ride the heartbeat/status path instead).  The
# device-truth metrics (telemetry/profiler.RoundProfiler.emit_metrics)
# register dynamically the same way: sink + gauges from one dict.
# The experiment-truth diagnostics gauges (telemetry/diagnostics.py,
# DESIGN.md §13): score-distribution summary + inter-round drift,
# selection composition, k-center pick distances, calibration — emitted
# through _emit_round_gauges whenever the strategy's diagnostics layer
# produced them that round, and POPPED from the scrape gauges on any
# round that did not (the honesty rule reaches the scrape: a drift the
# current round could not compute must not linger looking current).
DIAGNOSTICS_GAUGES = (
    "rd_score_mean", "rd_score_std", "rd_score_drift_psi",
    "rd_score_drift_js", "rd_pick_class_balance", "rd_pick_novelty",
    "rd_pick_min_dist", "rd_pick_mean_dist", "rd_ece",
)

# The streaming service's per-round gauges (stream/service.py): ingest
# volume, WAL backlog, trigger accounting, and ack-latency percentiles.
# Flat names only — the per-cause trigger counters ride the
# ``name{label=value}`` labeled-gauge convention (telemetry/prom.
# gauge_samples) and are completeness-checked by tests/test_stream.py.
STREAM_GAUGES = (
    "ingest_rows_total", "ingest_labels_total", "pool_rows_total",
    "wal_backlog_rows", "rounds_triggered_total", "ingest_ack_ms_p50",
    "ingest_ack_ms_p99",
)

# The disk tier's paging gauges (data/diskpool.py, DESIGN.md §16):
# rows resident on disk, the host block cache's hit fraction, paging
# throughput, and the gather-observed page-in stall percentiles.
# Emitted only on rounds where the pool runs on the disk backend — the
# memory backend pops them from the scrape (None drops, the same
# honesty rule as the diagnostics gauges).
PAGING_GAUGES = (
    "pool_disk_rows", "pool_cache_hit_frac", "page_in_rows_per_sec",
    "page_in_stall_ms_p50", "page_in_stall_ms_p99",
)

PER_ROUND_GAUGES = (
    "rd_round_time", "overlap_frac", "round_vs_max_phase",
    "rd_spec_score_time", "jit_cache_miss_delta", "fault_retries_total",
    "degrade_events", "hbm_peak_gb",
) + DIAGNOSTICS_GAUGES + STREAM_GAUGES + PAGING_GAUGES


def _emit_round_gauges(telemetry, sink: MetricsSink, rd: int,
                       metrics: dict) -> None:
    """One dict -> BOTH channels: the metrics sink (per-round history)
    and the Prometheus gauges (latest-value scrape).  Emitting through
    one spelling is what makes the scrape-file completeness auditable —
    a metric added to one channel cannot silently miss the other."""
    numeric = {k: v for k, v in metrics.items() if v is not None}
    for name, value in numeric.items():
        sink.log_metric(name, value, step=rd)
    telemetry.set_gauges(**numeric)


def _emit_overlap_telemetry(telemetry, sink: MetricsSink, rd: int,
                            round_s: float, phase_s: dict,
                            spec_s: float, pipeline_mode: str) -> None:
    """The pipelined round's proof-of-overlap metrics, from the driver's
    OWN telemetry stream (bench reads these back rather than timing the
    loop again):

      rd_round_time       the round span's wall;
      overlap_frac        1 − round / (Σ phase walls + speculative-
                          scorer busy) — the fraction of serial-
                          equivalent work hidden by overlap (a
                          sequential round reads ~0);
      round_vs_max_phase  round / max(phase, spec) — 1.0 is the
                          theoretical floor (round == its longest
                          stream), the sum/max gap still on the table.
    """
    if not telemetry.train_metrics or not phase_s:
        return
    serial = sum(phase_s.values()) + spec_s
    longest = max(max(phase_s.values()), spec_s)
    if serial <= 0 or longest <= 0:
        return
    _emit_round_gauges(telemetry, sink, rd, {
        "rd_round_time": round(round_s, 3),
        "overlap_frac": round(max(0.0, 1.0 - round_s / serial), 4),
        "round_vs_max_phase": round(round_s / longest, 3),
        "rd_spec_score_time": (round(spec_s, 3)
                               if pipeline_mode != "off" else None),
    })


def _emit_round_telemetry(telemetry, sink: MetricsSink, rd: int,
                          strategy, ladder=None,
                          retries_baseline: int = 0) -> None:
    """Round-boundary telemetry: the jit-compile miss delta (round 0
    carries the cold tax; ANY nonzero delta after it is a shape leak —
    the test_compile_reuse regression, now visible in production
    metrics), the HBM high-water where the backend exposes
    memory_stats, the failure-model counters (fault_retries_total
    cumulative, degrade_events — DESIGN.md §10; bench rides both on the
    al_round phases), the Prometheus gauge refresh, and an incremental
    trace export so a crash mid-run still leaves trace.json on disk."""
    if not telemetry.train_metrics:
        return
    # The experiment-truth layer's round close-out (DESIGN.md §13):
    # drift vs the previous scored round, score summary, composition,
    # calibration — through the SAME one-dict-two-channels spelling as
    # every other per-round metric (the PER_ROUND_GAUGES completeness
    # contract covers them automatically).
    diag = getattr(strategy, "diagnostics", None)
    if diag is not None:
        diag_gauges = diag.finish_round(rd)
        _emit_round_gauges(telemetry, sink, rd, diag_gauges)
        # Any diagnostics gauge THIS round produced no value for is
        # popped from the scrape set (set_gauges drops on None): a
        # below-MIN_DRIFT_N round must retract last round's drift, not
        # let it scrape as current.
        stale = {k: None for k in DIAGNOSTICS_GAUGES
                 if diag_gauges.get(k) is None}
        if stale:
            telemetry.set_gauges(**stale)
    # Per-RUN retries: the process counter is cumulative across every
    # run/phase sharing this interpreter (bench runs many), so the
    # run-start baseline is subtracted — the al_round retries rider must
    # attribute only what the measured rounds absorbed.
    retries = faults.retry_counters()
    run_retries = retries["total"] - retries_baseline
    hbm = tele_runtime.hbm_high_water_gb()
    # Per-round history + latest-value gauges from ONE dict (the scrape
    # completeness contract, PER_ROUND_GAUGES).
    _emit_round_gauges(telemetry, sink, rd, {
        "jit_cache_miss_delta": telemetry.jit_cache_delta(),
        "fault_retries_total": run_retries,
        "degrade_events": ladder.events if ladder is not None else 0,
        "hbm_peak_gb": hbm,
    })
    # The disk tier's per-round paging accounting (PAGING_GAUGES):
    # take_round_stats drains and resets the counters, so each round's
    # numbers are that round's alone.  On the memory backend the
    # dataset has no disk tier and the gauges retract from the scrape
    # (None values drop, same as stale diagnostics).
    take_stats = getattr(strategy.al_set, "take_round_stats", None)
    paging = take_stats() if callable(take_stats) else {}
    _emit_round_gauges(telemetry, sink, rd,
                       {k: paging.get(k) for k in PAGING_GAUGES})
    stale_paging = {k: None for k in PAGING_GAUGES
                    if paging.get(k) is None}
    if stale_paging:
        telemetry.set_gauges(**stale_paging)
    # Feed-boundedness gauges from the round's fit (trainer.last_feed):
    # a host-bound warm round reads off the Prometheus scrape / `status`
    # without a profiler.  feed_source is non-numeric, so it rides the
    # heartbeat detail instead (the trainer ticks `feed=` every epoch;
    # `status` renders it).  The span-buffer drop counter rides here
    # too: a capped trace silently truncates evidence, and the only
    # place that shows is the tracer's own counter — nonzero
    # al_run_span_events_dropped on a scrape means trace.json is no
    # longer the whole story.
    feed = strategy.trainer.last_feed
    telemetry.set_gauges(
        round=rd, cumulative_budget=strategy.pool.cumulative_cost,
        labeled=strategy.pool.num_labeled,
        jit_cache_total=telemetry.jit_cache_total(),
        degrade_active=(len(ladder.active) if ladder is not None else 0),
        feed_stall_frac=feed.get("feed_stall_frac"),
        host_wait_ms_p50=feed.get("host_wait_ms_p50"),
        span_events_dropped=tele_spans.get_tracer().dropped)
    telemetry.write_prometheus()
    telemetry.export_trace()
    telemetry.tick(force=True, phase="round_end", round=rd)


def _labeled_crc(pool: PoolState) -> int:
    """CRC of the labeled mask — the round journal's cheap labeled-set
    digest (a resume/retry that diverged would show a different CRC at
    the same round, without dumping 1.2M indices into a JSON file)."""
    return int(zlib.crc32(np.ascontiguousarray(pool.labeled).tobytes()))


def _round_snapshot(strategy) -> dict:
    """Everything a ROUND mutates, captured at its start so a failed
    attempt can be rolled back and retried bit-identically (the
    degradation ladder, DESIGN.md §10): pool state, the host rng chain,
    the per-experiment init key, and a host copy of the model variables
    (round r's query scores with round r-1's best weights — re-running
    the query without restoring them would score with the failed
    attempt's re-initialized model)."""
    variables = None
    if strategy.state is not None:
        variables = jax.tree.map(np.asarray, strategy.state.variables)
    return {
        "pool": strategy.pool.to_arrays(),
        "rng_state": copy.deepcopy(strategy.rng.bit_generator.state),
        "init_key": np.asarray(strategy._init_key).copy(),
        "best_epoch": int(strategy.best_epoch),
        "resume_next_fit": bool(strategy.resume_next_fit),
        "variables": variables,
    }


def _restore_round_snapshot(strategy, snap: dict,
                            round_idx: Optional[int] = None) -> None:
    """Roll the strategy back to the round-start snapshot.  The
    ATTEMPTED round's stale mid-fit state is deleted too: it was written
    under an rng chain this restore just rewound, and resuming from it
    would splice two divergent attempts together.  (``round_idx`` names
    that round explicitly — the pool restore rewinds ``strategy.round``
    to the previous round's value, so weight_paths() alone would point
    at the wrong fit state.)"""
    if round_idx is not None:
        fit_state = ckpt_lib.weight_paths(
            strategy.cfg.ckpt_path, strategy.cfg.exp_name,
            strategy.exp_hash, round_idx)["fit_state"]
        ckpt_lib.delete_fit_state(fit_state)
    strategy.pool = PoolState.from_arrays(snap["pool"])
    strategy.rng.bit_generator.state = copy.deepcopy(snap["rng_state"])
    strategy._init_key = jax.numpy.asarray(snap["init_key"])
    strategy.best_epoch = snap["best_epoch"]
    strategy.resume_next_fit = snap["resume_next_fit"]
    if snap["variables"] is None:
        strategy.state = None
    elif strategy.state is not None:
        # Re-replicates from the host copies — fresh device buffers, so
        # arrays the failed attempt donated are never read again.
        strategy.state = strategy.trainer.replace_variables(
            strategy.state, snap["variables"])
    # The failed attempt's partial diagnostics must not double-count
    # into the retried round (the previous round's drift reference
    # survives — reset_round clears the CURRENT accumulators only).
    if strategy.diagnostics is not None:
        strategy.diagnostics.reset_round()


def run_experiment(cfg: ExperimentConfig, sink: Optional[MetricsSink] = None,
                   data=None, mesh=None,
                   train_cfg: Optional[TrainConfig] = None, model=None):
    """Run the full experiment; returns the finished Strategy.

    Mirrors main_al.py:124-184: fresh or resumed setup, then the round loop
    with per-phase wall-clock timers (the reference prints them,
    main_al.py:160-178; here they also land in the metrics sink).
    """
    # Device-truth profiling (telemetry/profiler.py, DESIGN.md §11):
    # when capture windows are armed, the HLO byte-table dump must be
    # pointed at its sidecar dir BEFORE the first backend touch — XLA
    # latches XLA_FLAGS at backend init, and the rendezvous below is
    # that first touch.  Env-only here (no logger yet); the
    # RoundProfiler itself is built after logging setup.
    profiling_armed = bool(cfg.profile_rounds or cfg.profile_dir)
    profile_dir = hlo_dump_dir = None
    # XLA_FLAGS is restored at run exit: XLA latched it at backend init,
    # so the env var is dead weight for THIS process afterwards — but a
    # leaked --xla_dump_to would arm dumping in every later subprocess
    # (bench children, status probes) against a dir this run owns.
    prev_xla_flags = os.environ.get("XLA_FLAGS")
    if profiling_armed:
        profile_dir = cfg.profile_dir or os.path.join(cfg.log_dir,
                                                      "profile")
        hlo_dump_dir = tele_profiler.arm_hlo_dump(
            os.path.join(profile_dir, "hlo"))
    # Multi-host rendezvous first — nothing above this may touch a JAX
    # backend.  A no-op unless the config carries the multi-host fields.
    mesh_lib.initialize_distributed(cfg.coordinator_address,
                                    cfg.num_processes, cfg.process_id)
    # Persistent executable reuse across rounds AND runs (config update
    # only — safe before or after backend init).
    enable_compilation_cache(cfg.compilation_cache_dir)
    # Arm the fault-injection registry (DESIGN.md §10) ONLY when a spec
    # is explicitly given — a run with neither --fault_spec nor
    # $AL_FAULT_SPEC must not clobber an arming a test installed
    # programmatically before calling run_experiment.  What this run
    # arms, its finally disarms: the registry is process-global, and a
    # spec leaking into the NEXT in-process run (bench phases, pytest)
    # would corrupt a clean measurement with no indication why.
    fault_spec = cfg.fault_spec or os.environ.get("AL_FAULT_SPEC")
    if fault_spec:
        faults.configure(fault_spec, seed=cfg.run_seed)

    if cfg.exp_hash is None:
        cfg.exp_hash = uuid.uuid4().hex[:9]
        if jax.process_count() > 1:
            # Every process must agree on the hash — it names the shared
            # checkpoint/resume directories that non-coordinators read.
            from jax.experimental import multihost_utils
            agreed = multihost_utils.broadcast_one_to_all(
                np.uint64(int(cfg.exp_hash, 16)))
            cfg.exp_hash = f"{int(agreed):09x}"

    today = date.today()
    log_filename = (f"{cfg.exp_hash}_{today.month:02d}{today.day:02d}.log")
    if jax.process_count() > 1:
        # Per-process log files, like the reference's per-rank logging.
        log_filename = log_filename.replace(
            ".log", f"_p{jax.process_index()}.log")
    logger = setup_logging(cfg.log_dir, log_filename)
    if fault_spec:
        logger.warning(f"fault injection ARMED: {fault_spec} "
                       f"(seed {cfg.run_seed}); disarmed at run exit")

    # The per-round capture windows (coordinator only: one process's
    # profiler session; pod-wide capture is a ROADMAP pod-tier item).
    # Unarmed, round_profiler stays None and the loop's hook is a null
    # context — zero per-round work (tests/test_profiler.py bounds it).
    round_profiler = None
    if profiling_armed and mesh_lib.is_coordinator():
        rounds, rejected = tele_profiler.parse_profile_rounds(
            cfg.profile_rounds)
        if rejected:
            logger.warning(
                f"profiler: --profile_rounds entries {rejected} ignored "
                "(round 0 pays the cold compile tax and never captures; "
                "rounds are positive integers)")
        reachable = [r for r in rounds if r < cfg.rounds]
        if not reachable:
            # e.g. --profile_dir on a rounds=1 run: the default warm
            # window (round 1) does not exist.  Say so and arm NOTHING
            # — a "capture armed" log followed by an empty profile_dir
            # would read as a profiler bug, not a config gap.
            logger.warning(
                f"profiler: no selected round {list(rounds)} exists in "
                f"a {cfg.rounds}-round run — nothing will be captured "
                "(round 0 never captures; run >= 2 rounds or pass "
                "--profile_rounds inside the run)")
        else:
            if len(reachable) < len(rounds):
                logger.warning(
                    "profiler: rounds "
                    f"{[r for r in rounds if r >= cfg.rounds]} exceed "
                    f"the {cfg.rounds}-round run and will not capture")
            round_profiler = tele_profiler.RoundProfiler(
                profile_dir, rounds=reachable, hlo_dump_dir=hlo_dump_dir,
                logger=logger)
            logger.info(
                f"profiler: device-truth capture armed for rounds "
                f"{reachable} -> {profile_dir} "
                f"(HLO byte table: {hlo_dump_dir or 'unavailable'})")

    resuming = cfg.resume_training and resume_lib.has_saved_experiment(cfg)
    preempted_round0 = False
    if cfg.resume_training and not resuming:
        # No completed round on disk.  One legitimate way to get here:
        # preempted (SIGTERM/SIGINT) DURING round 0, before the first
        # save_experiment — the journal records it, and the mid-fit
        # state (epoch-granular, saved by the trainer's preemption
        # boundary) is the only durable progress.  Restart round 0 and
        # let its first fit consume that state; everything before the
        # fit (init pool, eval split, init weights) is a deterministic
        # replay of the same seeds, so the resumed run still reproduces
        # the uninterrupted one bit-identically (tests/test_faults.py).
        prior = faults.read_journal(
            os.path.join(cfg.log_dir, faults.JOURNAL_FILE))
        if (prior is not None and prior.get("status") == "preempted"
                and prior.get("exp_hash") == cfg.exp_hash
                and prior.get("exp_name") == cfg.exp_name
                and int(prior.get("round", -1)) == 0):
            # The identity check matters: the journal is keyed by
            # log_dir, not by experiment — a forgotten --exp_hash (a
            # fresh uuid was just minted above) or a preemption at
            # round N re-run against the wrong --ckpt_path must still
            # hit the explicit error below, not silently restart.
            preempted_round0 = True
        else:
            # Never silently restart a run the user asked to resume (the
            # reference would die unpickling a missing file,
            # resume_training.py:13).
            raise FileNotFoundError(
                f"--resume_training: no saved experiment state for "
                f"exp_name={cfg.exp_name!r} exp_hash={cfg.exp_hash!r} under "
                f"{cfg.ckpt_path!r}; pass the original --exp_hash/--ckpt_path")
    if sink is None:
        key = (resume_lib.saved_experiment_key(cfg) if resuming
               else cfg.exp_hash)
        # Metrics/assets are run-level side effects: process 0 only.
        sink = make_sink(cfg.enable_metrics and mesh_lib.is_coordinator(),
                         cfg.log_dir, experiment_key=key,
                         backend=cfg.metrics_backend,
                         rotate_bytes=cfg.metrics_rotate_bytes)
    # The round journal (faults/journal.py): WHERE the run is — round/
    # phase/attempt, labeled-set digest, active degradation rungs,
    # terminal status — atomically rewritten next to the heartbeat so
    # `status --strict` and post-mortems read it with no jax import.
    journal = faults.RoundJournal(
        os.path.join(cfg.log_dir, faults.JOURNAL_FILE),
        enabled=mesh_lib.is_coordinator())
    # A resumed run must not silently FLIP the gradient-sync precision
    # mid-experiment: if the original launch's int8 probe failed (the
    # journal records grad_allreduce=f32_degraded), every later segment
    # of the same run stays on f32 — re-running the probe on resume and
    # having it pass would splice bounded-delta int8 rounds onto
    # bit-exact f32 ones under a journal that still says degraded.
    # (The other direction — int8 run resumed, probe now fails — keeps
    # the normal probe path: degrading TOWARD the bit-exact sync is
    # always safe, and gets journaled again.)
    prior_journal = faults.read_journal(
        os.path.join(cfg.log_dir, faults.JOURNAL_FILE))
    sticky_degrade = bool(
        (resuming or preempted_round0) and prior_journal
        and prior_journal.get("grad_allreduce") == "f32_degraded")
    if sticky_degrade:
        logger.info(
            "resume: the original run degraded grad_allreduce to f32 "
            "(journaled); keeping f32 for the resumed segment instead "
            "of re-probing")
        cfg.grad_allreduce = "f32"
    # Identity first: a preemption at ANY later point leaves a journal
    # the round-0 resume path above can verify belongs to THIS
    # experiment (the journal is keyed by log_dir, not exp_hash).
    journal.write(exp_name=cfg.exp_name, exp_hash=cfg.exp_hash)
    if sticky_degrade:
        # Re-assert the provenance the identity write just preserved
        # alongside (merge-write keeps other fields; this keeps the
        # degrade record explicit for `status --strict`/post-mortems).
        journal.write(grad_allreduce="f32_degraded")
    # The ladder is built after the strategy exists; the watchdog's
    # callback closes over this box so a stall can reach it.
    ladder_box: dict = {}

    # Run-wide telemetry (DESIGN.md §7): heartbeat + spans + per-step
    # metrics + optional watchdog/trace/scrape file, installed BEFORE the
    # stack is built so the trainer/strategies register their jitted
    # steps with the compile counter.  The watchdog's stall event rides
    # the metrics sink (thread-safe by JsonlSink's lock); with
    # --watchdog_action snapshot/degrade it also journals the stall, and
    # degrade additionally asks the ladder for escalation at the next
    # safe point (the watchdog thread itself never mutates run state).
    def _on_stall(stalled_s: float) -> None:
        logger.warning(
            f"watchdog: no progress for {stalled_s:.0f}s (deadline "
            f"{cfg.telemetry.stall_deadline_s:.0f}s) — stall suspected")
        sink.log_metric("stall_suspected", round(stalled_s, 1))
        tele_spans.get_tracer().instant(
            "stall_suspected", args={"stalled_s": round(stalled_s, 1)})
        action = getattr(cfg.telemetry, "watchdog_action", "log")
        if action in ("snapshot", "degrade"):
            journal.write(status="stalled", stalled_s=round(stalled_s, 1))
        if action == "degrade" and ladder_box.get("ladder") is not None:
            ladder_box["ladder"].request_stall()

    telemetry = tele_runtime.start_run(
        cfg.telemetry, log_dir=cfg.log_dir,
        process_index=jax.process_index(),
        process_count=jax.process_count(), logger=logger,
        on_stall=_on_stall)

    # Everything from here runs under the run's telemetry; the finally
    # below both finishes it (final heartbeat status + trace export) and
    # UNINSTALLS it — an exception anywhere, including setup, must not
    # leak an installed runtime into the next in-process run.  Preemption
    # handlers install for the same span: SIGTERM/SIGINT record a
    # request that the trainer's epoch boundaries and the driver's phase
    # boundaries turn into checkpoint-and-exit (faults/preempt.py).
    status = "crashed"
    pipeline = None
    # Per-run retry baseline: the process counter never resets (other
    # runs/phases in this interpreter own their own slices of it).
    run_retries0 = faults.retry_counters()["total"]
    preempt_lib.reset()
    prev_handlers = preempt_lib.install(logger)
    try:
        strategy = build_experiment(cfg, sink=sink, data=data, mesh=mesh,
                                    train_cfg=train_cfg, model=model,
                                    skip_init_pool=resuming)
        if getattr(strategy.trainer, "grad_allreduce_degraded", False):
            # The int8 learning probe failed (build_experiment already
            # fell back to f32 and logged): surface it LOUDLY through
            # the same channels a ladder escalation uses — the journal
            # (status --strict renders degrade lists) and the
            # degrade_events metric — so a run that silently trains
            # bit-exact when int8 was asked for is impossible to miss.
            journal.write(grad_allreduce="f32_degraded")
            sink.log_metric("degrade_events", 1, step=-1)
            sink.log_metric("grad_allreduce_degraded", 1, step=-1)
        if resuming:
            start_round = resume_lib.load_experiment(strategy, cfg)
            # The first fit of a resumed run may consume a mid-round fit
            # state (epoch-level recovery); non-resumed runs discard
            # stale ones.
            strategy.resume_next_fit = True
        else:
            start_round = 0
            sink.log_parameters(config_to_dict(cfg))
            if preempted_round0:
                # Preempted mid-round-0: replay the round from its seeds
                # but let the first fit consume the mid-fit state the
                # preemption boundary saved.
                logger.info(
                    "resume: journal records a round-0 preemption; "
                    "replaying round 0 and consuming its mid-fit state")
                strategy.resume_next_fit = True

        init_pool_size = cfg.resolved_init_pool_size()
        logger.info(f"Experiment Name: {cfg.exp_name}")
        logger.info(f"Dataset: {cfg.dataset}")
        logger.info(f"Strategy: {cfg.strategy}")
        logger.info(
            f"Budget used before starting: {strategy.pool.num_labeled}")
        logger.info(f"Log file name: {log_filename}")
        logger.info(f"Mesh: {strategy.mesh.devices.size} devices")

        # The per-run report artifact (telemetry/diagnostics.py,
        # DESIGN.md §13): the label-efficiency curve — accuracy vs
        # labeled count vs wall-clock per round, plus the round's
        # drift/composition/calibration diagnostics — atomically
        # rewritten as run_report.json after every round, so a crashed
        # or preempted run still leaves a renderable artifact
        # (`python -m active_learning_tpu report <log_dir>` /
        # scripts/run_report.py).  On resume, completed rounds' rows
        # are merged back from the prior file.
        run_report_path = os.path.join(cfg.log_dir,
                                       diag_lib.RUN_REPORT_FILE)
        write_report = mesh_lib.is_coordinator() and cfg.enable_metrics
        report_rows: list = []
        # Resumed segments continue the CUMULATIVE wall clock from the
        # last merged row (accuracy-vs-time must stay monotone across a
        # preemption; a fresh-zero clock would make round N+1 look
        # cheaper than round N).  Preemption downtime is not counted —
        # the curve measures compute time spent, not queue luck.  ONE
        # resume-merge rule, shared with the stream service
        # (diag_lib.resume_report_rows).
        report_wall_base = 0.0
        if write_report and start_round > 0:
            report_rows, report_wall_base = diag_lib.resume_report_rows(
                run_report_path, cfg.exp_hash, start_round)
        report_header = {
            "exp_name": cfg.exp_name, "exp_hash": cfg.exp_hash,
            "strategy": cfg.strategy, "dataset": cfg.dataset,
            "model": cfg.model, "run_seed": cfg.run_seed,
            "rounds_planned": cfg.rounds,
            "round_budget": cfg.round_budget,
            "init_pool_size": cfg.resolved_init_pool_size(),
        }
        run_t0 = time.monotonic()

        # The pipelined round coordinator (experiment/pipeline.py,
        # DESIGN.md §8): armed before each fit so the next query's pool
        # scoring overlaps the fit's patience tail, consumed by
        # Strategy.collect_scores at the next query.  Installed on the
        # strategy (train() wires the best-ckpt publish into fit);
        # bit-identical to the sequential loop by contract.  The
        # degradation ladder may detach it for a degraded round
        # (strategy.pipeline is the live switch; this local keeps the
        # shutdown handle either way).
        pipeline_mode = pipeline_lib.resolve_round_pipeline(
            cfg.round_pipeline, strategy.mesh)
        if pipeline_mode == "speculative":
            pipeline = pipeline_lib.RoundPipeline(strategy)
            strategy.pipeline = pipeline
        logger.info(f"Round pipeline: {pipeline_mode}")

        # The degradation ladder (faults/ladder.py, DESIGN.md §10): a
        # failure that survives the site-level retries costs a ROUND
        # ATTEMPT, not the run — the round rolls back to its snapshot
        # and re-runs one rung down.  The save below rides the unified
        # retry policy too (transient IO never loses a completed round).
        ladder = ladder_lib.DegradationLadder(strategy, logger=logger,
                                              sink=sink, journal=journal)
        ladder_box["ladder"] = ladder
        save_retry = faults.RetryPolicy(site="experiment_save",
                                        classify=faults.classify_exception)

        def _boundary(rd: int, phase: str) -> None:
            """A driver safe point: journal where we are, then honor a
            recorded preemption or a watchdog degrade request.  The
            durable state is consistent at every boundary by
            construction (atomic saves, monotonic tags)."""
            journal.write(round=rd, phase=phase)
            preempt_lib.check()
            ladder.check_stall()

        def _run_round(rd: int, attempt: int):
            """One round attempt — the reference loop body, verb for
            verb.  Returns (phase walls, round span) for the overlap
            accounting; raises to the attempt loop on failure."""
            phase_s = {}
            with tele_spans.get_tracer().span(
                    "round", args={"round": rd,
                                   "attempt": attempt}) as round_sp:
                strategy.round = rd
                telemetry.tick(force=True, round=rd,
                               phase="round_start", epoch=0, step=0)
                journal.write(status="running", round=rd,
                              phase="round_start", attempt=attempt,
                              labeled=strategy.pool.num_labeled,
                              labeled_crc=_labeled_crc(strategy.pool),
                              degrade=list(ladder.active),
                              pipeline_armed=bool(strategy.pipeline))
                logger.info(f"Active Learning Round {rd} start.")
                # Pool residency is default behavior: re-size the auto
                # budget from live HBM headroom at every round start (a
                # no-op for explicit integer budgets; already-uploaded
                # pools stay resident regardless —
                # parallel/resident.cached).
                budget = strategy.trainer.refresh_resident_budget()
                logger.info(
                    f"Resident pool budget for round {rd}: "
                    f"{budget / 1e9:.2f} GB "
                    f"({'auto' if strategy.train_cfg.resident_scoring_bytes is None else 'explicit'}, "
                    f"per chip, {strategy.trainer.pool_sharding} layout)")

                # Round 0 only queries when there is no initial pool —
                # with an SSL or transfer-learned init the model can
                # score the pool before any labels exist
                # (main_al.py:149-157).
                al_round_0 = rd == 0 and init_pool_size == 0
                if rd > 0 or al_round_0:
                    if al_round_0:
                        strategy.init_network_weights()
                    with phase_timer("query_time", rd, sink,
                                     logger) as sp:
                        labeled_idxs, cur_cost = strategy.query(
                            cfg.round_budget)
                    phase_s["query"] = sp.duration_s
                    strategy.update(labeled_idxs, cur_cost)
                    _boundary(rd, "query")

                with phase_timer("init_network_weights_time", rd, sink,
                                 logger) as sp:
                    strategy.init_network_weights()
                phase_s["init"] = sp.duration_s
                _boundary(rd, "init")
                # Arm the speculative plan for the NEXT round's query
                # before the fit starts publishing best checkpoints —
                # the scorer overlaps the fit's patience tail.  The
                # last round has no next query: nothing to speculate.
                if strategy.pipeline is not None and rd + 1 < cfg.rounds:
                    strategy.pipeline.arm(rd)
                with phase_timer("train_time", rd, sink, logger) as sp:
                    strategy.train()
                phase_s["train"] = sp.duration_s
                _boundary(rd, "train")
                with phase_timer("load_best_ckpt_time", rd, sink,
                                 logger) as sp:
                    strategy.load_best_ckpt()
                phase_s["load_best"] = sp.duration_s
                with phase_timer("test_time", rd, sink, logger) as sp:
                    strategy.test()
                phase_s["test"] = sp.duration_s

                # No preemption check between test and save: the round's
                # work is done, so the completed round is persisted
                # FIRST and the signal honored at the next boundary.
                if mesh_lib.is_coordinator():
                    save_retry.call(resume_lib.save_experiment,
                                    strategy, cfg)
                cfg.resume_training = True  # crash after this resumes (main_al.py:181)
                journal.write(round=rd, phase="round_end",
                              labeled=strategy.pool.num_labeled,
                              labeled_crc=_labeled_crc(strategy.pool))
            return phase_s, round_sp

        with tele_spans.get_tracer().span(
                "experiment", args={"exp_name": cfg.exp_name,
                                    "exp_hash": cfg.exp_hash}):
            for rd in range(start_round, cfg.rounds):
                preempt_lib.check()
                # Degradation is per-round: every round starts at full
                # capability; a systematic fault re-engages the ladder,
                # a transient one stays recovered.
                ladder.relax(rd)
                snapshot = _round_snapshot(strategy)
                for attempt in range(ladder.max_attempts()):
                    try:
                        # The device-truth capture window (DESIGN.md
                        # §11): a selected WARM round runs inside one
                        # jax.profiler window; on exit the device ops
                        # splice into the span trace and the
                        # device_busy_frac / collective_bytes metrics
                        # emit.  Inside the try: a failed attempt stops
                        # the trace on its way to the ladder.
                        with tele_profiler.round_scope(
                                round_profiler, rd,
                                tracer=tele_spans.get_tracer(),
                                sink=sink, telemetry=telemetry):
                            phase_s, round_sp = _run_round(rd, attempt)
                        break
                    except preempt_lib.PreemptionRequested:
                        raise
                    except ladder_lib.DegradeRequested as exc:
                        if ladder.escalate(exc, rd) is None:
                            raise
                        _restore_round_snapshot(strategy, snapshot, rd)
                    except (Exception, faults.ThreadDeath) as exc:
                        # Quiesce a possibly mid-chunk scorer before
                        # rolling back (escalate's pipeline_off rung
                        # also disarms; this covers the other rungs).
                        if strategy.pipeline is not None:
                            strategy.pipeline.disarm()
                        if ladder.escalate(exc, rd) is None:
                            raise
                        _restore_round_snapshot(strategy, snapshot, rd)
                pipe = strategy.pipeline
                if pipe is not None:
                    # Scorer busy minus the round's gate contention on
                    # BOTH sides: chunk busy already excludes the
                    # scorer's own gate waits (pipeline._score_chunk),
                    # and the main thread's waits on scorer holds are
                    # inside the phase walls — leaving them in spec_s
                    # would double-count serialized time as overlap
                    # (most visible in drain-mode CPU rounds, where a
                    # chunk's whole execution can stall the fit).
                    spec_s = max(
                        0.0, pipe.take_busy_s()
                        - strategy.trainer.dispatch_lock.take_wait_s())
                else:
                    spec_s = 0.0
                _emit_overlap_telemetry(
                    telemetry, sink, rd, round_sp.duration_s, phase_s,
                    spec_s, pipeline_mode if pipe is not None else "off")
                _emit_round_telemetry(telemetry, sink, rd, strategy,
                                      ladder,
                                      retries_baseline=run_retries0)
                if write_report:
                    row = {
                        "round": rd,
                        "labeled": int(strategy.pool.num_labeled),
                        "cumulative_budget":
                            float(strategy.pool.cumulative_cost),
                        "test_accuracy": strategy.last_test_acc,
                        "round_time_s": round(round_sp.duration_s, 3),
                        "wall_clock_s": round(
                            report_wall_base
                            + (time.monotonic() - run_t0), 3),
                        "phases_s": {k: round(v, 3)
                                     for k, v in phase_s.items()},
                    }
                    diag = getattr(strategy, "diagnostics", None)
                    if diag is not None:
                        row.update(diag.last_row)
                    report_rows.append(row)
                    diag_lib.write_run_report(run_report_path,
                                              report_header, report_rows)
                if len(strategy.available_query_idxs(shuffle=False)) == 0:
                    logger.info("Finished querying all Images!")
                    break
        status = "finished"
        journal.write(status="finished")
    except preempt_lib.PreemptionRequested as exc:
        # Checkpoint-and-exit: every durable artifact (experiment state,
        # mid-round fit state, best checkpoints, this journal) is
        # already consistent — the resumed run reproduces the
        # uninterrupted one bit-identically (tests/test_faults.py).
        status = "preempted"
        journal.write(status="preempted", signal=int(exc.signum))
        logger.info(
            "preemption: durable state checkpointed; re-run with "
            "--resume_training to continue bit-identically")
        raise
    finally:
        if profiling_armed:
            # Un-leak the HLO dump arming (see prev_xla_flags above).
            if prev_xla_flags is None:
                os.environ.pop("XLA_FLAGS", None)
            else:
                os.environ["XLA_FLAGS"] = prev_xla_flags
        if fault_spec:
            # Disarm only what THIS run armed (cleanup runs fault-free;
            # a programmatic arming by the caller is left alone).
            faults.configure(None)
        preempt_lib.uninstall(prev_handlers)
        # Stop the speculative scorer BEFORE telemetry teardown: its
        # thread ticks the heartbeat and records spans, both of which
        # must not outlive the run they belong to.
        if pipeline is not None:
            pipeline.shutdown()
        telemetry.finish(status)
        tele_runtime.uninstall(telemetry)
    return strategy
