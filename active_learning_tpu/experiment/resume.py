"""Round-level experiment save/restore.

The reference pickles the ENTIRE strategy object + args + status every round
(src/utils/resume_training.py:38-52) and unpickles it to resume
(:8-35).  Pickling live objects is fragile (any code change breaks old
checkpoints) so here the state is explicit arrays + json:

  * pool state (labeled mask, eval idxs, recent, cost, round) — npz;
  * the host RNG's bit-generator state and the per-experiment JAX init key —
    resuming reproduces the SAME round-(n+1) query an uninterrupted run
    would make;
  * a config echo — compared on load with a warning on mismatch, like the
    reference's args comparison (resume_training.py:22-25);
  * the metrics experiment key, so the sink continues the same stream
    (the reference reattaches the comet ExistingExperiment,
    resume_training.py:29-32).

Model weights are NOT duplicated here: the per-round best checkpoint
(best_rd_{n}.msgpack, train/checkpoint.py) is the model state of record and
is reloaded on resume.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .. import faults
from ..config import ExperimentConfig, config_to_dict
from ..pool import PoolState
# Shared weight-compatibility version: see its definition site for when it
# bumps.  Both resume surfaces (this file and the mid-round fit state in
# train/checkpoint.py) check it.
from ..train.checkpoint import MODEL_FORMAT_VERSION
from ..utils.logging import get_logger

STATE_FILE = "experiment_state.npz"
META_FILE = "experiment_state.json"
# Sampler-owned aux state (Strategy.aux_state_bytes — e.g. VAAL's
# VAE/discriminator/optimizers), msgpack via flax.serialization.
AUX_FILE = "aux_state.msgpack"


def _state_dir(cfg: ExperimentConfig) -> str:
    exp_hash = cfg.exp_hash or "no_hash"
    return os.path.join(cfg.ckpt_path, f"{cfg.exp_name}_{exp_hash}")


def save_experiment(strategy, cfg: ExperimentConfig) -> str:
    """Persist end-of-round state.  Called once per round after ``test()``
    (reference: main_al.py:180 → save_experiment)."""
    faults.site("ckpt_write")
    directory = _state_dir(cfg)
    os.makedirs(directory, exist_ok=True)
    arrays = strategy.pool.to_arrays()
    arrays["init_key"] = np.asarray(strategy._init_key)
    # Atomic writes (tmp + rename), meta LAST: has_saved_experiment checks
    # both files, so a crash mid-save can never leave a round-N state file
    # paired with a stale or truncated meta.
    state_path = os.path.join(directory, STATE_FILE)
    np.savez(state_path + ".tmp.npz", **arrays)
    os.replace(state_path + ".tmp.npz", state_path)
    aux_path = os.path.join(directory, AUX_FILE)
    aux = strategy.aux_state_bytes()
    if aux is not None:
        with open(aux_path + ".tmp", "wb") as fh:
            fh.write(aux)
        os.replace(aux_path + ".tmp", aux_path)
    elif os.path.exists(aux_path):
        # A stale aux blob from an older round of a sampler that stopped
        # producing one must not be restored later.
        os.remove(aux_path)
    # Torn point between the state npz and the meta json: a crash here
    # leaves a round-N state file with round-(N-1) (or no) meta — which
    # has_saved_experiment/meta-last ordering reads as the LAST COMPLETE
    # round, never a spliced pair (chaos-tested via ckpt_write:torn@N).
    faults.site("ckpt_write", point="torn")
    meta = {
        "round": int(strategy.round),
        "model_format": MODEL_FORMAT_VERSION,
        "rng_state": strategy.rng.bit_generator.state,
        "config": {k: _jsonable(v) for k, v in config_to_dict(cfg).items()},
        "experiment_key": getattr(strategy.sink, "experiment_key", None),
        "best_epoch": int(strategy.best_epoch),
    }
    meta_path = os.path.join(directory, META_FILE)
    with open(meta_path + ".tmp", "w") as fh:
        json.dump(meta, fh, indent=2)
    os.replace(meta_path + ".tmp", meta_path)
    get_logger().info(f"Saved experiment state for round {strategy.round} "
                      f"to {directory}")
    return directory


def has_saved_experiment(cfg: ExperimentConfig) -> bool:
    d = _state_dir(cfg)
    return (os.path.exists(os.path.join(d, STATE_FILE))
            and os.path.exists(os.path.join(d, META_FILE)))


def load_experiment(strategy, cfg: ExperimentConfig) -> int:
    """Restore ``strategy`` in place from the last completed round; returns
    the round to resume from (reference: load_experiment returns
    ``previous_round + 1``, resume_training.py:35)."""
    logger = get_logger()
    directory = _state_dir(cfg)
    with np.load(os.path.join(directory, STATE_FILE)) as arrs:
        arrays = {k: arrs[k] for k in arrs.files}
    with open(os.path.join(directory, META_FILE)) as fh:
        meta = json.load(fh)

    saved_fmt = int(meta.get("model_format", 1))
    if saved_fmt != MODEL_FORMAT_VERSION:
        # Shapes would match, so the npz/msgpack loads would succeed and
        # the run would silently diverge — refuse instead.
        raise RuntimeError(
            f"Saved experiment in {directory} uses model format "
            f"{saved_fmt}, this code writes {MODEL_FORMAT_VERSION}: its "
            "checkpointed weights are not alignment-compatible with the "
            "current conv padding. Restart the experiment (or re-run with "
            "the code version that wrote it).")

    # Warn (don't fail) on config drift, mirroring resume_training.py:22-25.
    current = {k: _jsonable(v) for k, v in config_to_dict(cfg).items()}
    saved = meta.get("config", {})
    for key in sorted(set(saved) | set(current)):
        if key in ("resume_training",):
            continue
        if saved.get(key) != current.get(key):
            logger.warning(
                f"Resume config mismatch for '{key}': saved "
                f"{saved.get(key)!r} != current {current.get(key)!r}")

    init_key = arrays.pop("init_key")
    strategy.pool = PoolState.from_arrays(arrays)
    import jax
    strategy._init_key = jax.numpy.asarray(init_key)
    strategy.rng.bit_generator.state = meta["rng_state"]
    strategy.best_epoch = int(meta.get("best_epoch", 0))

    prev_round = int(meta["round"])
    strategy.round = prev_round
    # Reload the trained model of the completed round so the next round's
    # query scores with it (the reference gets this for free by pickling the
    # whole object with its weights).  The state skeleton is built with a
    # throwaway key — NOT init_network_weights, which would consume a split
    # of the restored _init_key (diverging post-resume training from an
    # uninterrupted run) and pointlessly overlay any pretrained checkpoint
    # right before load_best_ckpt overwrites it.
    best = strategy.weight_paths()["best_ckpt"]
    if os.path.exists(best):
        if strategy.state is None:
            import jax
            sample = strategy.train_set.gather(np.zeros(1, dtype=np.int64))
            strategy.state = strategy.trainer.init_state(
                jax.random.PRNGKey(0), sample)
        strategy.load_best_ckpt()
    aux_path = os.path.join(directory, AUX_FILE)
    if os.path.exists(aux_path):
        with open(aux_path, "rb") as fh:
            strategy.restore_aux_state(fh.read())
        logger.info("Restored sampler aux state (VAE/discriminator)")
    logger.info(f"Resuming experiment from round {prev_round + 1}")
    return prev_round + 1


def saved_experiment_key(cfg: ExperimentConfig) -> Optional[str]:
    """The metrics experiment key of a saved run (for sink reattachment)."""
    path = os.path.join(_state_dir(cfg), META_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return json.load(fh).get("experiment_key")


def _jsonable(v: Any):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (tuple, list)):
        return [_jsonable(e) for e in v]
    return str(v)
