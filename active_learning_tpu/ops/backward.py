"""Hand-written backward passes for the two hand-built forward kernels
(DESIGN.md §4, "The gradient path").

`mfu_decomposition.json` names the backward pass as the step's largest
cost: the forward runs at 0.467 MFU, the full train step at 0.318, and
the ~0.33 implied backward is whatever XLA derives from the forward
graph.  For the two kernels this repo hand-built — the space-to-depth
stem conv and the fused bf16 BN statistics — XLA's derivation loses the
very properties the forwards were built for:

  * ``FusedBatchNorm``'s forward reads bf16 activations with float32
    ACCUMULATION (the casts fuse into the reduce); autodiff of that
    graph materializes full-tensor float32 cotangents for the
    ``astype(float32)`` links in the stats path — the 2x-bytes
    materialization the forward exists to avoid, now on the backward.
  * the s2d stem's weight gradient is a contraction over batch x space
    (the worst-tiling conv on the MXU, DESIGN.md §4's weight-gradient
    row); derived from a bf16 forward it accumulates in bf16 and casts
    to f32 afterwards, instead of reading bf16 and accumulating f32
    like every forward reduction here does.

Both customs keep the PRIMAL bit-identical to the existing forward (the
checkpoint-tree and logits-parity contracts are untouched) and replace
only the cotangent computation:

  * ``stem_conv``: dx is the same transposed conv XLA derives (bf16 in,
    bf16 out — there is nothing to win); dW is ONE conv with
    ``preferred_element_type=float32`` — bf16 element reads, float32
    accumulation, f32 output landing directly in the f32 parameter
    cotangent (no bf16-round-then-cast).
  * ``fused_bn_train``: the per-channel reductions (dscale, dbias, the
    mean/variance chain) read bf16 and accumulate f32; dx is computed
    in one fused elementwise pass over bf16 reads with a single cast to
    the activation dtype at the end.  No full-size f32 tensor is ever
    materialized.

Gradient equivalence to the flax/XLA-derived backward is proven the
same way the s2d forward was (tests/test_backward.py): rounding-order
tolerance at bf16, ~1e-10 identity at f64.

Every ``jax.custom_vjp`` in the train path lives in THIS module and is
named in ``TRAIN_PATH_VJPS`` — scripts/trace_lint.py check 9 statically
verifies the registry is closed and that each name has a registered
parity test (``PARITY_TESTED_VJPS`` in tests/test_backward.py).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# The CLOSED registry of train-path custom VJPs (trace_lint check 9):
# every jax.custom_vjp in the package must be defined here and named
# in this tuple, and every name must carry a registered parity test.
TRAIN_PATH_VJPS = ("stem_conv", "fused_bn_train")

_CONV_DN = ("NHWC", "HWIO", "NHWC")


@functools.lru_cache(maxsize=None)
def _stem_conv_fn(dtype: Any, padding: Tuple[Tuple[int, int], ...]):
    """custom_vjp'd stem conv for one (compute dtype, padding) pair —
    cached so repeated traces reuse one custom_vjp object (and one jit
    cache lineage)."""
    dtype = jnp.dtype(dtype)

    def _primal(x, kernel):
        # Exactly flax nn.Conv's forward (promote to the compute dtype,
        # stride-1 NHWC conv, default precision): the primal must stay
        # bit-identical to the nn.Conv it replaces.
        return lax.conv_general_dilated(
            x.astype(dtype), kernel.astype(dtype), (1, 1), padding,
            dimension_numbers=_CONV_DN)

    @jax.custom_vjp
    def conv(x, kernel):
        return _primal(x, kernel)

    def fwd(x, kernel):
        return _primal(x, kernel), (x, kernel)

    def bwd(res, g):
        x, kernel = res
        kd = kernel.astype(dtype)
        kh, kw = kd.shape[0], kd.shape[1]
        (pl0, pr0), (pl1, pr1) = padding
        # dx: the standard stride-1 transposed conv (flipped kernel,
        # in/out channels swapped, complementary padding) — the same
        # conv XLA's transpose rule emits, bf16 reads and writes.
        kt = jnp.flip(kd, (0, 1)).swapaxes(2, 3)
        dx = lax.conv_general_dilated(
            g, kt, (1, 1),
            ((kh - 1 - pl0, kh - 1 - pr0), (kw - 1 - pl1, kw - 1 - pr1)),
            dimension_numbers=_CONV_DN)
        # dW[h,w,c,f] = sum_{b,i,j} x[b, i+h-ph, j+w-pw, c] * g[b,i,j,f]
        # — the batch/space contraction, expressed as ONE conv whose
        # "batch" is the input channel and whose contraction runs over
        # the true batch: bf16 element reads, float32 ACCUMULATION
        # (preferred_element_type), f32 output landing directly in the
        # f32 parameter cotangent.
        dw = lax.conv_general_dilated(
            x.astype(dtype), g, (1, 1), padding,
            dimension_numbers=("CHWN", "IHWO", "HWNC"),
            # f32 accumulation over bf16/f32 reads; promoted to f64
            # under enable_x64 (preferred_element_type may not narrow).
            preferred_element_type=jnp.promote_types(dtype, jnp.float32))
        return dx.astype(x.dtype), dw.astype(kernel.dtype)

    conv.defvjp(fwd, bwd)
    return conv


def stem_conv(x: jnp.ndarray, kernel: jnp.ndarray, *, dtype: Any,
              padding=((2, 1), (2, 1))) -> jnp.ndarray:
    """The s2d stem's 4x4/stride-1 conv with the hand-written backward
    (see module docstring).  ``padding`` is the folded 7x7/pad-3 window
    in s2d coordinates (models/resnet.s2d_stem_kernel)."""
    padding = tuple(tuple(int(v) for v in p) for p in padding)
    return _stem_conv_fn(jnp.dtype(dtype), padding)(x, kernel)


def _balanced_relu_grad(a, g):
    """d/da of jnp.maximum(a, 0.0) applied to cotangent ``g``, matching
    jax's tie rule exactly (half the cotangent at a == 0) so the f64
    identity proof holds even on the clamp boundary."""
    return g * jnp.where(a > 0, 1.0, jnp.where(a == 0, 0.5, 0.0))


@functools.lru_cache(maxsize=None)
def _fused_bn_fn(dtype: Any, epsilon: float, ndim: int):
    """custom_vjp'd training-mode BN (batch statistics + normalize) for
    one (stats/compute dtype, epsilon, rank) triple.  Returns
    ``(y, mean, var)`` — the module updates its running statistics from
    mean/var outside (mutable collections carry no gradient; the bwd
    still honors their cotangents for correctness)."""
    dtype = jnp.dtype(dtype)
    axes = tuple(range(ndim - 1))
    # Accumulation dtype: float32 over bf16/f32 reads (the production
    # discipline); promoted to f64 under enable_x64 so the f64 identity
    # proof compares exact math to exact math.
    f32 = jnp.promote_types(dtype, jnp.float32)

    def _primal(x, scale, bias):
        # Bit-identical to the pre-custom-VJP FusedBatchNorm train
        # branch (models/resnet.py): bf16 element reads, f32-accumulated
        # statistics, fast-variance with the f32 square (see the
        # module's comment on cancellation), clamped at zero.  mean2 is
        # returned too: it is already an intermediate of var, and the
        # backward needs the PRE-clamp value's sign (var reads 0 both
        # at the clamp boundary and below it).
        x_stats = x.astype(dtype)
        mean = jnp.mean(x_stats, axes, dtype=f32)
        mean2 = jnp.mean(lax.square(x_stats.astype(f32)), axes)
        var = jnp.maximum(mean2 - lax.square(mean), 0.0)
        mul = (scale * lax.rsqrt(var + epsilon)).astype(dtype)
        sub = mean.astype(dtype) * mul - bias.astype(dtype)
        y = x.astype(dtype) * mul - sub
        return y, mean, var, mean2

    @jax.custom_vjp
    def bn(x, scale, bias):
        y, mean, var, _ = _primal(x, scale, bias)
        return y, mean, var

    def fwd(x, scale, bias):
        y, mean, var, mean2 = _primal(x, scale, bias)
        return (y, mean, var), (x, scale, mean, mean2)

    def bwd(res, cts):
        x, scale, mean, mean2 = res
        gy, gmean, gvar = cts
        n = float(np.prod([x.shape[a] for a in axes]))
        a_pre = mean2 - lax.square(mean)
        var = jnp.maximum(a_pre, 0.0)
        x_c = x.astype(dtype)
        r = lax.rsqrt(var + epsilon)                      # f32 [C]
        mulf = scale * r                                  # f32 [C]
        mul32 = mulf.astype(dtype).astype(f32)            # fwd's rounded mul
        # Per-channel reductions: bf16 element reads, f32 accumulation
        # (the casts fuse into the reduce's input computation — no f32
        # copy of the activation or cotangent is materialized).
        s1 = jnp.sum(gy, axes, dtype=f32)                 # Σ gy
        s2 = jnp.sum(gy.astype(f32) * x_c.astype(f32), axes)  # Σ gy·x
        dbias = s1                                        # y = ... + bias_c
        dmul = s2 - s1 * mean                             # Σ gy·(x − mean)
        dscale = dmul * r
        # var chain: r = (var+eps)^{-1/2}; var = max(mean2 − mean², 0).
        dvar = dmul * scale * (-0.5) * r * r * r + gvar
        da = _balanced_relu_grad(a_pre, dvar)
        dmean2 = da
        dmean = -s1 * mul32 + gmean - 2.0 * mean * da
        # dx, in ONE fused elementwise pass: bf16 reads of gy/x, f32
        # arithmetic against the per-channel f32 coefficients, a single
        # cast to the activation dtype on the way out.
        c2 = 2.0 * dmean2 / n                             # f32 [C]
        c1 = dmean / n                                    # f32 [C]
        dx = (gy.astype(f32) * mul32 + x_c.astype(f32) * c2 + c1)
        return dx.astype(x.dtype), dscale, dbias

    bn.defvjp(fwd, bwd)
    return bn


def fused_bn_train(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
                   *, dtype: Any, epsilon: float):
    """Training-mode fused-statistics BatchNorm with the hand-written
    backward: returns ``(y, mean, var)``; see the module docstring."""
    return _fused_bn_fn(jnp.dtype(dtype), float(epsilon), x.ndim)(
        x, scale, bias)
