"""Custom TPU kernels (Pallas).

Only ops where measured XLA performance leaves headroom get a kernel —
see DESIGN.md §5 for the decision record.  Current contents:

  * kcenter_pallas — the k-center selection's fused batched
    distance-update + block-local argmax (Q-center MXU matmul, min over
    centers, running-min update and masked argmax in one VMEM-resident
    pass over the transposed factor tiles); routed by the measured
    dispatcher in strategies/kcenter.py.
"""
