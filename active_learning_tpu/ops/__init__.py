"""Custom ops: places where measured XLA performance leaves headroom.

``backward.py`` — the hand-written backward passes (custom VJPs) for
the two hand-built forward kernels: the s2d stem conv's f32-accumulated
weight gradient and FusedBatchNorm's bf16-reads/f32-accumulation
backward, replacing XLA's materialize-as-f32 derivation on the train
step's gradient path (DESIGN.md §4; parity pinned by
tests/test_backward.py, registry closed by trace_lint check 9).

No Pallas kernels, on purpose.  Only ops where measured XLA performance
leaves headroom get a kernel, and the one kernel that ever lived here —
``kcenter_pallas``, the k-center selection's fused batched
distance-update + block-local argmax — failed that bar on real
hardware: the r5 on-MXU A/B measured 0.67x/1.11x/0.93x the XLA scan
with ``pallas_picks_match: False`` in all three runs, so it was deleted
per the r5 verdict rather than kept as an env-var-gated trap.  The full
decision record (what the kernel fused, why XLA's matvec was already
HBM-bound, and the bar any future kernel must clear) is DESIGN.md §5.
"""
