"""Custom TPU kernels (Pallas).

Currently EMPTY, on purpose.  Only ops where measured XLA performance
leaves headroom get a kernel, and the one kernel that ever lived here —
``kcenter_pallas``, the k-center selection's fused batched
distance-update + block-local argmax — failed that bar on real
hardware: the r5 on-MXU A/B measured 0.67x/1.11x/0.93x the XLA scan
with ``pallas_picks_match: False`` in all three runs, so it was deleted
per the r5 verdict rather than kept as an env-var-gated trap.  The full
decision record (what the kernel fused, why XLA's matvec was already
HBM-bound, and the bar any future kernel must clear) is DESIGN.md §5.
"""
