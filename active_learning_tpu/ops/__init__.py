"""Custom TPU kernels (Pallas).

Only ops where measured XLA performance leaves headroom get a kernel —
see DESIGN.md §5 for the decision record.  Current contents:

  * kcenter_pallas — the k-center scan's per-pick fused distance-update
    (matvec + d_new + running-min in one pass over the factor matrix).
"""
