"""Pallas TPU kernel for the k-center scan's distance update.

The greedy selection loop (strategies/kcenter.py) spends its time in one
operation per pick: ``min_dist <- min(min_dist, sqn + sqn[idx] - 2 X@X[idx])``
— a skinny matvec over the whole [N, D] factor matrix plus two [N]
elementwise passes.  XLA runs this at well under HBM bandwidth on TPU (the
matvec's output lane width is 1), so this kernel restructures the layout:

  * the factor matrix is stored TRANSPOSED, XT [D, N], so pool rows lie
    along the lane dimension and the matvec becomes [1, TILE_D] @
    [TILE_D, TILE_N] MXU tiles accumulating a [1, TILE_N] strip;
  * d_new and the running min fuse into the same pass — one read of XT,
    one read-modify of min_dist, nothing else touches HBM.

Equivalence to the XLA path is proven in INTERPRET mode
(tests/test_kcenter_pallas.py pins the kernel against the plain jnp
expression); on a real MXU the tiled accumulation order differs from
XLA's matvec, so float32 rounding can differ in the last ulp and an
exact argmax tie could flip a pick.  bench.py's A/B therefore also
reports whether the on-TPU pick sequences match
(``pallas_picks_match``).

**Hardware A/B verdict (v5e, 2026-07-31, BENCH r5, three runs): keep
the XLA scan.** At N=50k, D=2048, budget=10k the kernel measured 0.67x
the scan (552 vs 826 picks/s), 1.11x (874 vs 789), and 0.93x (485 vs
519) across three backend windows — parity within tunnel noise,
nowhere near a win worth a numerics change — and
``pallas_picks_match=False`` in ALL THREE runs: the accumulation-order
rounding divergence above is real on hardware, not hypothetical.  XLA's fused matvec is already HBM-bound
here, so the restructured layout buys no bandwidth it doesn't already
have.  The kernel therefore stays opt-in (AL_TPU_KCENTER_PALLAS=1),
kept as the scaffold for a future multi-pick batched variant — see
DESIGN.md §5 — and the caller falls back to the XLA scan if the
compiled kernel fails at runtime (strategies/kcenter.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_N = 512
TILE_D = 512

# Set by strategies/kcenter.py when the compiled kernel failed at runtime
# and the XLA scan answered instead; bench.py's A/B checks it so a
# fallback can never masquerade as a Pallas measurement.
LAST_FALLBACK_ERROR = None


def _update_kernel(sqn_idx_ref, v_ref, xt_ref, sqn_ref, min_ref, out_ref,
                   acc_ref):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[:, :] = jnp.zeros_like(acc_ref)

    acc_ref[:, :] += jnp.dot(v_ref[:, :], xt_ref[:, :],
                             preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(1) - 1)
    def _finish():
        d_new = sqn_ref[:, :] + sqn_idx_ref[0, 0] - 2.0 * acc_ref[:, :]
        out_ref[:, :] = jnp.minimum(min_ref[:, :], d_new)


@functools.partial(jax.jit, static_argnames=("interpret",))
def min_dist_update(xt: jnp.ndarray, sqn: jnp.ndarray,
                    min_dist: jnp.ndarray, idx: jnp.ndarray,
                    interpret: bool = False) -> jnp.ndarray:
    """One fused distance-update against pool row ``idx``.

    xt [D, N] float32 (transposed factors, N and D multiples of the
    tiles); sqn [1, N]; min_dist [1, N]; idx scalar int32.  Returns the
    updated [1, N] min-distance row.
    """
    d, n = xt.shape
    assert n % TILE_N == 0 and d % TILE_D == 0, (n, d)
    v = jax.lax.dynamic_slice(xt, (0, idx), (d, 1)).T  # [1, D]
    sqn_idx = jax.lax.dynamic_slice(sqn, (0, idx), (1, 1))  # [1, 1]

    grid = (n // TILE_N, d // TILE_D)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    return pl.pallas_call(
        _update_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda j, k: (0, 0)),          # sqn[idx]
            pl.BlockSpec((1, TILE_D), lambda j, k: (0, k)),     # v
            pl.BlockSpec((TILE_D, TILE_N), lambda j, k: (k, j)),  # XT
            pl.BlockSpec((1, TILE_N), lambda j, k: (0, j)),     # sqn
            pl.BlockSpec((1, TILE_N), lambda j, k: (0, j)),     # min_dist
        ],
        out_specs=pl.BlockSpec((1, TILE_N), lambda j, k: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, TILE_N), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(sqn_idx, v, xt, sqn, min_dist)


def pad_to_tiles(x: jnp.ndarray) -> jnp.ndarray:
    """Pad an [N, D] factor matrix with zero rows/cols to tile multiples
    and return it TRANSPOSED as [D_pad, N_pad] for min_dist_update.
    Zero-padded pool rows have distance sqn[idx] - 0 >= 0 to everything
    and must be masked ineligible by the caller (kcenter does, via its
    ``selectable`` vector)."""
    n, d = x.shape
    pad_n = (-n) % TILE_N
    pad_d = (-d) % TILE_D
    return jnp.pad(x, ((0, pad_n), (0, pad_d))).T
