"""Fused Pallas TPU kernel for the k-center selection hot path.

The greedy loop (strategies/kcenter.py) spends its time in one operation
per scan step: fold a set of freshly-picked centers into the running
min-distance vector, then find the farthest remaining point.  Expressed
in XLA that is (a) a skinny matmul over the whole [N, D] factor matrix,
(b) an elementwise min pass over min_dist, and (c) a masked argmax pass
over min_dist — the pool-sized operands stream from HBM more than once
per pick.  This kernel restructures the layout and fuses all three:

  * the factor matrix is stored TRANSPOSED, XT [D, N], so pool rows lie
    along the lane dimension and the center matmul becomes
    [Q, TILE_D] @ [TILE_D, TILE_N] MXU tiles accumulating a [Q, TILE_N]
    strip — Q centers amortize ONE read of the pool tiles (the batched
    greedy's q picks per step map straight onto Q);
  * the [Q, TILE_N] distance strip, the min over centers, the running
    min_dist update, and the BLOCK-LOCAL masked argmax all happen while
    the tile is resident in VMEM: per pick-batch the pool is read once,
    and only per-block (max, argmax) pairs plus the updated min row go
    back to HBM.  The host-side scan finishes the argmax with a trivial
    [N / TILE_N] reduction.

Equivalence to the XLA path is proven in INTERPRET mode
(tests/test_kcenter_pallas.py pins the fused output and the argmax
against the plain jnp expressions); on a real MXU the tiled accumulation
order differs from XLA's matmul, so float32 rounding can differ in the
last ulp and an exact argmax tie could flip a pick.  bench.py's A/B
therefore also reports whether the on-TPU pick sequences match
(``pallas_picks_match``).

**Hardware history.**  The r5 A/B (v5e, three runs) showed the earlier
PER-PICK matvec kernel at parity with the XLA scan (0.67x/1.11x/0.93x)
— a [1, TILE_D] strip leaves the MXU idle and XLA's matvec is already
HBM-bound.  That measurement is why the dispatcher
(strategies/kcenter.py:_select_backend) only routes to this kernel in
the BATCHED regime (Q >= CENTER_TILE, full tiles), where the Q-row MXU
matmul plus the single fused pass has headroom the matvec never had;
everywhere else it falls back to the XLA scan so ``pallas_x >= 1.0``
holds by construction (the fallback is recorded, never silent — see
LAST_BACKEND / LAST_FALLBACK_ERROR below).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_N = 512
TILE_D = 512
# Centers are padded to a multiple of this (the float32 sublane tile):
# a [CENTER_TILE, TILE_D] strip is the smallest left operand that keeps
# the MXU fed, and padding with repeated centers leaves the min over
# centers unchanged.
CENTER_TILE = 8

# Set by strategies/kcenter.py: which path actually answered the last
# kcenter_greedy call ("xla" | "xla-batched" | "pallas" |
# "pallas-interpret"), and the error when a compiled-kernel failure
# forced the XLA fallback.  bench.py's A/B reads both so a fallback can
# never masquerade as a Pallas measurement.
LAST_BACKEND = None
LAST_FALLBACK_ERROR = None

# jax renamed TPUCompilerParams -> CompilerParams across versions; the
# kernel must load on both.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")


def _fused_kernel(sqn_c_ref, v_ref, xt_ref, sqn_ref, min_ref, sel_ref,
                  out_min_ref, out_bmax_ref, out_barg_ref, acc_ref):
    j = pl.program_id(0)
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[:, :] = jnp.zeros_like(acc_ref)

    acc_ref[:, :] += jnp.dot(v_ref[:, :], xt_ref[:, :],
                             preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(1) - 1)
    def _finish():
        # d[c, i] = ||x_i - center_c||^2 over the [Q, TILE_N] strip, its
        # min over centers, the running-min update, and the block-local
        # masked argmax — one VMEM-resident pass, nothing re-read.
        d = sqn_c_ref[:, :] + sqn_ref[:, :] - 2.0 * acc_ref[:, :]
        new_min = jnp.minimum(min_ref[:, :], jnp.min(d, axis=0,
                                                     keepdims=True))
        out_min_ref[:, :] = new_min
        masked = jnp.where(sel_ref[:, :] > 0, new_min, -jnp.inf)
        bmax = jnp.max(masked)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, TILE_N), 1)
        # Lowest index among block maxima — jnp.argmax's tie-break, so
        # the scan's global reduction reproduces XLA's pick exactly.
        barg = jnp.min(jnp.where(masked >= bmax, lane,
                                 jnp.int32(2 ** 31 - 1)))
        out_bmax_ref[0, 0] = bmax
        out_barg_ref[0, 0] = barg + j * TILE_N


def fused_update_argmax(xt: jnp.ndarray, sqn: jnp.ndarray,
                        min_dist: jnp.ndarray, selectable: jnp.ndarray,
                        center_idxs: jnp.ndarray, interpret: bool = False):
    """Fold ``center_idxs`` into the min-distance row and return the next
    farthest point, in one pass over the pool tiles.

    xt [D, N] float32 (transposed factors; N, D tile multiples);
    sqn / min_dist / selectable [1, N]; center_idxs [Q] int32 pool
    indices with Q a CENTER_TILE multiple (pad with repeats — the min
    over centers is unaffected).  Returns (new_min [1, N],
    block_max [1, N/TILE_N], block_arg [1, N/TILE_N]); the global pick
    is ``block_arg[0, argmax(block_max[0])]``.
    """
    d, n = xt.shape
    q = center_idxs.shape[0]
    assert n % TILE_N == 0 and d % TILE_D == 0, (n, d)
    assert q % CENTER_TILE == 0, q
    v = jnp.take(xt, center_idxs, axis=1).T  # [Q, D]
    sqn_c = jnp.take(sqn[0], center_idxs)[:, None]  # [Q, 1]

    grid = (n // TILE_N, d // TILE_D)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    return pl.pallas_call(
        _fused_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((q, 1), lambda j, k: (0, 0)),            # sqn_c
            pl.BlockSpec((q, TILE_D), lambda j, k: (0, k)),       # v
            pl.BlockSpec((TILE_D, TILE_N), lambda j, k: (k, j)),  # XT
            pl.BlockSpec((1, TILE_N), lambda j, k: (0, j)),       # sqn
            pl.BlockSpec((1, TILE_N), lambda j, k: (0, j)),       # min_dist
            pl.BlockSpec((1, TILE_N), lambda j, k: (0, j)),       # selectable
        ],
        out_specs=[
            pl.BlockSpec((1, TILE_N), lambda j, k: (0, j)),
            pl.BlockSpec((1, 1), lambda j, k: (0, j)),
            pl.BlockSpec((1, 1), lambda j, k: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n // TILE_N), jnp.float32),
            jax.ShapeDtypeStruct((1, n // TILE_N), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((q, TILE_N), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(sqn_c, v, xt, sqn, min_dist, selectable)


def pad_centers(idxs: jnp.ndarray) -> jnp.ndarray:
    """Pad a [q] center-index vector to the CENTER_TILE multiple with
    repeats of the first entry (min over duplicate centers is a no-op)."""
    q = idxs.shape[0]
    pad = (-q) % CENTER_TILE
    if pad:
        idxs = jnp.concatenate([idxs, jnp.broadcast_to(idxs[:1], (pad,))])
    return idxs


def pad_to_tiles(x: jnp.ndarray) -> jnp.ndarray:
    """Pad an [N, D] factor matrix with zero rows/cols to tile multiples
    and return it TRANSPOSED as [D_pad, N_pad] for fused_update_argmax.
    Zero-padded pool rows have distance sqn[idx] - 0 >= 0 to everything
    and must be masked ineligible by the caller (kcenter does, via its
    ``selectable`` vector)."""
    n, d = x.shape
    pad_n = (-n) % TILE_N
    pad_d = (-d) % TILE_D
    return jnp.pad(x, ((0, pad_n), (0, pad_d))).T
