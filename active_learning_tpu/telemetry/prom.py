"""Prometheus text exposition (format 0.0.4) — stdlib only.

One shared encoder serves both workloads: ``serve/server.py`` answers
``GET /metrics?format=prometheus`` with it, and the driver's optional
scrape file (TelemetryConfig.prometheus_file) is the same text written
atomically for node-exporter's textfile collector — so stock Prometheus
tooling monitors an AL run and a scoring service without any custom
exporter.

Everything is emitted as a gauge: counters here are process-lifetime
snapshots read from one process's memory, and a gauge with a _total
suffix scrapes identically while staying honest about resets.
"""

from __future__ import annotations

import math
import os
import re
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

# (name, labels-or-None, value)
Sample = Tuple[str, Optional[Dict[str, str]], Any]

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_]")


def sanitize_name(name: str) -> str:
    """A valid metric name from an arbitrary internal one (dots, dashes
    and any other punctuation become underscores; a leading digit gets a
    prefix)."""
    name = _NAME_BAD_CHARS.sub("_", str(name))
    if not name or not _NAME_OK.match(name):
        name = f"_{name}"
    return name


def _escape_label_value(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _format_value(v: Any) -> Optional[str]:
    if isinstance(v, bool):
        return "1" if v else "0"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


def render(samples: Iterable[Sample],
           help_map: Optional[Mapping[str, str]] = None) -> str:
    """Prometheus exposition text from (name, labels, value) samples.

    Samples sharing a name are grouped under one ``# TYPE`` header (the
    format requires it); None/unconvertible values are dropped rather
    than emitted as parse errors for the scraper."""
    by_name: Dict[str, List[Tuple[Optional[Dict[str, str]], str]]] = {}
    order: List[str] = []
    for name, labels, value in samples:
        text = _format_value(value)
        if text is None:
            continue
        name = sanitize_name(name)
        if name not in by_name:
            by_name[name] = []
            order.append(name)
        by_name[name].append((labels, text))
    lines: List[str] = []
    for name in order:
        if help_map and name in help_map:
            lines.append(f"# HELP {name} {help_map[name]}")
        lines.append(f"# TYPE {name} gauge")
        for labels, text in by_name[name]:
            if labels:
                body = ",".join(
                    f'{_LABEL_BAD_CHARS.sub("_", str(k))}='
                    f'"{_escape_label_value(v)}"'
                    for k, v in sorted(labels.items()))
                lines.append(f"{name}{{{body}}} {text}")
            else:
                lines.append(f"{name} {text}")
    return "\n".join(lines) + ("\n" if lines else "")


# One labeled-gauge spelling for the flat gauge dicts the RunTelemetry
# runtime carries: a key like ``rounds_triggered{cause=watermark}``
# renders as ``al_run_rounds_triggered{cause="watermark"} v`` — the
# streaming service's per-cause trigger counters use it, and the same
# bracketed key is what rides metrics.jsonl (one spelling, two
# channels, like every other gauge).
_LABELED_KEY = re.compile(
    r"^(?P<name>[^{}]+)\{(?P<label>[a-zA-Z0-9_]+)=(?P<value>[^{}=]*)\}$")


def gauge_samples(gauges: Mapping[str, Any], prefix: str = ""
                  ) -> List[Sample]:
    """Flat name->value mapping as samples (the driver's gauge dict).
    Keys matching ``name{label=value}`` become labeled samples."""
    out: List[Sample] = []
    for name, value in sorted(gauges.items()):
        m = _LABELED_KEY.match(str(name))
        if m:
            out.append((f"{prefix}{m.group('name')}",
                        {m.group("label"): m.group("value")}, value))
        else:
            out.append((f"{prefix}{name}", None, value))
    return out


def write_textfile(path: str, text: str) -> bool:
    """Atomic scrape-file write (node-exporter textfile collector reads
    whole files; a torn write would be a parse error for every metric in
    it).  Never raises — a full disk must not kill the run."""
    try:
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write(text)
        os.replace(tmp, path)
        return True
    except OSError:
        return False


def parse(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]:
    """Minimal exposition parser: {name: {labels-tuple: value}}.  Exists
    for tests (round-tripping what render produced) and for the status
    verb; not a general scraper."""
    out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)"
                     r"(?:\{(.*)\})?\s+(\S+)$", line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, labelstr, value = m.groups()
        labels: List[Tuple[str, str]] = []
        if labelstr:
            for part in re.findall(r'([a-zA-Z0-9_]+)="((?:[^"\\]|\\.)*)"',
                                   labelstr):
                k, v = part
                v = (v.replace(r"\"", '"').replace(r"\n", "\n")
                     .replace(r"\\", "\\"))
                labels.append((k, v))
        out.setdefault(name, {})[tuple(labels)] = float(value)
    return out
