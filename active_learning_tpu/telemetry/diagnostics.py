"""The experiment-truth layer: acquisition diagnostics the AL loop
itself emits (DESIGN.md §13).

After host spans (§7) and device truth (§11), this is the third leg of
the observability stack — what did the learner SELECT, is the score
distribution drifting round over round, and how do two strategies
compare at equal label budget.  Everything here is computed WHERE THE
NUMBERS ALREADY EXIST: acquisition scores arrive on host as the normal
output of every scoring pass, k-center pick distances ride out of the
selection scans the picks already ride out of, and calibration counts
piggyback on the eval batches — zero extra pool passes, zero extra
device syncs, and picks bit-identical with diagnostics on or off
(pinned in tests/test_diagnostics.py).

This module is HOST-PURE by contract: numpy + stdlib only, no jax
import, no device handles.  It consumes arrays that are already host
arrays and produces floats, dicts, and JSON.  The contract is
statically enforced — scripts/al_lint.py's ``diagnostics-inert`` check
reads the ``_DIAGNOSTICS_HOST_PURE`` marker below and forbids jax
imports and device-sync calls here, and forbids strategy/driver code
from touching a ``.diagnostics`` attribute outside a flag-gated
function — so the disabled path stays one None check per site and the
enabled path can never add a hidden device round-trip to a strategy
hot path.

The histogram is the load-bearing structure: FIXED bin edges per score
kind, so bin counts are pure sums — per-chunk partials from the
speculative scorer merge at consume, per-shard partials from a
row-sharded pool would psum, and two rounds' histograms compare without
re-binning.  Merge order never changes a count (integer adds), so the
chunked, sharded, and monolithic histograms are bit-equal (pinned).

Honesty rules for the drift numbers (documented here because a drift
metric that silently lies is worse than none):

  * PSI and JS are only defined over histograms with IDENTICAL specs
    (key/range/bins/transform) — a mismatch raises, never coerces.
  * Fewer than ``MIN_DRIFT_N`` samples on either side returns None
    (the gauge is dropped, not faked): tiny-round noise is not drift.
  * PSI zero-bins are floored at ``PSI_EPS`` (the standard convention);
    JS needs no smoothing (0·log 0 = 0) and is bounded by ln 2.
  * Out-of-range mass clamps into the edge bins (it still counts and
    still drifts); NaNs are dropped and counted in ``n_nan``.
"""

from __future__ import annotations

import json
import math
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# The static host-purity marker scripts/al_lint.py's diagnostics-inert
# check anchors on: this module may never import jax or call a device
# sync (block_until_ready / device_get / device_put).
_DIAGNOSTICS_HOST_PURE = True

# Lock discipline (scripts/al_lint.py lock-discipline): ServeScoreDrift
# is written by the serve executor thread and snapshotted by the asyncio
# server thread — every touch of the live/baseline state holds _lock.
_GUARDED_BY = {"_live": "_lock", "_baseline": "_lock",
               "_baseline_round": "_lock"}

# Fixed-bin specs per score kind: (lo, hi, bins, transform).  The
# bounded scores use their natural range; unbounded non-negative scores
# (MASE radii, k-center squared distances) bin on log1p so one fixed
# ladder covers pixels-to-embedding scales without a data-dependent
# range (which would break cross-round and cross-chunk mergeability).
SCORE_SPECS: Dict[str, Tuple[float, float, int, str]] = {
    "margin": (0.0, 1.0, 64, "none"),
    "confidence": (0.0, 1.0, 64, "none"),
    "entropy": (0.0, 8.0, 64, "none"),
    "min_margin": (0.0, 32.0, 64, "log1p"),
    "kcenter_dist": (0.0, 32.0, 64, "log1p"),
}

# The scalar acquisition score a scoring-pass output dict carries, in
# priority order (min_margin beats margin: the MASE step emits both and
# selects on min_margin).
SCORE_KEY_PRIORITY = ("min_margin", "margin", "confidence", "entropy")

# Below this many samples on either side, drift is None — not a number.
MIN_DRIFT_N = 16
# PSI zero-bin floor (the standard convention; JS needs none).
PSI_EPS = 1e-4
# Calibration bins for the eval-batch piggyback (train/evaluation.py
# imports this so the device counts and the host ECE can never disagree
# on the ladder).
NUM_CAL_BINS = 10


def primary_score_key(out: Dict[str, Any]) -> Optional[str]:
    """The canonical scalar score key of a scoring-pass output dict, or
    None when the pass carries no scalar score (embedding/factor
    passes)."""
    for key in SCORE_KEY_PRIORITY:
        v = out.get(key)
        if v is not None and getattr(v, "ndim", 0) == 1:
            return key
    return None


class ScoreHistogram:
    """A mergeable fixed-bin streaming histogram with exact summary
    accumulators (n/sum/sumsq/min/max are computed on the RAW values, so
    mean/std survive the binning).  Counts are int64 and bin edges are
    fixed at construction: merging is pure integer addition, so chunked
    / sharded / monolithic accumulation orders are bit-equal."""

    __slots__ = ("key", "lo", "hi", "bins", "transform", "counts", "n",
                 "n_nan", "vsum", "vsumsq", "vmin", "vmax")

    def __init__(self, key: str, lo: float, hi: float, bins: int,
                 transform: str = "none"):
        if not hi > lo or bins < 2:
            raise ValueError(f"bad histogram spec ({lo}, {hi}, {bins})")
        if transform not in ("none", "log1p"):
            raise ValueError(f"unknown transform {transform!r}")
        self.key = key
        self.lo = float(lo)
        self.hi = float(hi)
        self.bins = int(bins)
        self.transform = transform
        self.counts = np.zeros(self.bins, dtype=np.int64)
        self.n = 0
        self.n_nan = 0
        self.vsum = 0.0
        self.vsumsq = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    # -- spec / identity --------------------------------------------------

    def spec(self) -> Tuple[str, float, float, int, str]:
        return (self.key, self.lo, self.hi, self.bins, self.transform)

    def same_spec(self, other: "ScoreHistogram") -> bool:
        return self.spec() == other.spec()

    # -- accumulation -----------------------------------------------------

    def add(self, values) -> "ScoreHistogram":
        """Fold host values in.  NaNs are dropped (and counted); mass
        outside [lo, hi] clamps into the edge bins — it still counts and
        still drifts, per the honesty rules."""
        v = np.asarray(values, dtype=np.float64).ravel()
        if v.size == 0:
            return self
        finite = np.isfinite(v)
        self.n_nan += int(v.size - np.count_nonzero(finite))
        v = v[finite]
        if v.size == 0:
            return self
        self.n += int(v.size)
        self.vsum += float(v.sum())
        self.vsumsq += float(np.square(v).sum())
        self.vmin = min(self.vmin, float(v.min()))
        self.vmax = max(self.vmax, float(v.max()))
        t = np.log1p(np.maximum(v, 0.0)) if self.transform == "log1p" \
            else v
        idx = np.floor((t - self.lo) / (self.hi - self.lo) * self.bins)
        idx = np.clip(idx, 0, self.bins - 1).astype(np.int64)
        self.counts += np.bincount(idx, minlength=self.bins
                                   ).astype(np.int64)
        return self

    def merge(self, other: "ScoreHistogram") -> "ScoreHistogram":
        """Integer-exact merge of a partial (per-chunk, per-shard) into
        this one.  Specs must match — a silent re-bin would fabricate
        drift."""
        if not self.same_spec(other):
            raise ValueError(
                f"cannot merge histograms with different specs: "
                f"{self.spec()} vs {other.spec()}")
        self.counts += other.counts
        self.n += other.n
        self.n_nan += other.n_nan
        self.vsum += other.vsum
        self.vsumsq += other.vsumsq
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        return self

    # -- readout ----------------------------------------------------------

    def fractions(self) -> np.ndarray:
        total = int(self.counts.sum())
        if total == 0:
            return np.zeros(self.bins, dtype=np.float64)
        return self.counts / float(total)

    def edges(self) -> np.ndarray:
        """Upper bin edges in TRANSFORMED space ([lo, hi] ladder)."""
        return self.lo + (np.arange(1, self.bins + 1)
                          * (self.hi - self.lo) / self.bins)

    def summary(self) -> Dict[str, Optional[float]]:
        if self.n == 0:
            return {"n": 0, "mean": None, "std": None, "min": None,
                    "max": None}
        mean = self.vsum / self.n
        var = max(0.0, self.vsumsq / self.n - mean * mean)
        return {"n": self.n, "mean": round(mean, 6),
                "std": round(math.sqrt(var), 6),
                "min": round(self.vmin, 6), "max": round(self.vmax, 6)}

    def to_dict(self) -> Dict[str, Any]:
        return {"key": self.key, "lo": self.lo, "hi": self.hi,
                "bins": self.bins, "transform": self.transform,
                "counts": self.counts.tolist(), "n": self.n,
                "n_nan": self.n_nan, "sum": self.vsum,
                "sumsq": self.vsumsq,
                "min": None if self.n == 0 else self.vmin,
                "max": None if self.n == 0 else self.vmax}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ScoreHistogram":
        h = cls(d["key"], d["lo"], d["hi"], d["bins"],
                d.get("transform", "none"))
        h.counts = np.asarray(d["counts"], dtype=np.int64)
        h.n = int(d["n"])
        h.n_nan = int(d.get("n_nan", 0))
        h.vsum = float(d.get("sum", 0.0))
        h.vsumsq = float(d.get("sumsq", 0.0))
        h.vmin = math.inf if d.get("min") is None else float(d["min"])
        h.vmax = -math.inf if d.get("max") is None else float(d["max"])
        return h


def histogram_for(key: str) -> ScoreHistogram:
    """An empty histogram with the canonical spec for a score kind
    (unknown kinds get the log1p ladder — safe for any non-negative
    score)."""
    lo, hi, bins, transform = SCORE_SPECS.get(key, (0.0, 32.0, 64,
                                                   "log1p"))
    return ScoreHistogram(key, lo, hi, bins, transform)


def histogram_from_chunks(key: str, chunks: Sequence) -> ScoreHistogram:
    """Per-chunk partials summed — exactly the accumulation the
    speculative scorer's consume path performs (bit-equal to one add
    over the concatenation; pinned in tests/test_diagnostics.py)."""
    hist = histogram_for(key)
    for c in chunks:
        if isinstance(c, ScoreHistogram):
            hist.merge(c)
        else:
            hist.add(c)
    return hist


# -- drift -------------------------------------------------------------------

def _check_comparable(a: ScoreHistogram, b: ScoreHistogram
                      ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    if not a.same_spec(b):
        raise ValueError(
            f"drift between different histogram specs is undefined: "
            f"{a.spec()} vs {b.spec()}")
    if a.n < MIN_DRIFT_N or b.n < MIN_DRIFT_N:
        return None
    return a.fractions(), b.fractions()


def psi(cur: ScoreHistogram, ref: ScoreHistogram) -> Optional[float]:
    """Population Stability Index of ``cur`` against ``ref``: sum over
    bins of (p - q)·ln(p/q), zero-bins floored at PSI_EPS.  None below
    MIN_DRIFT_N on either side.  Rule of thumb: < 0.1 stable, 0.1-0.25
    shifting, > 0.25 a different population."""
    fracs = _check_comparable(cur, ref)
    if fracs is None:
        return None
    p = np.maximum(fracs[0], PSI_EPS)
    q = np.maximum(fracs[1], PSI_EPS)
    return float(np.sum((p - q) * np.log(p / q)))


def js_divergence(cur: ScoreHistogram, ref: ScoreHistogram
                  ) -> Optional[float]:
    """Jensen–Shannon divergence (nats, bounded by ln 2) — the
    symmetric, smoothing-free companion to PSI (0·log 0 = 0 is exact,
    so no epsilon enters the number)."""
    fracs = _check_comparable(cur, ref)
    if fracs is None:
        return None
    p, q = fracs
    m = 0.5 * (p + q)

    def _kl(a: np.ndarray) -> float:
        nz = a > 0
        return float(np.sum(a[nz] * np.log(a[nz] / m[nz])))

    return 0.5 * _kl(p) + 0.5 * _kl(q)


# -- calibration -------------------------------------------------------------

def ece_from_counts(cal_count, cal_correct, cal_conf_sum
                    ) -> Optional[float]:
    """Expected Calibration Error from the additive per-bin counts the
    eval step emits (train/evaluation.batch_metric_counts):
    sum_b (n_b/N)·|acc_b − conf_b| over populated bins.  None on an
    empty eval set."""
    count = np.asarray(cal_count, dtype=np.float64)
    correct = np.asarray(cal_correct, dtype=np.float64)
    conf = np.asarray(cal_conf_sum, dtype=np.float64)
    n = float(count.sum())
    if n <= 0:
        return None
    nz = count > 0
    gap = np.abs(correct[nz] / count[nz] - conf[nz] / count[nz])
    return float(np.sum(count[nz] / n * gap))


# -- selection composition ---------------------------------------------------

def pick_composition(picks: np.ndarray, targets: Optional[np.ndarray],
                     labeled_mask_before: Optional[np.ndarray],
                     num_classes: int) -> Dict[str, Optional[float]]:
    """Class balance + novelty of one round's picks, from oracle labels
    where the protocol has them (simulated AL always does; None fields
    otherwise):

      class_balance  normalized entropy of the picks' class histogram
                     (1.0 = uniform over classes, 0.0 = one class);
      novelty        fraction of picks whose class had NO labeled
                     example before this round's update.
    """
    n = int(len(picks))
    out: Dict[str, Optional[float]] = {"n_picks": n, "class_balance": None,
                                       "novelty": None}
    if n == 0 or targets is None or num_classes < 2:
        return out
    targets = np.asarray(targets)
    pick_classes = targets[np.asarray(picks, dtype=np.int64)]
    hist = np.bincount(pick_classes, minlength=num_classes
                       ).astype(np.float64)
    fracs = hist / n
    nzf = fracs[fracs > 0]
    ent = float(-np.sum(nzf * np.log(nzf)))
    out["class_balance"] = round(ent / math.log(num_classes), 6)
    if labeled_mask_before is not None:
        seen = np.bincount(targets[np.asarray(labeled_mask_before,
                                              dtype=bool)],
                           minlength=num_classes) > 0
        out["novelty"] = round(float(np.mean(~seen[pick_classes])), 6)
    return out


# -- the per-round accumulator -----------------------------------------------

class RoundDiagnostics:
    """One experiment's acquisition-diagnostics state: the current
    round's accumulators, the previous scored round's histograms (the
    drift reference), and the last finished round's report row.

    Driven single-threaded from the strategy/driver round loop;
    everything it consumes is already a host array.  ``reset_round``
    clears the current round only (the degradation ladder's rollback
    path — the previous round's reference must survive a retried
    attempt)."""

    def __init__(self, num_classes: int = 0):
        self.num_classes = int(num_classes)
        self._cur: Dict[str, ScoreHistogram] = {}
        self._prev: Dict[str, ScoreHistogram] = {}
        self._composition: Optional[Dict[str, Optional[float]]] = None
        self._pick_dists: List[np.ndarray] = []
        self._ece: Optional[float] = None
        self._cal_hist: Optional[List[float]] = None
        self.last_row: Dict[str, Any] = {}

    # -- observations (all host arrays, all cheap) ------------------------

    def observe_scores(self, key: str, values) -> None:
        self._cur.setdefault(key, histogram_for(key)).add(values)

    def observe_histogram(self, key: str, hist: ScoreHistogram) -> None:
        """A pre-merged partial (the speculative consume path hands the
        per-chunk sum straight over)."""
        self._cur.setdefault(key, histogram_for(key)).merge(hist)

    def observe_picks(self, picks, targets, labeled_mask_before) -> None:
        self._composition = pick_composition(
            np.asarray(picks, dtype=np.int64), targets,
            labeled_mask_before, self.num_classes)

    def observe_pick_dists(self, dists) -> None:
        """k-center pick distances (distance-to-labeled at pick time,
        straight out of the selection scan; NaN marks the seed pick).
        They double as the k-center family's drift signal."""
        d = np.asarray(dists, dtype=np.float64).ravel()
        if d.size == 0:
            return
        self._pick_dists.append(d)
        self.observe_scores("kcenter_dist", d)

    def observe_calibration(self, cal_count, cal_correct,
                            cal_conf_sum) -> None:
        self._ece = ece_from_counts(cal_count, cal_correct, cal_conf_sum)
        self._cal_hist = [int(c) for c in np.asarray(cal_count).tolist()]

    # -- round boundary ---------------------------------------------------

    def reset_round(self) -> None:
        """Drop the CURRENT round's accumulators (a failed round attempt
        rolls back and replays; its partial observations must not
        double-count).  The previous round's drift reference survives."""
        self._cur = {}
        self._composition = None
        self._pick_dists = []
        self._ece = None
        self._cal_hist = None

    def finish_round(self, rd: int) -> Dict[str, Optional[float]]:
        """Close the round: drift vs the previous scored round on the
        primary score histogram, score summary stats, composition, pick
        distances, calibration — as the flat gauge dict the driver
        pushes through BOTH metric channels.  Rolls the current
        histograms into the drift reference (a round that scored
        nothing, e.g. a seeded round 0, leaves the reference alone, so
        drift always compares consecutive SCORED rounds)."""
        gauges: Dict[str, Optional[float]] = {}
        key = next((k for k in (*SCORE_KEY_PRIORITY, "kcenter_dist")
                    if k in self._cur), None)
        if key is not None:
            cur = self._cur[key]
            s = cur.summary()
            gauges["rd_score_mean"] = s["mean"]
            gauges["rd_score_std"] = s["std"]
            ref = self._prev.get(key)
            if ref is not None:
                p = psi(cur, ref)
                j = js_divergence(cur, ref)
                gauges["rd_score_drift_psi"] = (None if p is None
                                                else round(p, 6))
                gauges["rd_score_drift_js"] = (None if j is None
                                               else round(j, 6))
        comp = self._composition
        if comp is not None:
            gauges["rd_pick_class_balance"] = comp["class_balance"]
            gauges["rd_pick_novelty"] = comp["novelty"]
        if self._pick_dists:
            d = np.concatenate(self._pick_dists)
            if np.isfinite(d).any():
                gauges["rd_pick_min_dist"] = round(float(np.nanmin(d)), 6)
                gauges["rd_pick_mean_dist"] = round(float(np.nanmean(d)),
                                                    6)
        if self._ece is not None:
            gauges["rd_ece"] = round(self._ece, 6)
        self.last_row = {
            "score_key": key,
            "score": (self._cur[key].summary() if key is not None
                      else None),
            "drift": {"psi": gauges.get("rd_score_drift_psi"),
                      "js": gauges.get("rd_score_drift_js")},
            "composition": comp,
            "pick_dist": {"min": gauges.get("rd_pick_min_dist"),
                          "mean": gauges.get("rd_pick_mean_dist")},
            "calibration": {"ece": gauges.get("rd_ece"),
                            "conf_hist": self._cal_hist},
        }
        if self._cur:
            self._prev = self._cur
        self.reset_round()
        return gauges


# -- serve-side drift --------------------------------------------------------

class ServeScoreDrift:
    """The same histogram/drift machinery, online: the executor folds
    each served batch's acquisition scores into a live histogram; when a
    new checkpoint hot-reloads, the accumulated histogram becomes the
    checkpoint-time BASELINE and a fresh live one starts — the drift
    gauge on /metrics then reads the current model's score distribution
    against the distribution the previous checkpoint served (the online
    drift signal ROADMAP item 3's streaming loop consumes).

    Thread contract: ``observe``/``rebaseline`` run on the executor
    thread, ``snapshot`` on the asyncio server thread — all state under
    ``_lock`` (see _GUARDED_BY)."""

    def __init__(self, key: str = "margin"):
        self.key = key
        self._lock = threading.Lock()
        self._live = histogram_for(key)
        self._baseline: Optional[ScoreHistogram] = None
        self._baseline_round: Optional[int] = None

    def observe(self, values) -> None:
        with self._lock:
            self._live.add(values)

    def rebaseline(self, served_round: Optional[int]) -> None:
        """A new checkpoint took over: what the previous one served is
        now the reference distribution."""
        with self._lock:
            if self._live.n > 0:
                self._baseline = self._live
                self._baseline_round = served_round
            self._live = histogram_for(self.key)

    def snapshot(self) -> Dict[str, Any]:
        # Everything — the dict serialization AND the drift math — runs
        # under the lock: the executor thread's observe() mutates the
        # live histogram's n/counts non-atomically, so reading them
        # outside the lock could expose a count/bucket mismatch or a
        # PSI over half-updated bins to a scrape.  All cheap numpy over
        # a 64-bin vector; contention is nil.
        with self._lock:
            live, base = self._live, self._baseline
            out: Dict[str, Any] = {
                "key": self.key, "live": live.to_dict(),
                "baseline_round": self._baseline_round,
                "psi": None, "js": None,
            }
            if base is not None:
                p = psi(live, base)
                j = js_divergence(live, base)
                out["psi"] = None if p is None else round(p, 6)
                out["js"] = None if j is None else round(j, 6)
        return out


# -- the per-run report artifact ---------------------------------------------

RUN_REPORT_FILE = "run_report.json"


def write_run_report(path: str, header: Dict[str, Any],
                     rows: List[Dict[str, Any]]) -> bool:
    """Atomically persist the per-run report (the label-efficiency curve
    plus this layer's per-round diagnostics).  Never raises — a full
    disk must not kill the run (same contract as the Prometheus scrape
    file)."""
    payload = {"schema": 1, **header, "rounds": rows}
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=1, default=_json_default)
        os.replace(tmp, path)
        return True
    except OSError:
        return False


def read_run_report(path: str) -> Optional[Dict[str, Any]]:
    """The persisted report, or None when absent/unparseable (resume
    merges prior rounds' rows through this)."""
    try:
        with open(path) as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


def resume_report_rows(path: str, exp_hash: Optional[str],
                       start_round: int
                       ) -> tuple[List[Dict[str, Any]], float]:
    """(completed rounds' rows merged back from a prior
    run_report.json, cumulative wall-clock base to continue from) — THE
    resume-merge rule, shared by the batch driver and the stream
    service so the row filter and the monotone-wall-clock contract
    (accuracy-vs-time must not reset to zero across a preemption) can
    never drift between the two writers.  Empty/0.0 when no prior
    report exists or it belongs to a different experiment."""
    prior = read_run_report(path)
    if not prior or prior.get("exp_hash") != exp_hash:
        return [], 0.0
    rows = [r for r in prior.get("rounds", [])
            if isinstance(r, dict) and isinstance(r.get("round"), int)
            and r["round"] < start_round]
    base = max((float(r.get("wall_clock_s") or 0.0) for r in rows),
               default=0.0)
    return rows, base


def _json_default(o: Any):
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, np.generic):
        return o.item()
    return str(o)
