"""The ``status`` CLI verb: a live run summary from heartbeat + metrics.

    python -m active_learning_tpu status --log_dir ./logs

Reads what the run writes anyway — ``heartbeat*.json`` (liveness,
current round/phase/epoch/step) and the tail of ``metrics.jsonl`` (last
test accuracy, phase wall-clocks, step-time percentiles, throughput) —
and renders one screen of state.  No jax import, no backend touch: this
must answer in milliseconds against a wedged run on a loaded host, from
any shell, including one that could never initialize the run's
accelerator.

Staleness is judged from the heartbeat file's MTIME against the
deadline the run embedded in it (``stall_deadline_s``; ``--stale_after``
overrides) — the same contract an external liveness probe would use.
The round journal (``round_journal.json``, faults/journal.py) rides
along when present: round/phase/attempt, the labeled-set digest, and
the active degradation rungs.

Exit codes: 0 = alive (or finished), 2 = no heartbeat found,
3 = stale heartbeat.  With ``--strict`` (the orchestrator contract,
documented in README): 0 = healthy, 2 = no heartbeat, 3 = stale
(staleness beats degradation — no progress is the worse state), 4 =
alive but DEGRADED-MODE-ACTIVE (the journal's ``degrade`` list is
non-empty: the run is making progress on a ladder rung — replicated
pool, host feed, halved batch — and capacity planning should know),
5 = INGEST-STARVED (streaming runs only: the journal shows a WAL
backlog with no round fired inside the deadline — the service is
accepting rows faster than it serves them, or its trigger loop
wedged).  ``--json`` emits the machine-readable summary either way.

Streaming runs (the ``stream`` verb) additionally render a stream tail
— pool rows, WAL backlog, last trigger cause and age — read from the
same journal + heartbeat files.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import time
from typing import Any, Dict, List, Optional

from ..faults.journal import JOURNAL_FILE, read_journal
from . import heartbeat as hb_lib

# How much of metrics.jsonl's tail to scan: enough for several rounds of
# per-epoch telemetry, bounded so a gigabyte stream stays instant.
_TAIL_BYTES = 256 << 10


def get_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m active_learning_tpu status",
        description="Render a live run summary from heartbeat + metrics")
    p.add_argument("--log_dir", type=str, default="./logs",
                   help="the run's --log_dir (holds heartbeat*.json + "
                        "metrics.jsonl)")
    p.add_argument("--stale_after", type=float, default=None,
                   help="staleness deadline in seconds (default: the "
                        "heartbeat's embedded stall_deadline_s)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--strict", action="store_true",
                   help="orchestrator exit-code contract: 0 healthy / "
                        "2 no heartbeat / 3 stale / 4 degraded-mode-"
                        "active (from round_journal.json)")
    return p


def read_metrics_tail(log_dir: str, tail_bytes: int = _TAIL_BYTES
                      ) -> List[Dict[str, Any]]:
    """Parsed events from the tail of metrics.jsonl (whole file when it
    fits).  The first line after a mid-line seek is dropped — it may be
    torn."""
    path = os.path.join(log_dir, "metrics.jsonl")
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as fh:
            if size > tail_bytes:
                fh.seek(size - tail_bytes)
                fh.readline()  # partial line
            raw = fh.read().decode(errors="replace")
    except OSError:
        return []
    events = []
    for line in raw.splitlines():
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(ev, dict):
            events.append(ev)
    return events


def _latest_metrics(events: List[Dict[str, Any]],
                    names: List[str]) -> Dict[str, Any]:
    """{name: (value, step)} of each metric's LAST occurrence."""
    out: Dict[str, Any] = {}
    for ev in events:
        if ev.get("kind") != "metric":
            continue
        for name, value in (ev.get("metrics") or {}).items():
            if name in names:
                out[name] = {"value": value, "step": ev.get("step"),
                             "ts": ev.get("ts")}
    return out


def summarize(log_dir: str, stale_after: Optional[float] = None,
              now: Optional[float] = None) -> Dict[str, Any]:
    """The status payload: heartbeats (with per-file staleness), the
    latest headline metrics, and an overall ok/stale/missing state."""
    now = time.time() if now is None else now
    hb_paths = sorted(glob.glob(os.path.join(log_dir, "heartbeat*.json")))
    heartbeats = []
    any_stale = False
    for path in hb_paths:
        hb = hb_lib.read_heartbeat(path) or {}
        age = hb_lib.heartbeat_age_s(path, now=now)
        deadline = (stale_after if stale_after is not None
                    else float(hb.get("stall_deadline_s", 600.0)))
        finished = hb.get("status") in ("finished", "crashed", "preempted")
        stale = (age is not None and age > deadline and not finished)
        any_stale = any_stale or stale
        heartbeats.append({
            "path": path,
            "age_s": round(age, 1) if age is not None else None,
            "deadline_s": deadline,
            "stale": stale,
            **{k: hb.get(k) for k in ("status", "round", "phase", "epoch",
                                      "step", "spec_phase", "spec_chunk",
                                      "fault_last_site", "degrade",
                                      "process_index", "pid", "progress")},
        })
    events = read_metrics_tail(log_dir)
    metrics = _latest_metrics(events, [
        "rd_test_accuracy", "cumulative_budget", "step_time_ms_p50",
        "step_time_ms_p99", "imgs_per_sec", "pool_rows_per_sec",
        "train_loss_ema", "grad_norm_ema", "hbm_peak_gb",
        "jit_cache_miss_delta", "stall_suspected",
        "fault_retries_total", "degrade_events",
        "rd_query_time", "rd_train_time", "rd_test_time",
        "overlap_frac", "round_vs_max_phase", "spec_hit_frac",
        "rd_score_drift_psi", "rd_score_drift_js", "rd_score_mean",
        "rd_pick_class_balance", "rd_pick_novelty", "rd_ece",
        "pool_disk_rows", "pool_cache_hit_frac", "page_in_rows_per_sec",
        "page_in_stall_ms_p50", "page_in_stall_ms_p99",
    ])
    state = ("no-heartbeat" if not heartbeats
             else "stale" if any_stale else "ok")
    # The round journal (WHERE the run is, and in what mode — see
    # faults/journal.py): the degraded flag drives --strict's exit 4.
    # A terminal status — including a CLEAN preemption — is history, not
    # live capacity loss: exit 4 is for runs still making progress on a
    # rung, never for one that already checkpointed-and-exited.
    journal = read_journal(os.path.join(log_dir, JOURNAL_FILE))
    degraded = bool(journal and journal.get("degrade")
                    and journal.get("status") not in ("finished",
                                                      "crashed",
                                                      "preempted"))
    # The streaming tail (stream/service.py journals these every poll):
    # a NON-EMPTY WAL backlog whose last trigger is older than the
    # staleness deadline means rows are being accepted faster than
    # rounds serve them — --strict's exit 5.  A run that never fired a
    # round yet is judged from the journal's own ts instead (a fresh
    # service warming up is not starved).
    stream = None
    ingest_starved = False
    if journal and journal.get("stream"):
        deadline = (stale_after if stale_after is not None
                    else float((heartbeats[0].get("deadline_s")
                                if heartbeats else None) or 600.0))
        last_trigger = journal.get("stream_last_trigger_ts")
        anchor = last_trigger if last_trigger else journal.get("ts")
        backlog = journal.get("stream_wal_backlog") or 0
        trigger_age = (round(now - anchor, 1) if anchor else None)
        ingest_starved = bool(
            backlog > 0 and trigger_age is not None
            and trigger_age > deadline
            and journal.get("status") not in ("finished", "crashed",
                                              "preempted"))
        stream = {
            "pool_rows_total": journal.get("stream_pool_rows"),
            "wal_backlog_rows": backlog,
            "wal_last_seq": journal.get("stream_wal_seq"),
            "rounds_run": journal.get("stream_rounds_run"),
            "last_trigger_cause": journal.get(
                "stream_last_trigger_cause"),
            "last_trigger_age_s": trigger_age,
            "ingest_starved": ingest_starved,
        }
    return {"log_dir": log_dir, "state": state, "heartbeats": heartbeats,
            "journal": journal, "degraded": degraded, "stream": stream,
            "ingest_starved": ingest_starved, "metrics": metrics}


def render_text(summary: Dict[str, Any]) -> str:
    lines = [f"run status: {summary['state'].upper()}  "
             f"({summary['log_dir']})"]
    for hb in summary["heartbeats"]:
        flag = "STALE" if hb["stale"] else (hb.get("status") or "running")
        # The pipelined round runs TWO phases at once (DESIGN.md §8): the
        # main thread's train/test phase and the speculative scorer's.
        # Both render; an idle scorer is omitted rather than printed.
        keys = ["round", "phase", "epoch", "step"]
        if hb.get("spec_phase") not in (None, "idle"):
            keys += ["spec_phase", "spec_chunk"]
        where = " ".join(
            f"{k}={hb[k]}" for k in keys if hb.get(k) is not None)
        age = f"{hb['age_s']}s ago" if hb["age_s"] is not None else "?"
        proc = (f"p{hb['process_index']}"
                if hb.get("process_index") is not None else "p0")
        lines.append(f"  heartbeat[{proc}] {flag:>8}  {age:>12}  {where}")
    if not summary["heartbeats"]:
        lines.append("  (no heartbeat*.json — run not started, telemetry "
                     "off, or wrong --log_dir)")
    jr = summary.get("journal")
    if jr:
        where = " ".join(f"{k}={jr[k]}" for k in
                         ("status", "round", "phase", "attempt", "labeled")
                         if jr.get(k) is not None)
        lines.append(f"  journal: {where}  (seq {jr.get('seq')})")
        if jr.get("degrade"):
            lines.append("  DEGRADED: active ladder rungs "
                         f"{jr['degrade']} (reverts at the next round "
                         "boundary)")
    st = summary.get("stream")
    if st:
        cause = st.get("last_trigger_cause") or "none yet"
        age = (f"{st['last_trigger_age_s']}s ago"
               if st.get("last_trigger_age_s") is not None else "never")
        lines.append(
            f"  stream: pool_rows={st.get('pool_rows_total')}  "
            f"wal_backlog={st.get('wal_backlog_rows')}  "
            f"rounds={st.get('rounds_run')}  "
            f"last_trigger={cause} ({age})")
        if st.get("ingest_starved"):
            lines.append(
                "  INGEST-STARVED: WAL backlog with no round fired "
                "inside the deadline — the trigger loop is behind (or "
                "wedged)")
    m = summary["metrics"]
    if m:
        lines.append("  latest metrics:")
        for name in ("rd_test_accuracy", "cumulative_budget",
                     "imgs_per_sec", "step_time_ms_p50",
                     "step_time_ms_p99", "pool_rows_per_sec",
                     "train_loss_ema", "grad_norm_ema", "hbm_peak_gb",
                     "jit_cache_miss_delta", "stall_suspected",
                     "fault_retries_total", "degrade_events",
                     "rd_query_time", "rd_train_time", "rd_test_time",
                     "overlap_frac", "round_vs_max_phase",
                     "spec_hit_frac"):
            if name in m:
                e = m[name]
                step = f" @step {e['step']}" if e.get("step") is not None \
                    else ""
                lines.append(f"    {name:>22} = {e['value']}{step}")
        # The drift tail (telemetry/diagnostics.py, DESIGN.md §13),
        # next to the pipeline-health tail: the latest score-drift /
        # composition / calibration readings, so a shell glance shows
        # whether the acquisition distribution is moving — not just
        # whether the machinery is.
        drift_names = ("rd_score_drift_psi", "rd_score_drift_js",
                       "rd_score_mean", "rd_pick_class_balance",
                       "rd_pick_novelty", "rd_ece")
        if any(name in m for name in drift_names):
            lines.append("  drift / acquisition:")
            for name in drift_names:
                if name in m:
                    e = m[name]
                    step = (f" @step {e['step']}"
                            if e.get("step") is not None else "")
                    lines.append(f"    {name:>22} = {e['value']}{step}")
        # The disk-tier tail (data/diskpool.py, DESIGN.md §16): present
        # only when the run pages its pool from disk — spill volume,
        # host-cache hit rate, and page-in stall percentiles, so a
        # glance shows whether the paging tier is keeping up or the
        # round is stalling on reads.
        paging_names = ("pool_disk_rows", "pool_cache_hit_frac",
                        "page_in_rows_per_sec", "page_in_stall_ms_p50",
                        "page_in_stall_ms_p99")
        if any(name in m for name in paging_names):
            lines.append("  disk tier:")
            for name in paging_names:
                if name in m:
                    e = m[name]
                    step = (f" @step {e['step']}"
                            if e.get("step") is not None else "")
                    lines.append(f"    {name:>22} = {e['value']}{step}")
    else:
        lines.append("  (no metrics.jsonl events found)")
    return "\n".join(lines)


def strict_exit_code(summary: Dict[str, Any]) -> int:
    """The ``--strict`` orchestrator contract as a FUNCTION — the fleet
    controller consumes status programmatically (fleet/controller.py)
    through the same code path the CLI exits with, so the two can never
    drift:

      0 = healthy, 2 = no heartbeat, 3 = stale (staleness beats
      degradation — no progress is the worse state), 4 = alive but
      degraded-mode-active, 5 = ingest-starved (streaming only;
      degradation beats it — a run on a rung is already a stronger
      capacity signal).

    Exit 4 lets orchestrators alert on capacity loss without killing a
    self-healing run; exit 5 means the service is alive yet falling
    behind its ingest."""
    if summary["state"] == "no-heartbeat":
        return 2
    if summary["state"] == "stale":
        return 3
    if summary.get("degraded"):
        return 4
    if summary.get("ingest_starved"):
        return 5
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = get_parser().parse_args(argv)
    summary = summarize(args.log_dir, stale_after=args.stale_after)
    code = strict_exit_code(summary)
    if not args.strict and code in (4, 5):
        # Degradation and ingest starvation are --strict refinements of
        # "alive": the lax contract stays 0/2/3 exactly as published.
        code = 0
    if args.as_json:
        # The machine payload carries the exit code it ships with, so a
        # consumer parsing stdout never has to re-derive the contract
        # (and a pipeline that lost the process status still has it).
        print(json.dumps({**summary, "exit_code": code}, indent=1))
    else:
        print(render_text(summary))
    return code


if __name__ == "__main__":
    raise SystemExit(main())
