"""The per-run telemetry runtime: one object owning the tracer, the
heartbeat, the watchdog, the jit-compile counter, and the Prometheus
scrape file, installed process-wide for the duration of a run.

The driver calls ``start_run`` once (after logging setup, before the
stack is built) and ``finish`` at exit; everything between — the
trainer's per-epoch step stats, ``phase_timer``'s ticks, the scoring
engine's chunk spans — reaches the run through ``get_run()`` /
``spans.get_tracer()`` without any plumbing through constructors.  When
no run is installed the default instance is fully inert: ``tick`` is a
no-op, ``train_metrics`` is False (the trainer skips even the
per-step ``perf_counter`` calls), and nothing touches the filesystem —
library users and unit tests see exactly the pre-telemetry behavior.

The jit registry generalizes the serve executor's compile counter
(serve/executor.compile_counts) to the offline stack: the trainer and
strategies register their jitted steps, ``jit_cache_total()`` sums the
live cache sizes, and the driver emits the per-round DELTA — a nonzero
delta after round 1 is a shape leak (the exact regression
tests/test_compile_reuse.py pins, now visible in production metrics
instead of only under test).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from . import heartbeat as hb_lib
from . import prom as prom_lib
from . import spans as spans_lib

# Lock discipline, statically enforced (scripts/al_lint.py
# lock-discipline): gauges and the jit registry are written by the
# driver thread and read by the watchdog/status paths — always under
# the run's _lock.
_GUARDED_BY = {"_gauges": "_lock", "_jits": "_lock",
               "_jit_total_last": "_lock"}


def percentile(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (same convention as serve/metrics.py and
    scripts/serve_loadgen.py, so step-time and latency percentiles are
    comparable numbers); None on empty."""
    if not values:
        return None
    vals = sorted(values)
    idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
    return float(vals[idx])


def hbm_high_water_gb() -> Optional[float]:
    """Peak device HBM in GB via ``memory_stats()`` — None where the
    backend exposes no statistics (CPU, some tunneled runtimes)."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        return round(peak / 2**30, 3) if peak else None
    except Exception:  # noqa: BLE001 - backend-dependent, absence is fine
        return None


class RunTelemetry:
    """Everything one run's telemetry owns.  The inert default (no
    config) records nothing and writes nothing."""

    def __init__(self, cfg=None, tracer: Optional[spans_lib.SpanTracer] = None,
                 heartbeat: Optional[hb_lib.HeartbeatWriter] = None,
                 watchdog: Optional[hb_lib.StallWatchdog] = None,
                 trace_path: Optional[str] = None,
                 prometheus_file: Optional[str] = None,
                 logger=None):
        self.cfg = cfg
        self.tracer = tracer or spans_lib.SpanTracer(enabled=False)
        self.heartbeat = heartbeat
        self.watchdog = watchdog
        self.trace_path = trace_path
        self.prometheus_file = prometheus_file
        self.logger = logger
        # Per-step/per-epoch metric collection in the trainer and the
        # pool-scan rate metric in the strategies key off this.
        self.train_metrics = bool(cfg and getattr(cfg, "enabled", False))
        self._lock = threading.Lock()
        self._gauges: Dict[str, float] = {}
        self._jits: Dict[str, Any] = {}
        self._jit_total_last = 0
        self.finished = False

    # -- progress ----------------------------------------------------------

    def tick(self, force: bool = False, **fields: Any) -> None:
        """One progress event (round/phase/epoch/step...).  Inert when no
        heartbeat is configured."""
        if self.heartbeat is not None:
            self.heartbeat.tick(force=force, **fields)

    # -- gauges / prometheus ----------------------------------------------

    def set_gauges(self, **gauges: Any) -> None:
        with self._lock:
            for k, v in gauges.items():
                if v is None:
                    self._gauges.pop(k, None)
                else:
                    self._gauges[k] = v

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def write_prometheus(self) -> None:
        if not self.prometheus_file:
            return
        text = prom_lib.render(
            prom_lib.gauge_samples(self.gauges(), prefix="al_run_"))
        prom_lib.write_textfile(self.prometheus_file, text)

    # -- jit-compile accounting -------------------------------------------

    def register_jit(self, name: str, fn: Any) -> None:
        """Track a jitted callable's cache size (the serve-side compile
        counter, generalized).  No-op on the inert default so unit-test
        Trainers don't accumulate in a process-global registry."""
        if not self.train_metrics or fn is None:
            return
        with self._lock:
            self._jits[name] = fn

    def jit_cache_sizes(self) -> Dict[str, int]:
        with self._lock:
            jits = dict(self._jits)
        sizes = {}
        for name, fn in jits.items():
            try:
                sizes[name] = int(fn._cache_size())
            except Exception:  # noqa: BLE001 - jax-version-dependent
                pass
        return sizes

    def jit_cache_total(self) -> int:
        return sum(self.jit_cache_sizes().values())

    def jit_cache_delta(self) -> int:
        """Compiles since the last call — the per-round miss delta."""
        total = self.jit_cache_total()
        with self._lock:
            delta = total - self._jit_total_last
            self._jit_total_last = total
        return delta

    # -- lifecycle ---------------------------------------------------------

    def export_trace(self, metadata: Optional[Dict[str, Any]] = None
                     ) -> Optional[str]:
        if not self.trace_path:
            return None
        return self.tracer.export(self.trace_path, metadata=metadata)

    def finish(self, status: str = "finished") -> None:
        """Final heartbeat + trace export + watchdog stop.  Idempotent —
        the driver's exception path and its normal path may both land
        here."""
        if self.finished:
            return
        self.finished = True
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.heartbeat is not None:
            self.heartbeat.write_now(status=status)
        self.export_trace(metadata={"status": status})
        self.write_prometheus()


# -- process-wide install ----------------------------------------------------

_DEFAULT = RunTelemetry()
_CURRENT = _DEFAULT


def get_run() -> RunTelemetry:
    return _CURRENT


def install(rt: RunTelemetry) -> RunTelemetry:
    global _CURRENT
    _CURRENT = rt
    spans_lib.set_tracer(rt.tracer)
    return rt


def uninstall(rt: Optional[RunTelemetry] = None) -> None:
    """Restore the inert default (only if ``rt`` is still the installed
    one — a nested run that already swapped must not be clobbered)."""
    global _CURRENT
    if rt is None or _CURRENT is rt:
        _CURRENT = _DEFAULT
        spans_lib.set_tracer(None)


def start_run(cfg, log_dir: str, process_index: int = 0,
              process_count: int = 1, logger=None,
              on_stall: Optional[Callable[[float], None]] = None
              ) -> RunTelemetry:
    """Build + install a run's telemetry from its TelemetryConfig.

    ``cfg.enabled`` False returns (and installs) an inert runtime — the
    telemetry-off path must add no per-step work anywhere.  Trace export
    and the watchdog are opt-in on top of enabled.
    """
    import os

    if cfg is None or not cfg.enabled:
        rt = RunTelemetry(logger=logger)
        return install(rt)
    suffix = f"_p{process_index}" if process_count > 1 else ""
    heartbeat = hb_lib.HeartbeatWriter(
        os.path.join(log_dir, hb_lib.heartbeat_filename(process_index,
                                                        process_count)),
        every_s=cfg.heartbeat_every_s,
        stall_deadline_s=cfg.stall_deadline_s,
        static_fields={"process_index": process_index,
                       "process_count": process_count,
                       "status": "running"})
    tracer = spans_lib.SpanTracer(enabled=cfg.export_trace)
    trace_path = (os.path.join(log_dir, f"trace{suffix}.json")
                  if cfg.export_trace else None)
    watchdog = None
    if cfg.watchdog:
        def _default_on_stall(stalled_s: float) -> None:
            if logger is not None:
                logger.warning(
                    f"watchdog: no progress for {stalled_s:.0f}s "
                    f"(deadline {cfg.stall_deadline_s:.0f}s) — "
                    "stall suspected")
            tracer.instant("stall_suspected",
                           args={"stalled_s": round(stalled_s, 1)})
        watchdog = hb_lib.StallWatchdog(
            heartbeat, cfg.stall_deadline_s,
            on_stall=on_stall or _default_on_stall)
    rt = RunTelemetry(cfg=cfg, tracer=tracer, heartbeat=heartbeat,
                      watchdog=watchdog, trace_path=trace_path,
                      prometheus_file=cfg.prometheus_file or None,
                      logger=logger)
    install(rt)
    heartbeat.tick(force=True, phase="startup")
    if watchdog is not None:
        watchdog.start()
    return rt
