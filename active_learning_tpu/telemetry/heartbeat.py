"""Liveness: the atomically-rewritten ``heartbeat.json`` + stall watchdog.

A multi-hour pod run whose host stalls (hung NFS, dead tunnel, wedged
collective) previously produced NO signal at all until the outer timeout
killed it.  The heartbeat file is the liveness contract: the driver
ticks it on every progress event (round/phase/epoch/step transitions),
the writer rewrites the file atomically (tmp + rename — a reader polling
mid-run can never see a torn file) at a bounded cadence, and any
external observer — the ``status`` CLI verb, a k8s liveness probe, cron
— reads staleness straight off the file's mtime: older than the
embedded ``stall_deadline_s`` means the process stopped making progress
(or died).

The in-process watchdog is the same check without an external observer:
a daemon thread samples the writer's progress counter and calls
``on_stall`` once per stall episode when it freezes past the deadline
(re-arming when progress resumes).  Both clocks are injectable so the
tests drive a frozen fake clock instead of sleeping.

Per-process on pods: every process writes its own ``heartbeat_p{i}.json``
(process 0 of a single-process run writes plain ``heartbeat.json``), so
a stalled non-coordinator host is visible even while process 0 keeps
ticking.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional


def heartbeat_filename(process_index: int = 0, process_count: int = 1) -> str:
    if process_count > 1:
        return f"heartbeat_p{process_index}.json"
    return "heartbeat.json"


class HeartbeatWriter:
    """Rate-limited atomic rewriter of one heartbeat file.

    ``tick(**fields)`` bumps the progress counter and merges the fields
    (round/phase/epoch/step/...) into the payload; the file is rewritten
    when ``every_s`` has elapsed since the last write (or on
    ``force=True`` — phase transitions force so the file never lags a
    whole cadence behind a phase change).  A tick is one lock + dict
    merge + monotonic compare when rate-limited — cheap enough for the
    per-step call sites.
    """

    def __init__(self, path: str, every_s: float = 5.0,
                 stall_deadline_s: float = 600.0,
                 static_fields: Optional[Dict[str, Any]] = None,
                 time_fn: Callable[[], float] = time.time,
                 monotonic_fn: Callable[[], float] = time.monotonic):
        self.path = path
        self.every_s = float(every_s)
        self.stall_deadline_s = float(stall_deadline_s)
        self._time = time_fn
        self._monotonic = monotonic_fn
        self._lock = threading.Lock()
        self._fields: Dict[str, Any] = dict(static_fields or {})
        self._last_write = float("-inf")
        self.progress = 0  # monotonically increasing; the watchdog's pulse
        self.writes = 0

    def tick(self, force: bool = False, **fields: Any) -> bool:
        """Record progress; rewrite the file if the cadence allows.
        Returns True when the file was (re)written."""
        with self._lock:
            self.progress += 1
            for k, v in fields.items():
                if v is not None:
                    self._fields[k] = v
            now = self._monotonic()
            if not force and now - self._last_write < self.every_s:
                return False
            self._last_write = now
            payload = self._payload()
        self._write(payload)
        return True

    def write_now(self, **fields: Any) -> None:
        """Unconditional rewrite (final status, stall marker)."""
        with self._lock:
            for k, v in fields.items():
                if v is not None:
                    self._fields[k] = v
            self._last_write = self._monotonic()
            payload = self._payload()
        self._write(payload)

    def _payload(self) -> Dict[str, Any]:
        return {
            **self._fields,
            "ts": self._time(),
            "pid": os.getpid(),
            "progress": self.progress,
            "every_s": self.every_s,
            "stall_deadline_s": self.stall_deadline_s,
        }

    def _write(self, payload: Dict[str, Any]) -> None:
        try:
            directory = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(directory, exist_ok=True)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(payload, fh)
            os.replace(tmp, self.path)
            self.writes += 1
        except OSError:
            # Liveness reporting must never take the run down (full disk,
            # yanked NFS) — the log already records real progress.
            pass


def read_heartbeat(path: str) -> Optional[Dict[str, Any]]:
    """The heartbeat payload, or None when absent/unparseable (a torn
    file is impossible by construction; a missing one just means the run
    never started or predates telemetry)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def heartbeat_age_s(path: str, now: Optional[float] = None
                    ) -> Optional[float]:
    """Seconds since the file was last rewritten (mtime-based, so it
    works even when clocks inside the payload drift)."""
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    return (time.time() if now is None else now) - mtime


def is_stale(path: str, deadline_s: Optional[float] = None,
             now: Optional[float] = None) -> Optional[bool]:
    """True when the heartbeat's mtime exceeds the deadline (the file's
    own embedded ``stall_deadline_s`` unless overridden); None when
    there is no heartbeat to judge."""
    age = heartbeat_age_s(path, now=now)
    if age is None:
        return None
    if deadline_s is None:
        hb = read_heartbeat(path) or {}
        deadline_s = float(hb.get("stall_deadline_s", 600.0))
    return age > deadline_s


class StallWatchdog:
    """Daemon thread that fires ``on_stall(stalled_s)`` when the
    heartbeat's progress counter freezes past ``deadline_s``.

    One callback per DEADLINE WINDOW: firing opens a new window, so a
    wedged collective logs one loud event per deadline — not one per
    poll, and (the fixed re-arm edge) not exactly-once-forever either.
    The old rule re-armed only when progress resumed, so a stall that
    NEVER resumed — the same phase, frozen for hours — fired exactly
    once and went quiet, which with ``--watchdog_action degrade`` would
    mean exactly one escalation attempt no matter how wedged the run
    was.  Now each full deadline of continued stall fires another
    episode (``stalled_s`` reports the TOTAL stall, not the window), and
    progress resuming resets everything.  ``check(now)`` is the whole
    decision function — public so tests drive it with a fake clock
    instead of sleeping.
    """

    def __init__(self, heartbeat: HeartbeatWriter, deadline_s: float,
                 on_stall: Callable[[float], None],
                 monotonic_fn: Callable[[], float] = time.monotonic,
                 poll_s: Optional[float] = None):
        self.heartbeat = heartbeat
        self.deadline_s = float(deadline_s)
        self.on_stall = on_stall
        self._monotonic = monotonic_fn
        self.poll_s = float(poll_s if poll_s is not None
                            else max(1.0, deadline_s / 4.0))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_progress = heartbeat.progress
        self._last_change = monotonic_fn()
        self._last_fire: Optional[float] = None
        self.stalls_detected = 0

    def check(self, now: Optional[float] = None) -> bool:
        """One watchdog evaluation; returns True iff a stall fired."""
        now = self._monotonic() if now is None else now
        progress = self.heartbeat.progress
        if progress != self._last_progress:
            self._last_progress = progress
            self._last_change = now
            self._last_fire = None
            return False
        stalled_s = now - self._last_change
        window_start = (self._last_fire if self._last_fire is not None
                        else self._last_change)
        if now - window_start > self.deadline_s:
            self._last_fire = now
            self.stalls_detected += 1
            try:
                self.on_stall(stalled_s)
            except Exception:  # noqa: BLE001 - the watchdog must survive
                pass
            return True
        return False

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run,
                                        name="al-telemetry-watchdog",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
