"""Run-wide telemetry: span traces, per-step train metrics, heartbeats,
and Prometheus exposition.

Four pieces, one install point (DESIGN.md §7):

  * ``spans``     — hierarchical host spans (experiment → round → phase
                    → epoch → collect_pool chunk) exported as Chrome
                    trace-event JSON; ``utils/tracing.phase_timer`` is a
                    thin shim over it, so phase metrics and phase spans
                    are the same measurement.
  * ``runtime``   — the per-run object (``start_run``/``get_run``):
                    gauges, the generalized jit-compile counter, the
                    Prometheus scrape file, lifecycle.
  * ``heartbeat`` — atomically-rewritten ``heartbeat.json`` liveness +
                    the in-process stall watchdog.
  * ``prom``      — the shared Prometheus text encoder (the serve
                    ``/metrics?format=prometheus`` view and the driver
                    scrape file).

``status.py`` is the read side: the ``status`` CLI verb renders a live
run summary from heartbeat + metrics.jsonl with no jax import.

``profiler.py`` is the DEVICE-truth layer on top (DESIGN.md §11):
bounded ``jax.profiler`` capture windows (never whole runs, never round
0), device-op classification + collective-bytes accounting, and the
merged host+device Chrome timeline.  It is the only module allowed to
touch ``jax.profiler`` (trace_lint check 10) and is deliberately NOT
re-exported here — its parsing half imports no jax and is used from
hosts that could never initialize a backend.

Default-on at negligible cost: per-step collection is two perf_counter
calls and a list append; heartbeat ticks are a lock + monotonic compare
when rate-limited.  Trace export and the watchdog are opt-in
(config.TelemetryConfig).  With telemetry off — or outside a driver run
— the installed runtime is inert and the stack behaves exactly as
before telemetry existed (pinned by tests/test_telemetry.py).
"""

from .heartbeat import (HeartbeatWriter, StallWatchdog, heartbeat_age_s,
                        is_stale, read_heartbeat)
from .runtime import (RunTelemetry, get_run, hbm_high_water_gb, install,
                      percentile, start_run, uninstall)
from .spans import Span, SpanTracer, get_tracer, set_tracer

__all__ = [
    "HeartbeatWriter", "StallWatchdog", "heartbeat_age_s", "is_stale",
    "read_heartbeat", "RunTelemetry", "get_run", "hbm_high_water_gb",
    "install", "percentile", "start_run", "uninstall", "Span",
    "SpanTracer", "get_tracer", "set_tracer",
]
