"""Hierarchical host-span tracing with Chrome-trace-event export.

The framework's only run-time timing signal used to be the driver's
round-granularity ``phase_timer`` wall-clocks — nothing between "a round
took 219 s" and a full XLA profiler capture.  This tracer fills that gap
with nested HOST spans (experiment → round → phase → epoch →
collect_pool chunk) recorded at perf_counter resolution and exported as
Chrome trace-event JSON (``trace.json``), loadable in Perfetto or
``chrome://tracing`` with zero extra tooling.  Device-side naming stays
with ``jax.profiler.TraceAnnotation`` (utils/tracing.annotate) — the
two nest: every phase span still wraps its annotation, so an XProf
capture and the host trace describe the same intervals.

Design constraints, each load-bearing:

  * **Timing is unconditional, recording is opt-in.**  ``span()`` always
    measures (``phase_timer`` derives the ``rd_{name}`` metric from the
    SAME span, so metrics and spans cannot fork — scripts/trace_lint.py
    asserts the routing), but events are only appended when the tracer
    is enabled (TelemetryConfig.export_trace).  A disabled span is two
    ``perf_counter`` calls.
  * **Thread-safe, bounded.**  The serve executor, watchdog, and data
    feeder threads may all open spans; events append under a lock and
    the buffer is capped (oldest runs are multi-hour — an unbounded
    event list is a slow leak) with an explicit drop counter.
  * **No jax dependency.**  Importable from the status verb and tests
    without touching a backend.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

# Lock discipline, statically enforced (scripts/al_lint.py
# lock-discipline): the event buffer, its drop counter, and the
# thread-name map are appended from every span-opening thread (main,
# spec-scorer, feed-prefetch, watchdog, serve executor) — always under
# the tracer's _lock.
_GUARDED_BY = {"events": "_lock", "dropped": "_lock",
               "_thread_names": "_lock"}


class Span:
    """One completed (or in-flight) host span."""

    __slots__ = ("name", "args", "t0", "t1", "tid")

    def __init__(self, name: str, args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.args = args
        self.t0 = time.perf_counter()
        self.t1: Optional[float] = None
        self.tid = threading.get_ident()

    @property
    def duration_s(self) -> float:
        end = self.t1 if self.t1 is not None else time.perf_counter()
        return end - self.t0


class SpanTracer:
    """Records nested host spans; exports one Chrome trace per run."""

    def __init__(self, enabled: bool = True, max_events: int = 200_000):
        self.enabled = bool(enabled)
        self.max_events = int(max_events)
        self.events: List[Dict[str, Any]] = []
        self.dropped = 0
        self._lock = threading.Lock()
        self._origin = time.perf_counter()
        self._wall_origin = time.time()
        self._local = threading.local()
        self._thread_names: Dict[int, str] = {}

    # -- span stack (per thread, for nesting introspection) ---------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def depth(self) -> int:
        return len(self._stack())

    # -- recording ---------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, args: Optional[Dict[str, Any]] = None
             ) -> Iterator[Span]:
        """Open a nested span.  Always measures; records only when
        enabled.  The yielded Span's ``duration_s`` is valid after the
        block exits (phase_timer reads it for the metrics sink)."""
        sp = Span(name, args)
        stack = self._stack()
        stack.append(sp)
        try:
            yield sp
        finally:
            sp.t1 = time.perf_counter()
            stack.pop()
            if self.enabled:
                self._record(sp)

    def complete(self, name: str, t0: float, t1: float,
                 args: Optional[Dict[str, Any]] = None) -> None:
        """Record a span retroactively from perf_counter stamps — for
        loop bodies (collect_pool chunks) where a ``with`` per chunk
        would contort the control flow."""
        if not self.enabled:
            return
        sp = Span(name, args)
        sp.t0, sp.t1 = t0, t1
        self._record(sp)

    def name_thread(self, name: str) -> None:
        """Label the CURRENT thread's track in the exported trace (a
        Chrome ``thread_name`` metadata event).  The pipelined round's
        executor threads (spec-scorer, feed-prefetch) call this once at
        start so their spans render as NAMED side-by-side tracks in
        Perfetto next to the main thread's — every thread already gets
        its own ``tid`` (Span stamps ``threading.get_ident()``), which is
        what keeps concurrent spans from corrupting each other's nesting;
        this adds the human-readable label.  Idempotent per (thread,
        name); metadata events don't count against the buffer cap (a
        handful per run, and dropping one would orphan a whole track's
        spans from their label)."""
        if not self.enabled:
            return
        tid = threading.get_ident() % 2**31
        with self._lock:
            if self._thread_names.get(tid) == name:
                return
            self._thread_names[tid] = name
            self.events.append({
                "name": "thread_name", "ph": "M", "pid": os.getpid(),
                "tid": tid, "args": {"name": name},
            })

    def instant(self, name: str, args: Optional[Dict[str, Any]] = None
                ) -> None:
        """A zero-duration marker event (e.g. ``stall_suspected``)."""
        if not self.enabled:
            return
        now = time.perf_counter()
        with self._lock:
            if len(self.events) >= self.max_events:
                self.dropped += 1
                return
            self.events.append({
                "name": name, "ph": "i", "s": "t",
                "ts": (now - self._origin) * 1e6,
                "pid": os.getpid(), "tid": threading.get_ident() % 2**31,
                **({"args": dict(args)} if args else {}),
            })

    def snapshot_events(self) -> List[Dict[str, Any]]:
        """A consistent copy of the recorded events (the profiler's
        per-phase attribution intersects device ops with the host phase
        spans recorded here)."""
        with self._lock:
            return list(self.events)

    @property
    def origin(self) -> float:
        """The perf_counter stamp exported ts values are relative to —
        the device-truth profiler re-bases spliced device events onto
        this axis (telemetry/profiler.splice_into_tracer)."""
        return self._origin

    def splice_events(self, events: List[Dict[str, Any]]) -> int:
        """Append pre-built Chrome events (the profiler's re-based
        device tracks) to the export buffer.  Not counted against
        ``max_events``: the splice is bounded by the profiler's own cap
        (MAX_SPLICED_EVENTS) and dropping host spans to make room for
        device ops — or vice versa — would orphan one half of the very
        merge the splice exists for.  Returns the number appended (0
        when recording is off)."""
        if not self.enabled:
            return 0
        with self._lock:
            self.events.extend(events)
        return len(events)

    def _record(self, sp: Span) -> None:
        event = {
            "name": sp.name, "ph": "X", "cat": "host",
            "ts": (sp.t0 - self._origin) * 1e6,
            "dur": (sp.t1 - sp.t0) * 1e6,
            "pid": os.getpid(), "tid": sp.tid % 2**31,
        }
        if sp.args:
            event["args"] = dict(sp.args)
        with self._lock:
            if len(self.events) >= self.max_events:
                self.dropped += 1
            else:
                self.events.append(event)

    # -- export ------------------------------------------------------------

    def export(self, path: str, metadata: Optional[Dict[str, Any]] = None
               ) -> Optional[str]:
        """Write Chrome trace-event JSON atomically (tmp + rename), so a
        reader polling mid-run never sees a torn file.  Returns the path
        (None when recording is off — nothing to export)."""
        if not self.enabled:
            return None
        with self._lock:
            events = list(self.events)
            dropped = self.dropped
        out = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_origin": self._wall_origin,
                "dropped_events": dropped,
                **(metadata or {}),
            },
        }
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:
            json.dump(out, fh)
        os.replace(tmp, path)
        return path


# The process-wide tracer: disabled (timing-only) until a run installs a
# recording one (telemetry/runtime.start_run).  phase_timer and the
# scoring/trainer span sites all route through this, which is exactly
# what lets one install switch the whole stack.
_TRACER = SpanTracer(enabled=False)


def get_tracer() -> SpanTracer:
    return _TRACER


def set_tracer(tracer: Optional[SpanTracer]) -> SpanTracer:
    """Install (or, with None, reset to the disabled default) the
    process-wide tracer; returns the active instance."""
    global _TRACER
    _TRACER = tracer if tracer is not None else SpanTracer(enabled=False)
    return _TRACER
