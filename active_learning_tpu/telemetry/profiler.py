"""The device-truth layer: bounded XLA profiler capture windows, device-op
classification, collective-bytes accounting, and the merged host+device
timeline (DESIGN.md §11).

Everything the run reports elsewhere is HOST wall-clock — the span
tracer (spans.py), ``mfu_decomposition``, and bench all time dispatch
loops from the host, which cannot distinguish "the device was busy" from
"the host stalled feeding it" or "the collective waited on a peer".
This module is the one place the framework asks the DEVICE what
happened:

  * **Bounded capture windows.**  ``start_capture``/``finish_capture``
    (and the ``capture_window`` context manager over them) arm
    ``jax.profiler.start_trace``/``stop_trace`` around a chosen slice of
    the run — one warm AL round (``--profile_rounds``), a serve window
    under live load (``POST /v1/profile``), or a bench timing loop
    (``AL_BENCH_PROFILE_DIR``).  One window at a time, process-wide;
    never a whole run (a multi-hour trace is unusable and its overhead
    taints every number recorded during it).  This module is the ONLY
    place ``jax.profiler`` may be imported or invoked —
    scripts/trace_lint.py check 10 enforces it statically, the way
    check 9 closes the custom-VJP registry.

  * **Device-op parsing + classification.**  The profiler's trace-viewer
    export (``<host>.trace.json.gz``) is Chrome trace-event JSON whose
    device-side tracks carry one X event per executed XLA op, with
    ``args.hlo_module``/``args.hlo_op`` naming the HLO instruction.
    ``classify_op`` buckets each into compute / collective (psum →
    all-reduce, all_gather, ppermute → collective-permute, ...) /
    transfer (copies, H2D/D2H, infeed) / infra (runtime scaffolding,
    excluded from busy time), and ``summarize_capture`` derives
    ``device_busy_frac`` (fraction of the window with ≥1 device op in
    flight), ``collective_frac``/``transfer_frac`` (share of total
    device-op time), and per-primitive counts and time.

  * **Collective bytes.**  Trace events carry no shapes, but the HLO
    text does: when a capture is armed at run start, ``arm_hlo_dump``
    points ``--xla_dump_to`` at a sidecar directory (XLA latches the
    flag at backend init, so this works from a fresh process — the
    production CLI path — and silently stays empty in a process whose
    backend is already up), and ``hlo_collective_bytes`` parses the
    ``*after_optimizations.txt`` dumps into a {(module, op): bytes}
    table.  Measured execution counts from the trace × exact HLO payload
    bytes = ``collective_bytes_total`` per primitive per round — the
    int8-vs-f32 wire model's first measured byte counts (DESIGN.md §4).

  * **One merged timeline.**  ``splice_into_tracer`` re-bases the device
    events onto the host tracer's clock (via an anchor
    ``TraceAnnotation`` emitted inside the window whose host
    ``perf_counter`` stamp is recorded at emission) and appends them as
    named device tracks, so ONE Perfetto file answers "was the gap host
    stall, H2D, or collective wait" next to the existing host /
    spec-scorer / feed-prefetch tracks.

Parsing and classification are stdlib-only and import no jax — the
tests and ``scripts/perf_report.py`` read capture summaries from hosts
that could never initialize the run's backend.  ``jax.profiler`` is
imported lazily inside the capture entry points only.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

# --------------------------------------------------------------------------
# Capture-window gating (the API trace_lint check 10 pins everything to).
# --------------------------------------------------------------------------

# The anchor annotation emitted inside every window: its trace timestamp
# plus the host perf_counter recorded at emission give the exact offset
# for re-basing device events onto the span tracer's clock.
ANCHOR_NAME = "al_profile_anchor"

# Bound on device events spliced into the merged timeline: a long window
# on a big mesh can carry millions of op events; the merged trace exists
# to answer gap questions, not to archive every op.
MAX_SPLICED_EVENTS = 120_000

# Serve-side bound on a live capture window (seconds).
MAX_SERVE_CAPTURE_S = 30.0

_ACTIVE_LOCK = threading.Lock()
_ACTIVE: Optional["CaptureHandle"] = None


class CaptureBusyError(RuntimeError):
    """A capture window is already open (one at a time, process-wide)."""


class CaptureHandle:
    """An open (or finished) capture window."""

    def __init__(self, out_dir: str, label: str):
        self.out_dir = out_dir
        self.label = label
        self.t0_pc: Optional[float] = None      # window open (perf_counter)
        self.t1_pc: Optional[float] = None      # window close
        self.anchor_pc: Optional[float] = None  # anchor annotation emission
        self.started_wall: Optional[float] = None
        self.session_dir: Optional[str] = None

    @property
    def window_s(self) -> Optional[float]:
        if self.t0_pc is None or self.t1_pc is None:
            return None
        return self.t1_pc - self.t0_pc


def start_capture(out_dir: str, label: str = "capture") -> CaptureHandle:
    """Open the process-wide capture window (raises CaptureBusyError when
    one is already open).  The jax.profiler import is deliberately inside:
    this module must stay importable without a backend."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise CaptureBusyError(
                f"a capture window ({_ACTIVE.label!r}) is already open")
        handle = CaptureHandle(out_dir, label)
        _ACTIVE = handle
    try:
        import jax.profiler
        os.makedirs(out_dir, exist_ok=True)
        handle.started_wall = time.time()
        jax.profiler.start_trace(out_dir)
        handle.t0_pc = time.perf_counter()
        # The re-basing anchor: a zero-work annotation whose host stamp
        # is taken at emission.
        handle.anchor_pc = time.perf_counter()
        with jax.profiler.TraceAnnotation(ANCHOR_NAME):
            pass
    except Exception:
        with _ACTIVE_LOCK:
            _ACTIVE = None
        raise
    return handle


def finish_capture(handle: CaptureHandle) -> CaptureHandle:
    """Close the window (idempotent per handle) and locate the session
    directory the profiler wrote."""
    global _ACTIVE
    try:
        import jax.profiler
        handle.t1_pc = time.perf_counter()
        jax.profiler.stop_trace()
    finally:
        with _ACTIVE_LOCK:
            if _ACTIVE is handle:
                _ACTIVE = None
    handle.session_dir = _newest_session_dir(handle.out_dir)
    return handle


@contextlib.contextmanager
def capture_window(out_dir: str, label: str = "capture"):
    """``with capture_window(dir) as handle: <profiled work>`` — the one
    spelling of a bounded capture.  The trace is stopped on ANY exit
    path (an exception mid-window must not leave the global profiler
    armed for the rest of the process)."""
    handle = start_capture(out_dir, label=label)
    try:
        yield handle
    finally:
        finish_capture(handle)


@contextlib.contextmanager
def trace_annotation(name: str):
    """Name the enclosed host span in device profiler traces; free when
    no trace is active.  ``utils.tracing.annotate`` delegates here — one
    device-naming convention, one module touching jax.profiler."""
    import jax.profiler
    with jax.profiler.TraceAnnotation(name):
        yield


def arm_hlo_dump(dump_dir: str) -> Optional[str]:
    """Point XLA's HLO text dump at ``dump_dir`` for the collective-bytes
    table.  XLA parses ``XLA_FLAGS`` once, at backend initialization
    (verified empirically on jax 0.4.37: set after ``jax.devices()`` the
    flag is inert; set before, every module compiled in the run lands in
    the dump) — so the driver arms this BEFORE its multi-host rendezvous,
    which is the run's first backend touch on the production CLI path.
    In a process whose backend is already up (bench in-process, pytest)
    the env change is silently inert and the byte table stays empty —
    the capture then reports counts/time without bytes rather than
    guessing.  Returns the directory armed, or the one an operator
    already set (their flags are never overridden), or None on failure."""
    flags = os.environ.get("XLA_FLAGS", "")
    existing = re.search(r"--xla_dump_to=(\S+)", flags)
    if existing:
        return existing.group(1)
    try:
        os.makedirs(dump_dir, exist_ok=True)
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_dump_to={dump_dir} "
            "--xla_dump_hlo_as_text").strip()
        return dump_dir
    except OSError:
        return None


# --------------------------------------------------------------------------
# Round selection (--profile_rounds).
# --------------------------------------------------------------------------

# The default window: the FIRST warm round.  Round 0 pays the cold
# compile tax (and, under the pipelined driver, is the arming round), so
# its trace answers "how slow is compilation", not "where does the
# steady-state round go" — captures never arm on round 0.
DEFAULT_PROFILE_ROUNDS = (1,)


def parse_profile_rounds(spec: Optional[str]) -> Tuple[Tuple[int, ...],
                                                       List[int]]:
    """``--profile_rounds`` → (rounds, rejected).  Accepts a
    comma-separated int list or the literal ``warm`` (= the default
    first-warm-round window); round 0 and negatives are REJECTED, never
    armed (returned in ``rejected`` so the caller can log why)."""
    if spec is None or str(spec).strip() in ("", "warm"):
        return DEFAULT_PROFILE_ROUNDS, []
    rounds, rejected = [], []
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        try:
            rd = int(tok)
        except ValueError:
            rejected.append(tok)
            continue
        if rd <= 0:
            rejected.append(rd)
        elif rd not in rounds:
            rounds.append(rd)
    return tuple(sorted(rounds)), rejected


# --------------------------------------------------------------------------
# Trace parsing (stdlib only — no jax).
# --------------------------------------------------------------------------

def _newest_session_dir(out_dir: str) -> Optional[str]:
    """The profiler writes <out_dir>/plugins/profile/<stamp>/; newest
    stamp wins (repeat captures into one dir share the tree)."""
    sessions = glob.glob(os.path.join(out_dir, "plugins", "profile", "*"))
    sessions = [s for s in sessions if os.path.isdir(s)]
    if not sessions:
        return None
    return max(sessions, key=os.path.getmtime)


def find_trace_file(out_dir: str) -> Optional[str]:
    """The trace-viewer JSON (``<host>.trace.json.gz``) of the newest
    session under ``out_dir`` — the artifact carrying hlo_module/hlo_op
    args per device event (the perfetto variant drops them).  Accepts
    either the capture's out_dir or a session directory itself."""
    if glob.glob(os.path.join(out_dir, "*.trace.json.gz")):
        session = out_dir
    else:
        session = _newest_session_dir(out_dir)
    if session is None:
        return None
    traces = [p for p in glob.glob(os.path.join(session, "*.trace.json.gz"))
              if "perfetto" not in os.path.basename(p)]
    return max(traces, key=os.path.getmtime) if traces else None


def parse_trace(path: str) -> Dict[str, Any]:
    """One trace-viewer JSON → {"events": [...], "processes": {pid:
    name}, "threads": {(pid, tid): name}}."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        data = json.load(fh)
    events = data["traceEvents"] if isinstance(data, dict) else data
    processes: Dict[int, str] = {}
    threads: Dict[Tuple[int, int], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            processes[e["pid"]] = (e.get("args") or {}).get("name", "")
        elif e.get("name") == "thread_name":
            threads[(e["pid"], e["tid"])] = (e.get("args") or {}).get(
                "name", "")
    return {"events": events, "processes": processes, "threads": threads}


# Device-track selection.  TPU/GPU planes arrive as /device:* processes
# (keep only the per-device "XLA Ops" line when one exists — the Steps /
# Modules / Framework lines re-describe the same intervals and would
# double-count busy time); the CPU backend has no device plane, so its
# XLA execution threads (the Eigen compute pool + the TfrtCpuClient
# execute threads) stand in for it.
_CPU_DEVICE_THREAD = re.compile(r"^tf_XLA")


def device_tracks(trace: Dict[str, Any]) -> List[Tuple[int, int]]:
    """(pid, tid) pairs whose events are device-side op executions."""
    device_pids = {pid for pid, name in trace["processes"].items()
                   if str(name).startswith("/device:")}
    tracks: List[Tuple[int, int]] = []
    for pid in device_pids:
        tids = [(p, t) for (p, t), _ in trace["threads"].items()
                if p == pid]
        ops_only = [(p, t) for (p, t) in tids
                    if "XLA Ops" in trace["threads"][(p, t)]]
        tracks.extend(ops_only or tids)
    for (pid, tid), name in trace["threads"].items():
        if pid in device_pids:
            continue
        proc = str(trace["processes"].get(pid, ""))
        if proc.startswith("/host:") and _CPU_DEVICE_THREAD.match(
                str(name)):
            tracks.append((pid, tid))
    return tracks


def device_events(trace: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The X events on device tracks, each tagged with its class."""
    tracks = set(device_tracks(trace))
    out = []
    for e in trace["events"]:
        if e.get("ph") != "X" or (e["pid"], e.get("tid")) not in tracks:
            continue
        out.append(dict(e, cls=classify_op(e.get("name", ""))))
    return out


# --------------------------------------------------------------------------
# Classification (DESIGN.md §11's event table).
# --------------------------------------------------------------------------

# HLO collective opcodes, matched as prefixes of the instruction name
# ("all-reduce.1", "all-gather-start.2", "collective-permute-done", ...).
COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all", "collective-broadcast", "ragged-all-to-all",
)
# Data-movement markers: device<->device / host<->device copies, infeed/
# outfeed, and host transfer send/recv.
_TRANSFER_PREFIXES = ("copy", "d2d", "h2d", "d2h", "infeed", "outfeed",
                      "send", "recv", "transfer", "memcpy")
# Runtime scaffolding — never counted as device busy time: these events
# describe the executor driving the ops, not the ops.
_INFRA_MARKERS = ("threadpoollistener", "thunkexecutor", "executehelper",
                  "execute", "parsearguments", "buffer::await",
                  "pjitfunction", "program", "::", "$")


def classify_op(name: str) -> str:
    """One device event name → "collective" | "transfer" | "compute" |
    "infra".  Collectives first (an `all-reduce` IS data movement, but
    its byte accounting is the whole point); infra last-but-one so a
    runtime frame never reads as compute."""
    low = str(name).lower().lstrip("%")
    for op in COLLECTIVE_OPS:
        if low.startswith(op):
            return "collective"
    for p in _TRANSFER_PREFIXES:
        if low.startswith(p):
            return "transfer"
    for m in _INFRA_MARKERS:
        if m in low:
            return "infra"
    return "compute"


def collective_primitive(name: str) -> Optional[str]:
    """"all-reduce-start.17" → "all-reduce"; None for non-collectives."""
    low = str(name).lower().lstrip("%")
    for op in COLLECTIVE_OPS:
        if low.startswith(op):
            return op
    return None


def _is_async_done(name: str) -> bool:
    """The -done half of an async collective pair: its -start twin holds
    the duration and the payload; counting both would double the op."""
    base = str(name).lower().split(".")[0]
    return base.endswith("-done")


# --------------------------------------------------------------------------
# The HLO collective-bytes table.
# --------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}
_SHAPE_RE = re.compile(r"(pred|[a-z]\d+[a-z0-9]*)\[([0-9,]*)\]")
_HLO_MODULE_RE = re.compile(r"^HloModule\s+([^\s,]+)", re.M)


def _shape_bytes(shape_text: str) -> int:
    """Total payload bytes of every array in an HLO result shape (tuple
    shapes sum their members; unknown dtypes contribute 0 rather than
    guess)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        total += n * size
    return total


def _collective_inst_re() -> "re.Pattern":
    pattern = "|".join(re.escape(op) for op in COLLECTIVE_OPS)
    # The async lowering emits '-start'/'-done' pairs; the -start
    # instruction carries the payload shape (and its NAME is what the
    # trace's hlo_op references), so the opcode match must accept it —
    # without this, every collective on the async-lowering platforms
    # (TPU) would land in collective_events_unattributed.
    return re.compile(
        rf"^\s*(?:ROOT\s+)?%?([\w.-]+)\s*=\s*(.+?)\s+"
        rf"({pattern})(?:-start)?\(",
        re.M)


def hlo_text_collective_bytes(text: str) -> Dict[str, int]:
    """{op_name: payload bytes} from ONE optimized-HLO module's text —
    the parsing core of ``hlo_collective_bytes``, exposed so callers
    holding compiled executables directly (``jitted.lower(...)
    .compile().as_text()`` — the pod-tier wire-bytes cross-check in
    tests/test_pod_tier.py and the gradient-sync bench rider) can
    measure collective payload bytes without arming a disk dump."""
    table: Dict[str, int] = {}
    for name, shape_text, _op in _collective_inst_re().findall(text):
        nbytes = _shape_bytes(shape_text)
        if nbytes > 0:
            table[name] = max(table.get(name, 0), nbytes)
    return table


def hlo_collective_bytes(dump_dir: Optional[str]
                         ) -> Dict[Tuple[str, str], int]:
    """{(hlo_module, op_name): payload bytes} from every
    ``*after_optimizations.txt`` under ``dump_dir``.  Payload = the
    instruction's result arrays (per shard, per execution).  When one
    (module, op) pair appears at several sizes (shape-bucketed
    recompiles share a module name), the LARGEST wins — a bound, not a
    fabrication, and flagged by the caller via ambiguity counting."""
    table: Dict[Tuple[str, str], int] = {}
    if not dump_dir or not os.path.isdir(dump_dir):
        return table
    inst_re = _collective_inst_re()
    for path in glob.glob(os.path.join(dump_dir,
                                       "*after_optimizations.txt")):
        try:
            with open(path) as fh:
                text = fh.read()
        except OSError:
            continue
        m = _HLO_MODULE_RE.search(text)
        module = m.group(1) if m else os.path.basename(path)
        for name, shape_text, _op in inst_re.findall(text):
            nbytes = _shape_bytes(shape_text)
            if nbytes <= 0:
                continue
            key = (module, name)
            table[key] = max(table.get(key, 0), nbytes)
    return table


# --------------------------------------------------------------------------
# Summarisation.
# --------------------------------------------------------------------------

def _union_time_us(intervals: List[Tuple[float, float]]) -> float:
    """Total covered time of possibly-overlapping [t0, t1) intervals."""
    if not intervals:
        return 0.0
    intervals.sort()
    total, cur0, cur1 = 0.0, intervals[0][0], intervals[0][1]
    for t0, t1 in intervals[1:]:
        if t0 > cur1:
            total += cur1 - cur0
            cur0, cur1 = t0, t1
        else:
            cur1 = max(cur1, t1)
    return total + (cur1 - cur0)


def summarize_capture(trace: Dict[str, Any], window_s: Optional[float],
                      byte_table: Optional[Dict[Tuple[str, str], int]]
                      = None) -> Dict[str, Any]:
    """The per-window device-truth summary (the numbers the driver emits
    as metrics):

      device_busy_frac   fraction of the window with >= 1 device op in
                         flight (union over device tracks) — low busy
                         under a slow phase means the gap was HOST side;
      collective_frac /  share of total device-op TIME (sum basis: a
      transfer_frac      collective on every chip counts every chip);
      collectives        per-primitive {count, time_ms, bytes} — counts
                         from the trace, bytes = count x the HLO payload
                         of that exact instruction (None when the dump
                         was not armed / the op is unmatched);
      collective_bytes_total  sum over attributed primitives.
    """
    evs = device_events(trace)
    ops = [e for e in evs if e["cls"] != "infra"]
    busy_us = _union_time_us(
        [(e["ts"], e["ts"] + e.get("dur", 0.0)) for e in ops])
    time_by_cls: Dict[str, float] = {}
    for e in ops:
        time_by_cls[e["cls"]] = time_by_cls.get(e["cls"], 0.0) \
            + e.get("dur", 0.0)
    total_op_us = sum(time_by_cls.values())

    byte_table = byte_table or {}
    collectives: Dict[str, Dict[str, Any]] = {}
    unattributed = 0
    for e in ops:
        prim = collective_primitive(e.get("name", ""))
        if prim is None:
            continue
        entry = collectives.setdefault(
            prim, {"count": 0, "time_ms": 0.0, "bytes": 0,
                   "attributed": 0})
        entry["time_ms"] += e.get("dur", 0.0) / 1000.0
        if _is_async_done(e.get("name", "")):
            continue
        entry["count"] += 1
        args = e.get("args") or {}
        key = (args.get("hlo_module", ""),
               args.get("hlo_op") or e.get("name", ""))
        nbytes = byte_table.get(key)
        if nbytes is None:
            unattributed += 1
        else:
            entry["bytes"] += nbytes
            entry["attributed"] += 1
    for entry in collectives.values():
        entry["time_ms"] = round(entry["time_ms"], 3)
        if entry["attributed"] == 0:
            entry["bytes"] = None  # counts measured, payload unknown
        del entry["attributed"]
    bytes_known = [v["bytes"] for v in collectives.values()
                   if v["bytes"] is not None]
    # No collectives executed -> an honest 0; collectives executed but
    # none byte-attributed (dump not armed) -> None, never a guess.
    if not collectives:
        collective_bytes_total: Optional[int] = 0
    elif bytes_known:
        collective_bytes_total = int(sum(bytes_known))
    else:
        collective_bytes_total = None
    window_us = window_s * 1e6 if window_s else None
    return {
        "window_s": round(window_s, 4) if window_s else None,
        "device_event_count": len(evs),
        "device_op_count": len(ops),
        "device_busy_frac": (round(min(1.0, busy_us / window_us), 4)
                             if window_us else None),
        "collective_frac": (round(
            time_by_cls.get("collective", 0.0) / total_op_us, 4)
            if total_op_us > 0 else None),
        "transfer_frac": (round(
            time_by_cls.get("transfer", 0.0) / total_op_us, 4)
            if total_op_us > 0 else None),
        "device_op_time_ms": {cls: round(us / 1000.0, 3)
                              for cls, us in sorted(time_by_cls.items())},
        "collectives": collectives,
        "collective_bytes_total": collective_bytes_total,
        "collective_events_unattributed": unattributed,
        "byte_table_entries": len(byte_table),
    }


# --------------------------------------------------------------------------
# The merged timeline.
# --------------------------------------------------------------------------

# Device tracks splice under synthetic pids well away from any real one:
# the host spans use os.getpid() and the raw trace reuses it too — the
# offset keeps Perfetto rendering them as separate named processes.
DEVICE_PID_BASE = 1 << 30


def _anchor_offset_us(trace: Dict[str, Any], handle: CaptureHandle,
                      host_origin_pc: float) -> Tuple[float, str]:
    """Offset to add to a raw trace ``ts`` to land on the span tracer's
    microsecond axis.  Exact when the anchor annotation survived into
    the trace; else aligned at the window start (sub-ms skew possible,
    recorded in the export metadata)."""
    anchor_host_us = (handle.anchor_pc - host_origin_pc) * 1e6
    for e in trace["events"]:
        if e.get("ph") == "X" and e.get("name") == ANCHOR_NAME:
            return anchor_host_us - e["ts"], "anchor"
    dev = device_events(trace)
    if dev and handle.t0_pc is not None:
        first = min(e["ts"] for e in dev)
        return (handle.t0_pc - host_origin_pc) * 1e6 - first, \
            "window_start"
    return 0.0, "none"


# Slack around the capture window when clipping spliced events (µs):
# events straddling the window edge keep their place; events whose
# timestamps live in a different epoch (some runtime threads carry
# process-lifetime stamps) are dropped instead of rendering as a bogus
# pre-history track.
_WINDOW_CLIP_SLACK_US = 100_000.0


def build_device_track_events(trace: Dict[str, Any],
                              handle: CaptureHandle,
                              host_origin_pc: float,
                              max_events: int = MAX_SPLICED_EVENTS
                              ) -> Tuple[List[Dict[str, Any]], int, str]:
    """Chrome events (metadata + re-based device OPS) ready to splice
    into the host trace; returns (events, dropped, alignment).  Only
    compute/collective/transfer ops splice — runtime scaffolding (the
    infra class, ThreadpoolListener at ~50 events per dispatched op on
    CPU) would flood the cap with tracks that answer nothing — and ops
    re-based outside the capture window (± slack) are dropped: a
    handful of runtime threads stamp against a different epoch, and a
    merged timeline with one track offset by minutes is worse than a
    missing one."""
    offset_us, alignment = _anchor_offset_us(trace, handle,
                                             host_origin_pc)
    lo = hi = None
    if handle.t0_pc is not None and handle.t1_pc is not None:
        lo = ((handle.t0_pc - host_origin_pc) * 1e6
              - _WINDOW_CLIP_SLACK_US)
        hi = ((handle.t1_pc - host_origin_pc) * 1e6
              + _WINDOW_CLIP_SLACK_US)
    ops = [e for e in device_events(trace) if e["cls"] != "infra"]
    pid_map: Dict[int, int] = {}
    out: List[Dict[str, Any]] = []
    dropped = 0
    n_ops = 0
    body: List[Dict[str, Any]] = []
    used_tracks = set()
    for e in ops:
        ts = e["ts"] + offset_us
        if lo is not None and not (lo <= ts <= hi):
            dropped += 1
            continue
        if n_ops >= max_events:
            dropped += 1
            continue
        n_ops += 1
        used_tracks.add((e["pid"], e.get("tid")))
        mapped = pid_map.setdefault(e["pid"],
                                    DEVICE_PID_BASE + len(pid_map))
        ev = {"name": e.get("name", "?"), "ph": "X", "cat": "device",
              "ts": ts, "dur": e.get("dur", 0.0),
              "pid": mapped, "tid": e.get("tid", 0) % 2**31,
              "args": {"class": e["cls"]}}
        args = e.get("args") or {}
        if args.get("hlo_module"):
            ev["args"]["hlo_module"] = args["hlo_module"]
        body.append(ev)
    # Metadata only for tracks that actually contributed ops (an empty
    # named track per threadpool thread is visual noise).
    for pid in sorted(pid_map):
        proc = str(trace["processes"].get(pid, f"pid{pid}"))
        out.append({"name": "process_name", "ph": "M",
                    "pid": pid_map[pid],
                    "args": {"name": f"XLA device ops ({proc})"}})
    for pid, tid in sorted(used_tracks):
        out.append({"name": "thread_name", "ph": "M",
                    "pid": pid_map[pid], "tid": (tid or 0) % 2**31,
                    "args": {"name": str(
                        trace["threads"].get((pid, tid), tid))}})
    return out + body, dropped, alignment


def splice_into_tracer(tracer, trace: Dict[str, Any],
                       handle: CaptureHandle
                       ) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Append the capture's device tracks to the span tracer so the next
    export is the merged host+device timeline.  Returns (splice stats,
    the re-based device op events) — the ops feed the per-phase
    attribution, already on the host time axis.  The ONE spelling of
    the splice: RoundProfiler.finalize calls this, not a copy."""
    events, dropped, alignment = build_device_track_events(
        trace, handle, tracer.origin)
    spliced = tracer.splice_events(events)
    stats = {"spliced_events": spliced, "device_events_dropped": dropped,
             "alignment": alignment}
    return stats, [e for e in events if e.get("ph") == "X"]


def phase_device_attribution(host_events: List[Dict[str, Any]], rd: int,
                             device_ops: List[Dict[str, Any]]
                             ) -> Dict[str, Dict[str, float]]:
    """Per-PHASE device attribution: intersect the re-based device ops
    with round ``rd``'s host phase spans (query_time / train_time /
    test_time / ... — the phase_timer spans already in the tracer), so
    "was the gap host stall or collective wait" has a NUMBER per phase,
    not just a picture: {phase: {busy_frac, collective_frac,
    device_ms}}.  ``device_ops`` are chrome X events on the HOST time
    axis (build_device_track_events output)."""
    spans = {}
    for e in host_events:
        if e.get("ph") != "X" or not str(e.get("name", "")).endswith(
                "_time"):
            continue
        if (e.get("args") or {}).get("round") != rd:
            continue
        spans[e["name"]] = (e["ts"], e["ts"] + e.get("dur", 0.0))
    ops = [e for e in device_ops if e.get("ph") == "X"]
    out: Dict[str, Dict[str, float]] = {}
    for name, (t0, t1) in spans.items():
        if t1 <= t0:
            continue
        clipped = []
        coll_us = total_us = 0.0
        for e in ops:
            a = max(e["ts"], t0)
            b = min(e["ts"] + e.get("dur", 0.0), t1)
            if b <= a:
                continue
            clipped.append((a, b))
            total_us += b - a
            if (e.get("args") or {}).get("class") == "collective":
                coll_us += b - a
        busy = _union_time_us(clipped)
        out[name] = {
            "busy_frac": round(busy / (t1 - t0), 4),
            "collective_frac": (round(coll_us / total_us, 4)
                                if total_us > 0 else None),
            "device_ms": round(total_us / 1000.0, 3),
        }
    return out


# --------------------------------------------------------------------------
# The driver hook: bounded per-round capture windows.
# --------------------------------------------------------------------------

def round_scope(rp: Optional["RoundProfiler"], rd: int, **kwargs):
    """The driver's per-round hook: a null context (two attribute reads)
    when profiling is unarmed or the round is not selected — the
    off-path cost tests/test_profiler.py bounds — else the capture
    window.  Round 0 can never arm (RoundProfiler.should_capture)."""
    if rp is None or not rp.should_capture(rd):
        return contextlib.nullcontext()
    return rp.round_capture(rd, **kwargs)


class RoundProfiler:
    """Owns a run's ``--profile_rounds`` windows: which rounds capture,
    where artifacts land, the HLO byte table, and the post-capture
    splice + metric emission."""

    def __init__(self, profile_dir: str,
                 rounds: Sequence[int] = DEFAULT_PROFILE_ROUNDS,
                 hlo_dump_dir: Optional[str] = None, logger=None):
        self.profile_dir = profile_dir
        self.rounds = tuple(int(r) for r in rounds)
        self.hlo_dump_dir = hlo_dump_dir
        self.logger = logger
        self.captures: Dict[int, Dict[str, Any]] = {}

    def should_capture(self, rd: int) -> bool:
        # Round 0 is the compile-tax round: never armed, whatever the
        # spec said (parse_profile_rounds already rejects it; this is
        # the second lock on the same door).
        return rd != 0 and rd in self.rounds

    @contextlib.contextmanager
    def round_capture(self, rd: int, tracer=None, sink=None,
                      telemetry=None):
        """One round's capture window + post-processing.  Post-capture
        failures (parse, splice, IO) are logged and swallowed — the
        profiler observes the round, it must never cost one."""
        out_dir = os.path.join(self.profile_dir, f"round_{rd}")
        if self.logger:
            self.logger.info(
                f"profiler: capture window armed for round {rd} "
                f"-> {out_dir}")
        with capture_window(out_dir, label=f"round_{rd}") as handle:
            yield handle
        try:
            summary = self.finalize(rd, handle, tracer=tracer, sink=sink,
                                    telemetry=telemetry)
            if self.logger and summary:
                self.logger.info(
                    "profiler: round %d device_busy_frac=%s "
                    "collective_frac=%s collective_bytes_total=%s (%s)"
                    % (rd, summary.get("device_busy_frac"),
                       summary.get("collective_frac"),
                       summary.get("collective_bytes_total"),
                       summary.get("summary_path")))
        except Exception as e:  # noqa: BLE001 - observe, never cost
            if self.logger:
                self.logger.warning(
                    f"profiler: round-{rd} capture post-processing "
                    f"failed: {e!r}")

    def finalize(self, rd: int, handle: CaptureHandle, tracer=None,
                 sink=None, telemetry=None) -> Optional[Dict[str, Any]]:
        """Parse + classify + bytes + splice + emit for one window."""
        trace_path = find_trace_file(handle.out_dir)
        if trace_path is None:
            if self.logger:
                self.logger.warning(
                    f"profiler: no trace file under {handle.out_dir} — "
                    "capture produced nothing to merge")
            return None
        trace = parse_trace(trace_path)
        byte_table = hlo_collective_bytes(self.hlo_dump_dir)
        summary = summarize_capture(trace, handle.window_s, byte_table)
        summary["round"] = rd
        summary["trace_path"] = trace_path
        if tracer is not None and getattr(tracer, "enabled", False):
            # One splice serves both consumers: the merged timeline AND
            # the per-phase attribution (device ops vs the round's host
            # phase spans, already on the same axis).
            summary["merge"], ops = splice_into_tracer(tracer, trace,
                                                       handle)
            summary["phase_attribution"] = phase_device_attribution(
                tracer.snapshot_events(), rd, ops)
        summary_path = os.path.join(handle.out_dir,
                                    f"device_profile_rd{rd}.json")
        try:
            with open(summary_path, "w") as fh:
                json.dump(summary, fh, indent=1)
            summary["summary_path"] = summary_path
        except OSError:
            pass
        self.captures[rd] = summary
        self.emit_metrics(rd, summary, sink=sink, telemetry=telemetry)
        return summary

    def emit_metrics(self, rd: int, summary: Dict[str, Any], sink=None,
                     telemetry=None) -> Dict[str, float]:
        """The device-truth metric set, through the MetricsSink AND the
        Prometheus gauges (the scrape-file completeness contract —
        every per-round metric rides both)."""
        metrics: Dict[str, float] = {}
        for name in ("device_busy_frac", "collective_frac",
                     "transfer_frac", "collective_bytes_total"):
            if summary.get(name) is not None:
                metrics[name] = summary[name]
        for prim, entry in (summary.get("collectives") or {}).items():
            slug = prim.replace("-", "_")
            metrics[f"collective_count_{slug}"] = entry["count"]
            if entry.get("bytes") is not None:
                metrics[f"collective_bytes_{slug}"] = entry["bytes"]
        if sink is not None:
            for name, value in metrics.items():
                sink.log_metric(name, value, step=rd)
        if telemetry is not None:
            telemetry.set_gauges(**metrics)
        return metrics


def serve_capture(out_dir: str, seconds: float) -> Dict[str, Any]:
    """The serve verb's bounded live-load capture (blocking; the server
    runs it off the event loop): open the window, sleep, close, parse,
    summarize, write the summary next to the trace.  Device events are
    whatever the executor dispatched during the window."""
    seconds = max(0.05, min(float(seconds), MAX_SERVE_CAPTURE_S))
    with capture_window(out_dir, label="serve") as handle:
        time.sleep(seconds)
    trace_path = find_trace_file(out_dir)
    if trace_path is None:
        return {"ok": False, "error": "capture produced no trace file",
                "out_dir": out_dir}
    summary = summarize_capture(parse_trace(trace_path), handle.window_s)
    summary["trace_path"] = trace_path
    path = os.path.join(out_dir, "device_profile_serve.json")
    try:
        with open(path, "w") as fh:
            json.dump(summary, fh, indent=1)
    except OSError:
        pass
    return {"ok": True, "out_dir": out_dir, "summary_path": path,
            **summary}
