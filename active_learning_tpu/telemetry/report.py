"""The ``report`` CLI verb: render a run's label-efficiency curve, or a
cross-run strategy comparison at matched label budgets — the paper's
headline figure (accuracy per strategy per budget) as a machine-
generated artifact.

    python -m active_learning_tpu report <log_dir>
    python -m active_learning_tpu report <log_dir_a> <log_dir_b> ...
    python scripts/run_report.py --selftest

Reads what the driver writes anyway: ``run_report.json`` (the per-round
rows the round loop atomically rewrites — experiment/driver.py,
DESIGN.md §13), falling back to reconstructing the curve from
``metrics.jsonl`` for experiment dirs that predate the report artifact.
Same contract as the ``status`` verb: stdlib only, no jax import,
answers in milliseconds from any shell.

Comparison mode tabulates N experiment dirs at MATCHED budgets: a row
per cumulative label budget every run reached, a column per run, best
accuracy starred.  Runs whose budget grids never intersect fall back to
the union grid with blanks — stated in the output, never silently
interpolated.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Any, Dict, List, Optional

RUN_REPORT_FILE = "run_report.json"

# The per-round columns of the single-run table: (header, row -> cell).
_COLUMNS = (
    ("round", lambda r: r.get("round")),
    ("labeled", lambda r: r.get("labeled")),
    ("budget", lambda r: _int_or_none(r.get("cumulative_budget"))),
    ("accuracy", lambda r: _fmt(r.get("test_accuracy"), 4)),
    ("round_s", lambda r: _fmt(r.get("round_time_s"), 1)),
    ("wall_s", lambda r: _fmt(r.get("wall_clock_s"), 1)),
    ("drift_psi", lambda r: _fmt((r.get("drift") or {}).get("psi"), 4)),
    ("drift_js", lambda r: _fmt((r.get("drift") or {}).get("js"), 4)),
    ("balance", lambda r: _fmt((r.get("composition") or {})
                               .get("class_balance"), 3)),
    ("novelty", lambda r: _fmt((r.get("composition") or {})
                               .get("novelty"), 3)),
    ("ece", lambda r: _fmt((r.get("calibration") or {}).get("ece"), 4)),
)

# Streaming-run columns, appended only when any row carries a "stream"
# block (the stream service's run_report rows, stream/service.py): the
# trigger cause + ingest/backlog/ack-latency joins — what the service
# did BETWEEN rounds, beside what the rounds cost.
_STREAM_COLUMNS = (
    ("trigger", lambda r: (r.get("stream") or {}).get("trigger_cause")),
    ("ingested", lambda r: _int_or_none(
        (r.get("stream") or {}).get("ingest_rows_total"))),
    ("backlog", lambda r: _int_or_none(
        (r.get("stream") or {}).get("wal_backlog_rows"))),
    ("ack_p99", lambda r: _fmt(
        (r.get("stream") or {}).get("ack_ms_p99"), 1)),
)


def _fmt(v: Any, digits: int) -> Optional[str]:
    if v is None:
        return None
    try:
        return f"{float(v):.{digits}f}"
    except (TypeError, ValueError):
        return None


def _int_or_none(v: Any) -> Optional[int]:
    try:
        return int(v)
    except (TypeError, ValueError):
        return None


def _report_path(path: str) -> str:
    return path if path.endswith(".json") else os.path.join(
        path, RUN_REPORT_FILE)


def _rows_from_metrics_jsonl(log_dir: str) -> List[Dict[str, Any]]:
    """Reconstruct the label-efficiency rows from metrics.jsonl — the
    fallback for experiment dirs older than the run_report artifact.
    Scans the WHOLE file (this is an offline reporting tool, not the
    status tail)."""
    path = os.path.join(log_dir, "metrics.jsonl")
    per_round: Dict[int, Dict[str, Any]] = {}
    wanted = {"rd_test_accuracy": "test_accuracy",
              "cumulative_budget": "cumulative_budget",
              "rd_round_time": "round_time_s",
              "rd_score_drift_psi": ("drift", "psi"),
              "rd_score_drift_js": ("drift", "js"),
              "rd_pick_class_balance": ("composition", "class_balance"),
              "rd_pick_novelty": ("composition", "novelty"),
              "rd_ece": ("calibration", "ece")}
    # The rotated predecessor first, so the live file's rows win.
    for name in ("metrics.jsonl.1", "metrics.jsonl"):
        try:
            fh = open(os.path.join(log_dir, name))
        except OSError:
            continue
        with fh:
            for line in fh:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(ev, dict) or ev.get("kind") != "metric":
                    continue
                step = ev.get("step")
                if not isinstance(step, (int, float)) or step < 0:
                    continue
                rd = int(step)
                for name_, dest in wanted.items():
                    if name_ not in (ev.get("metrics") or {}):
                        continue
                    row = per_round.setdefault(rd, {"round": rd})
                    value = ev["metrics"][name_]
                    if isinstance(dest, tuple):
                        row.setdefault(dest[0], {})[dest[1]] = value
                    else:
                        row[dest] = value
    return [per_round[rd] for rd in sorted(per_round)
            if "test_accuracy" in per_round[rd]
            or "cumulative_budget" in per_round[rd]]


def load_run(path: str) -> Optional[Dict[str, Any]]:
    """One experiment's report payload from a log dir (or a direct
    run_report.json path), with the metrics.jsonl fallback.  None when
    the dir holds neither."""
    report_path = _report_path(path)
    payload = None
    try:
        with open(report_path) as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        payload = None
    if isinstance(payload, dict) and payload.get("rounds"):
        payload.setdefault("source", report_path)
        return payload
    log_dir = path if os.path.isdir(path) else os.path.dirname(path)
    rows = _rows_from_metrics_jsonl(log_dir)
    if not rows:
        return None
    return {"schema": 0, "exp_name": os.path.basename(
                os.path.normpath(log_dir)),
            "strategy": None, "rounds": rows,
            "source": os.path.join(log_dir, "metrics.jsonl")}


def run_label(run: Dict[str, Any]) -> str:
    name = run.get("exp_name") or "run"
    strategy = run.get("strategy")
    return f"{name}[{strategy}]" if strategy else str(name)


def _table(headers: List[str], rows: List[List[Optional[str]]]) -> str:
    cells = [[("-" if c is None else str(c)) for c in row]
             for row in rows]
    widths = [max(len(h), *(len(r[i]) for r in cells)) if cells
              else len(h) for i, h in enumerate(headers)]
    lines = ["  ".join(h.rjust(w) for h, w in zip(headers, widths))]
    for row in cells:
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
    return "\n".join(lines)


def render_single(run: Dict[str, Any]) -> str:
    cols = list(_COLUMNS)
    streaming = any(isinstance(r.get("stream"), dict)
                    for r in run["rounds"])
    if streaming:
        cols += list(_STREAM_COLUMNS)
    rows = [[fn(r) for _, fn in cols] for r in run["rounds"]]
    head = (f"run report: {run_label(run)}  "
            f"(dataset={run.get('dataset')}, seed={run.get('run_seed')}, "
            + ("stream, " if streaming or run.get("stream") else "")
            + f"source={run.get('source')})")
    return head + "\n" + _table([h for h, _ in cols], rows)


def accuracy_by_budget(run: Dict[str, Any]) -> Dict[int, float]:
    """{cumulative budget: test accuracy} over the run's rounds (the
    label-efficiency curve's support points; rounds without a test
    accuracy are skipped)."""
    out: Dict[int, float] = {}
    for r in run.get("rounds", []):
        budget = _int_or_none(r.get("cumulative_budget"))
        acc = r.get("test_accuracy")
        if budget is not None and isinstance(acc, (int, float)):
            out[budget] = float(acc)
    return out


def render_compare(runs: List[Dict[str, Any]],
                   budgets: Optional[List[int]] = None) -> str:
    """The strategy-comparison table at matched budgets: one row per
    budget, one column per run, best accuracy starred."""
    curves = [accuracy_by_budget(r) for r in runs]
    labels = [run_label(r) for r in runs]
    if budgets:
        grid = sorted(budgets)
        note = "requested budgets"
    else:
        common = set(curves[0]) if curves else set()
        for c in curves[1:]:
            common &= set(c)
        if common:
            grid = sorted(common)
            note = "budgets matched across all runs"
        else:
            grid = sorted(set().union(*curves)) if curves else []
            note = ("no common budget grid — union shown, blanks where "
                    "a run never reached that budget")
    rows = []
    for b in grid:
        accs = [c.get(b) for c in curves]
        best = max((a for a in accs if a is not None), default=None)
        cells: List[Optional[str]] = [b]
        for a in accs:
            if a is None:
                cells.append(None)
            else:
                star = " *" if best is not None and a == best else ""
                cells.append(f"{a:.4f}{star}")
        rows.append(cells)
    head = (f"strategy comparison at matched label budgets "
            f"({note}; * best at that budget)")
    return head + "\n" + _table(["budget"] + labels, rows)


def compare_payload(runs: List[Dict[str, Any]]) -> Dict[str, Any]:
    return {"runs": [{"label": run_label(r), "source": r.get("source"),
                      "curve": accuracy_by_budget(r)} for r in runs]}


# -- selftest ----------------------------------------------------------------

def _selftest() -> int:
    """Build two synthetic experiment dirs, render both modes, assert
    the artifacts say what they must — the preflight gate's last link
    (scripts/preflight.sh)."""
    import tempfile

    def fake_run(root: str, name: str, strategy: str,
                 accs: List[float]) -> str:
        d = os.path.join(root, name)
        os.makedirs(d)
        rows = [{"round": i, "labeled": 16 * (i + 1),
                 "cumulative_budget": 16 * (i + 1),
                 "test_accuracy": a, "round_time_s": 1.0 + i,
                 "wall_clock_s": 2.0 * (i + 1),
                 "drift": {"psi": 0.01 * i if i else None,
                           "js": 0.005 * i if i else None}}
                for i, a in enumerate(accs)]
        with open(os.path.join(d, RUN_REPORT_FILE), "w") as fh:
            json.dump({"schema": 1, "exp_name": name,
                       "strategy": strategy, "rounds": rows}, fh)
        return d

    def fake_stream_run(root: str) -> str:
        d = os.path.join(root, "stream_run")
        os.makedirs(d)
        rows = [{"round": i, "labeled": 16 * (i + 1),
                 "cumulative_budget": 16 * (i + 1),
                 "test_accuracy": 0.3 + 0.1 * i, "round_time_s": 1.0,
                 "wall_clock_s": 2.0 * (i + 1),
                 "stream": {"trigger_cause":
                            ("bootstrap" if i == 0 else "watermark"),
                            "ingest_rows_total": 64 * i,
                            "wal_backlog_rows": 0,
                            "ack_ms_p99": 3.5}}
                for i in range(3)]
        with open(os.path.join(d, RUN_REPORT_FILE), "w") as fh:
            json.dump({"schema": 1, "exp_name": "stream_run",
                       "strategy": "MarginSampler", "stream": True,
                       "rounds": rows}, fh)
        return d

    with tempfile.TemporaryDirectory() as root:
        a = fake_run(root, "margin_run", "MarginSampler",
                     [0.30, 0.52, 0.61])
        b = fake_run(root, "coreset_run", "CoresetSampler",
                     [0.28, 0.55, 0.60])
        ra, rb = load_run(a), load_run(b)
        assert ra is not None and rb is not None
        single = render_single(ra)
        assert "margin_run[MarginSampler]" in single
        assert "0.5200" in single and "drift_psi" in single
        # Offline runs never grow the streaming columns...
        assert "trigger" not in single
        # ...streaming runs render them (cause + ingest/ack joins).
        rs = load_run(fake_stream_run(root))
        assert rs is not None
        stream_single = render_single(rs)
        assert "trigger" in stream_single and "ack_p99" in stream_single
        assert "watermark" in stream_single and "3.5" in stream_single
        table = render_compare([ra, rb])
        assert "matched" in table
        assert "0.5500 *" in table, table  # coreset wins at budget 32
        assert "0.6100 *" in table, table  # margin wins at budget 48
        # A dir with neither artifact is a None, not a crash.
        empty = os.path.join(root, "empty")
        os.makedirs(empty)
        assert load_run(empty) is None
    print("run_report selftest: ok")
    return 0


def get_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m active_learning_tpu report",
        description="Render per-run label-efficiency reports and "
                    "cross-run strategy comparisons at matched budgets")
    p.add_argument("dirs", nargs="*",
                   help="experiment log dirs (holding run_report.json "
                        "or metrics.jsonl); one = the run's curve, "
                        "several = the comparison table")
    p.add_argument("--budgets", type=str, default=None,
                   help="comma-separated budgets to compare at "
                        "(default: every budget all runs reached)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--selftest", action="store_true",
                   help="self-contained smoke over synthetic runs "
                        "(the preflight gate's last link); exits 0/1")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = get_parser().parse_args(argv)
    if args.selftest:
        try:
            return _selftest()
        except AssertionError as exc:
            print(f"run_report selftest FAILED: {exc}")
            return 1
    if not args.dirs:
        get_parser().print_usage()
        return 2
    runs = []
    for d in args.dirs:
        run = load_run(d)
        if run is None:
            print(f"report: no run_report.json or metrics.jsonl "
                  f"under {d!r}")
            return 2
        runs.append(run)
    if args.as_json:
        payload = (runs[0] if len(runs) == 1 else compare_payload(runs))
        print(json.dumps(payload, indent=1))
        return 0
    if len(runs) == 1:
        print(render_single(runs[0]))
        return 0
    budgets = ([int(b) for b in args.budgets.split(",") if b.strip()]
               if args.budgets else None)
    print(render_compare(runs, budgets=budgets))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
