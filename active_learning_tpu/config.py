"""Typed configuration for the TPU-native active-learning framework.

Replaces the reference's argparse + ``arg_pools`` dict + ``eval()``-string
system (reference: src/utils/parser.py, src/arg_pools/*.py, and the
``eval(f"optim.{...}")`` calls at src/query_strategies/strategy.py:345-350)
with explicit dataclasses and registries.  No ``eval``/``exec`` anywhere.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple

# Fallback budget for device-resident acquisition-scoring pools when the
# backend exposes no HBM statistics to auto-size from (CPU, some tunneled
# runtimes) — see TrainConfig.resident_scoring_bytes and
# parallel/resident.resolve_budget.
RESIDENT_SCORING_BYTES_DEFAULT = 2 ** 31


@dataclasses.dataclass(frozen=True)
class LoaderConfig:
    """Host->device input-pipeline parameters.

    Mirrors the reference's DataLoader kwargs (``loader_tr_args`` /
    ``loader_te_args``, e.g. src/arg_pools/default.py:7-8).  ``num_workers``
    maps to prefetch threads in our pipeline; on TPU the heavy lifting
    (normalize/augment) runs on-device inside the jitted step, so the host
    only gathers uint8 rows.
    """

    batch_size: int = 128
    num_workers: int = 0
    prefetch: int = 2


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Optimizer selection.  Reference: ``optimizer``/``optimizer_args`` in
    arg pools (src/arg_pools/default.py:9-10), instantiated by name via
    ``eval`` at src/query_strategies/strategy.py:345.  Here: a plain name
    resolved through an explicit factory in train/optim.py.
    """

    name: str = "sgd"
    lr: float = 0.1
    weight_decay: float = 5e-4
    momentum: float = 0.9


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    """LR schedule stepped once per *epoch*, matching torch's
    StepLR/CosineAnnealingLR semantics (``scheduler.step()`` per epoch at
    src/query_strategies/strategy.py:369).

    name: "step" (step_size/gamma) or "cosine" (t_max, warmup_epochs).

    warmup_epochs: cosine only — linear ramp from base_lr/warmup to
    base_lr over the first ``warmup_epochs`` epochs, cosine over the
    remainder.  0 (default) reproduces torch CosineAnnealingLR exactly.
    Measured need: from-scratch ResNet training re-initialized every AL
    round is bistable at small label counts without it (runs sit at
    chance while an identical config escapes to 78%+ — BN statistics and
    momentum at full lr on the first few hundred steps).
    """

    name: str = "cosine"
    step_size: int = 60
    gamma: float = 0.1
    t_max: int = 200
    warmup_epochs: int = 0


@dataclasses.dataclass(frozen=True)
class PretrainedConfig:
    """SSL / transfer-learning checkpoint ingestion.

    Mirrors ``init_pretrained_ckpt_path`` + ``required_key``/``skip_key``/
    ``replace_key`` state-dict surgery configured per arg pool
    (src/arg_pools/ssp_finetuning.py:13-16,34-37) and applied in
    src/utils/load_pretrained_weights.py.
    """

    path: Optional[str] = None
    required_key: Optional[Tuple[str, ...]] = None
    skip_key: Optional[Tuple[str, ...]] = None
    replace_key: Optional[Tuple[Tuple[str, str], ...]] = None

    @property
    def replace_map(self) -> Dict[str, str]:
        return dict(self.replace_key or ())


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Per-dataset training hyperparameters: one entry of an "arg pool"
    (reference: the per-dataset dicts in src/arg_pools/*.py).
    """

    eval_split: float = 0.01
    # Compute precision for the model's conv/matmul path (parameters,
    # batch-norm statistics, the classifier head, and all acquisition math
    # stay float32 — see models/resnet.py).  "auto" = bfloat16 on TPU,
    # float32 elsewhere; the reference trains float32 everywhere
    # (src/utils/get_networks.py:28-29 builds torch fp32 modules), but on
    # TPU the MXU's native precision is bf16 and fp32 would halve
    # throughput for no accuracy win at these model scales.
    dtype: str = "auto"
    # BatchNorm batch-statistics read precision.  "auto" follows the
    # compute dtype: bf16 models compute batch mean/var by reducing the
    # bf16 activations directly with float32 ACCUMULATION
    # (models/resnet.FusedBatchNorm) instead of flax's
    # materialize-as-float32-then-reduce — the stats pass was measured at
    # -23% of ResNet-50 forward throughput (mfu_decomposition.json).
    # "float32" forces the flax path; running statistics are float32
    # either way.
    bn_stats_dtype: str = "auto"
    # ResNet stem layout: "default" keeps the reference 7x7/s2 conv;
    # "s2d" folds it into an exact 4x4/s1 conv over space-to-depth
    # (112x112x12) input on the 224px path — same arithmetic, 4x the
    # contraction channels for the MXU (models/resnet.py; CIFAR-stem
    # models ignore this).
    stem: str = "default"
    loader_tr: LoaderConfig = dataclasses.field(default_factory=LoaderConfig)
    loader_te: LoaderConfig = dataclasses.field(
        default_factory=lambda: LoaderConfig(batch_size=100))
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    scheduler: SchedulerConfig = dataclasses.field(default_factory=SchedulerConfig)
    pretrained: PretrainedConfig = dataclasses.field(default_factory=PretrainedConfig)
    imbalanced_training: bool = False
    # Fused optimizer update (train/optim.FusedSGD, DESIGN.md §4): the
    # SGD+momentum+weight-decay update as ONE tree-fused expression
    # inside the donated train step instead of the optax chain's four
    # tree traversals.  "auto" (default) = fused whenever the optimizer
    # is SGD-family; "on" forces it (fails fast on non-SGD); "off"
    # keeps the optax chain.  At f32 optimizer state the fused path is
    # BIT-identical to optax (pinned in tests/test_backward.py) — this
    # knob is throughput-only there.
    fused_optimizer: str = "auto"
    # Momentum-buffer storage dtype for the fused path: "f32" (default,
    # bit-parity with optax) or "bf16" (HALF the optimizer HBM; buffers
    # read bf16, accumulate f32, round once on store — bounded-delta,
    # learn-tested).  Ignored on the optax path.
    optim_state_dtype: str = "f32"
    # Gradient all-reduce precision across the mesh (parallel/mesh.py,
    # DESIGN.md §4): "f32" (default) is the partitioner's bit-exact
    # psum; "int8" is the EQuARX-style block-scaled quantized sync —
    # ~4x fewer wire bytes per gradient — with global-batch BN kept via
    # explicit pmean'd statistics.  int8 is bounded-delta (never
    # bit-exact), OFF on single-device meshes, and gated on the
    # multichip learning probe at driver startup: a probe failure
    # degrades the run to f32 loudly (journaled).
    grad_allreduce: str = "f32"
    # Device-resident epochs for in-memory datasets (one jitted scan per
    # epoch instead of per-batch dispatch).  None = auto (on when the
    # images fit in HBM and the labeled set is large enough to amortize
    # the extra compile), True = force on, False = host-batched path.
    device_resident: Optional[bool] = None
    # Train-feed selection (the feed hierarchy, DESIGN.md §2a:
    # resident-gather > prefetched-host > serial-host).
    #   "auto"     — when the pool is pinned in HBM (or fits the resident
    #                budget) and the device-resident scan is worthwhile
    #                (see device_resident), train batches are ON-DEVICE
    #                gathers of labeled indices from that SAME pinned
    #                array — zero host image copies; otherwise the legacy
    #                labeled-subset upload, then the host feed.
    #   "resident" — force the resident-gather feed (falls back down the
    #                hierarchy with a logged warning when impossible:
    #                disk-backed pool, VAAL batch_hook, budget 0).
    #   "host"     — force the host feed (multi-worker + device-prefetch
    #                when feed_workers/prefetch allow, else serial).
    # Every feed produces a bit-identical batch stream at the same seeds
    # (tests/test_trainer_parallel.py) — this knob is throughput-only.
    train_feed: str = "auto"
    # Gather/decode worker threads for the host train feed; None defers
    # to loader_tr.num_workers (the reference's DataLoader num_workers).
    # The double-buffered device prefetch depth rides loader_tr.prefetch.
    feed_workers: Optional[int] = None
    # Epoch cadence for the current-weights checkpoint AND the mid-round
    # fit-state save (the reference writes rd_{n}.pth every epoch,
    # strategy.py:440; a full-variable host transfer per epoch would
    # dominate small-model epochs on TPU, so both are periodic here).
    current_ckpt_every: int = 25
    # Cache decoded eval rows across validation epochs for disk-backed
    # datasets (the val view is deterministic, so decoding each eval row
    # once per ROUND instead of once per EPOCH is exact); bounded by
    # cache_eval_bytes, falling back to per-epoch decode past the budget.
    cache_eval: bool = True
    cache_eval_bytes: int = 4 << 30
    # Disk-memmap decode-once cache for the WHOLE deterministic pool view
    # (al scoring + test set, data/cache.DecodedPoolCache): each row is
    # JPEG-decoded exactly once per experiment lifetime instead of once
    # per round/epoch, so steady-state ImageNet scoring is bounded by
    # host->device bandwidth, not decode (bench r3: 1,048 img/s/core
    # decode vs 3,133 img/s h2d vs 9,742 img/s device).  Applied only
    # when the FULL pool fits the byte budget (sparse file; a partial
    # cache would still thrash).  dir=None -> <tempdir>/al_tpu_decoded.
    cache_decoded_bytes: int = 32 << 30
    decoded_cache_dir: Optional[str] = None
    # Global batch for acquisition-scoring passes.  None = auto: the
    # reference scores with its test-loader batch (100, e.g.
    # src/arg_pools/default.py loader_te_args), which on an 8-chip mesh is
    # ~12 rows per chip — far below MXU-efficient occupancy.  Auto keeps
    # the reference batch on CPU (tests, parity) and raises it to a
    # row-size-scaled floor PER CHIP on accelerators (512 for <=64px
    # rows, 256 above, 128 when the row shape is unknown — v5e-measured,
    # Trainer.eval_batch_size).  Scores are per-example
    # statistics under eval-mode BN, so the batch size changes throughput
    # only, never a score.
    score_batch_size: Optional[int] = None
    # Resident-pool LAYOUT over the mesh (DESIGN.md §2b):
    #   "auto"       — row-sharded whenever the single-process mesh has
    #                  more than one device (each chip pins rows/ndev of
    #                  the pool and of every factor matrix, so residency
    #                  scales with chip count), replicated otherwise
    #                  (single device, multi-process pods).
    #   "row"        — force row sharding (downgraded with the same
    #                  gates as auto where impossible).
    #   "replicated" — one full copy per chip, the pre-sharding layout.
    # Scores, train batches, and k-center picks are bit-identical across
    # layouts (tests/test_pool_sharding.py) — throughput/HBM only.
    pool_sharding: str = "auto"
    # Pool storage backend (the disk tier, DESIGN.md §16):
    #   "auto"   — the in-memory pool unless it would cross the
    #              host-RAM watermark (pool_disk_watermark_frac of
    #              physical RAM), where the run takes the disk tier;
    #   "memory" — the classic whole-pool host array;
    #   "disk"   — demand-paged disk extents (data/diskpool.DiskPool):
    #              rows live in one sparse extent file per host, gathers
    #              page bucket-aligned blocks through a byte-bounded
    #              host cache (pool_host_cache_bytes), and the labeled
    #              hot set pins in HBM via the resident machinery.
    # Picks, scores, and experiment_state are bit-identical across
    # backends at the same seeds (tests/test_disk_pool.py) — this knob
    # trades host RAM for paged-read bandwidth only.
    pool_backend: str = "auto"
    # Rows per paged block (snapped onto the pool.bucket_size ladder).
    pool_page_rows: int = 2048
    # Host block-cache budget for the warm tier, in bytes.
    pool_host_cache_bytes: int = 1 << 30
    # "auto" backend watermark: take the disk tier when the pool exceeds
    # this fraction of physical host RAM.
    pool_disk_watermark_frac: float = 0.5
    # Keep in-memory datasets resident on device (replicated) for the
    # whole experiment — ONE shared upload serves every round's
    # acquisition scoring AND the per-epoch validation/test evaluation
    # (parallel/resident.py).  None = AUTO (the default): the budget is
    # sized from live HBM headroom at round start (bytes_limit −
    # bytes_in_use − a training-activation reserve), so any pool that
    # fits the chip pins by default; backends without memory statistics
    # fall back to a conservative 2 GB.  An explicit integer pins the
    # budget (0 disables both resident paths).  The budget is accounted
    # across the WHOLE resident cache (parallel/resident.pinned_bytes):
    # the AL pool, the test set, and the train feed share one pot, and
    # the al/train views' shared storage counts ONCE — one pinned pool
    # serves scoring, evaluation, AND training for one array's worth of
    # HBM.  Shrinking an explicit budget mid-run demotes pinned pools
    # LRU-first (parallel/resident.enforce_budget).
    resident_scoring_bytes: Optional[int] = None

    @property
    def has_pretrained(self) -> bool:
        return self.pretrained.path is not None


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """The online scoring service (active_learning_tpu/serve/): the
    ``serve`` CLI verb's knobs.  Unlike every other config here this has
    no reference counterpart — the reference has no serving path at all
    (PARITY.md); request latency, not round wall-clock, is its metric.
    """

    host: str = "127.0.0.1"
    # 0 = ephemeral (the bound port is logged and exposed on the server
    # object) — tests and the bench phase run over loopback this way.
    port: int = 8000
    # Rows per dispatched device batch, upper bound.  Served shapes are
    # the geometric bucket ladder serve_buckets(max_batch, bucket_floor)
    # — every one pre-compiled at startup.
    max_batch: int = 64
    # Microbatch deadline: a batch closes at max_batch rows or this many
    # ms after its first row, whichever comes first.
    max_latency_ms: float = 5.0
    # Admission bound in ROWS (queued + in flight); beyond it requests
    # get 429 + Retry-After.  Explicit backpressure, never unbounded
    # queueing.
    queue_depth: int = 512
    # Floor of the bucket ladder (pool.bucket_size floor): the smallest
    # padded batch a lone request is served at.
    bucket_floor: int = 8
    # Hot-reload poll cadence for a newer best_rd_{n} checkpoint; 0
    # checks before every batch.
    reload_every_s: float = 5.0
    # Bound on the SIGTERM graceful drain (in-flight completion).
    drain_timeout_s: float = 30.0


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """The streaming active-learning service (active_learning_tpu/stream/,
    DESIGN.md §14): the ``stream`` CLI verb's knobs.  Like ServeConfig
    this has no reference counterpart — the reference's AL loop is an
    offline batch job over a frozen disk pool (PARITY.md row 58)."""

    host: str = "127.0.0.1"
    # 0 = ephemeral (the bound port is logged and exposed on the service
    # object) — tests and the bench smoke phase run over loopback.
    port: int = 8008
    # Rows one POST /v1/pool may carry; beyond it the request is a
    # non-retryable 413 (it could never be admitted — split it).
    max_request_rows: int = 512
    # Accepted-but-undrained rows the service will hold; beyond it
    # ingest gets 429 + Retry-After until a round drains the backlog.
    # Explicit backpressure, never unbounded queueing (the serve
    # admission contract, applied to durability instead of batching).
    max_backlog_rows: int = 65536
    # Ingest-WAL segment rotation bound (stream/wal.py): the active
    # wal.jsonl seals (atomic rename) past this many bytes.
    wal_rotate_bytes: int = 64 << 20
    # Trigger policy (stream/scheduler.TriggerPolicy): a round fires on
    # the new-row watermark, on ServeScoreDrift PSI, or on the max wall
    # interval — whichever first.  0 disables a condition.
    watermark_rows: int = 1024
    drift_psi: float = 0.25
    max_interval_s: float = 3600.0
    # Scheduler poll cadence between rounds.
    poll_s: float = 0.5
    # Stop after this many total rounds (the driver's ``rounds``
    # semantics — a resumed run continues the same count); 0 = run
    # indefinitely (the production mode; SIGTERM checkpoint-and-exits).
    max_rounds: int = 0
    # Extent floor for pool growth (pool.bucket_size's floor): appended
    # capacity lands on this shape ladder so the resident upload and
    # its gather runners recompile at most once per bucket boundary.
    extent_floor: int = 256
    # How many whole batches one incremental drift-scoring chunk covers
    # (scoring.chunk_row_slices — the PR 7 chunk plan, reused over
    # appended row ranges).
    chunk_batches: int = 8


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Run-wide telemetry (active_learning_tpu/telemetry/, DESIGN.md §7):
    per-step/per-epoch train + scoring metrics through the MetricsSink,
    heartbeat liveness, host-span traces, and Prometheus exposition.

    ``enabled`` is the master switch and is ON by default — the
    default-on pieces (step-time/imgs-per-sec/grad-norm collection, the
    heartbeat file, the jit-compile counter) cost two perf_counter
    calls and a rate-limited dict merge per step.  Trace export and the
    stall watchdog are opt-in on top.
    """

    enabled: bool = True
    # Heartbeat rewrite cadence floor (phase transitions force a write
    # regardless); heartbeat.json lands in --log_dir, per-process on
    # pods (heartbeat_p{i}.json).
    heartbeat_every_s: float = 5.0
    # Chrome trace-event export: log_dir/trace.json, loadable in
    # Perfetto / chrome://tracing.  Off by default (the event buffer is
    # bounded either way).
    export_trace: bool = False
    # In-process stall watchdog: logs + emits a ``stall_suspected``
    # metric when the progress counter freezes past the deadline.  The
    # same deadline is embedded in heartbeat.json for EXTERNAL probes
    # (the ``status`` verb flags staleness off the file's mtime).
    watchdog: bool = False
    stall_deadline_s: float = 600.0
    # Prometheus textfile-collector scrape file (atomic rewrite); None
    # disables.  The serve path exposes the same exposition format live
    # at /metrics?format=prometheus.
    prometheus_file: Optional[str] = None
    # The experiment-truth diagnostics layer (telemetry/diagnostics.py,
    # DESIGN.md §13): per-round acquisition-score histograms + PSI/JS
    # drift, selection composition (class balance / novelty / k-center
    # pick distances), and eval-piggybacked calibration, emitted through
    # the sink + al_run_* gauges and persisted into run_report.json.
    # Default ON (it rides numbers that already exist on host — zero
    # extra pool passes, zero device syncs, picks bit-identical on/off);
    # requires ``enabled``.  Off = one None check per hook site.
    diagnostics: bool = True
    # What a CONFIRMED stall does beyond logging (DESIGN.md §10):
    #   "log"       log + stall_suspected metric (the pre-fault-model
    #               behavior);
    #   "snapshot"  also journal the stall into round_journal.json
    #               (status="stalled", stalled_s) for post-mortems and
    #               `status --strict`;
    #   "degrade"   snapshot + ask the degradation ladder to escalate at
    #               the driver's next safe point (the watchdog thread
    #               itself never mutates run state).
    watchdog_action: str = "log"


@dataclasses.dataclass(frozen=True)
class ImbalanceConfig:
    """Synthetic class-imbalance parameters.

    Reference: --imbalance_type/--imbalance_factor/--imbalance_seed
    (src/utils/parser.py:30-39) consumed by
    src/data_utils/custom_imbalanced_cifar10.py:16-27.
    """

    imbalance_type: Optional[str] = None  # "exp" | "step" | None
    imbalance_factor: float = 0.1
    imbalance_seed: int = 0


@dataclasses.dataclass(frozen=True)
class VAALConfig:
    """VAAL hyperparameters (reference: src/utils/parser.py:81-92)."""

    vae_latent_dim: int = 64
    adversary_param: float = 10.0
    lr_vae: float = 5e-5
    lr_discriminator: float = 1e-3


@dataclasses.dataclass
class ExperimentConfig:
    """Top-level experiment configuration: the 30 CLI flags of
    src/utils/parser.py as one typed object.
    """

    # Experiment identity / logging
    project_name: str = "active-learning"
    exp_name: str = "active_learning"
    exp_hash: Optional[str] = None
    log_dir: str = "./logs"
    ckpt_path: str = "./checkpoint"
    enable_metrics: bool = True
    # Comma-separated sink backends (utils/metrics.SINK_BACKENDS):
    # "jsonl", "csv", "tensorboard", or combinations ("jsonl,tensorboard").
    metrics_backend: str = "jsonl"
    # JsonlSink size-based rotation: when metrics.jsonl would exceed
    # this many bytes it rotates to metrics.jsonl.1 (atomic, lock-held,
    # no line ever split across the boundary — utils/metrics.JsonlSink).
    # 0 (default) = unbounded, the historical behavior; a
    # run-indefinitely service (ROADMAP item 3) sets a cap.
    metrics_rotate_bytes: int = 0

    # Dataset
    dataset: str = "cifar10"
    dataset_dir: Optional[str] = None
    arg_pool: str = "default"
    # Root onto which an arg pool's relative pretrained-ckpt path is rebased
    # (the reference hardcodes a ../pretrained_ckpt layout,
    # ssp_finetuning.py:13).
    pretrained_root: Optional[str] = None
    imbalance: ImbalanceConfig = dataclasses.field(default_factory=ImbalanceConfig)

    # Active-learning globals
    strategy: str = "RandomSampler"
    rounds: int = 5
    round_budget: int = 5000
    freeze_feature: bool = False
    init_pool_size: int = -1  # -1 => round_budget (main_al.py:74-76)
    init_pool_type: str = "random"  # "random" | "random_balance"

    # Training
    model: str = "SSLResNet18"
    resume_training: bool = False
    n_epoch: int = 60
    early_stop_patience: int = 30

    # Self-provision disk datasets when absent (the reference's
    # torchvision download=True, custom_cifar10.py:30-33).
    download_data: bool = False

    # Debug
    debug_mode: bool = False
    # The device-truth layer (telemetry/profiler.py, DESIGN.md §11):
    # bounded XLA profiler capture windows around chosen AL rounds.
    # profile_dir names where the trace artifacts + per-round
    # device_profile_rd{n}.json summaries land (set alone it captures
    # the default window); profile_rounds picks WHICH rounds capture —
    # a comma-separated list or "warm" (default: round 1, the first
    # warm round).  Round 0 NEVER captures: it pays the cold compile
    # tax and its trace would answer "how slow is compilation", not
    # "where does the steady-state round go".  Setting profile_rounds
    # without profile_dir lands artifacts under <log_dir>/profile.
    # Unset, the capture hooks are inert (no per-step or per-round
    # work — pinned in tests/test_profiler.py).
    profile_dir: Optional[str] = None
    profile_rounds: Optional[str] = None

    # Compute-precision override: None defers to the arg pool's
    # TrainConfig.dtype ("auto" = bf16 on TPU / f32 elsewhere).
    dtype: Optional[str] = None

    # BN batch-statistics precision override: None defers to the arg
    # pool's TrainConfig.bn_stats_dtype ("auto" = fused bf16 stats on
    # bf16 models).
    bn_stats_dtype: Optional[str] = None

    # ResNet stem override ("default"/"s2d"): None defers to the arg
    # pool's TrainConfig.stem.  See TrainConfig.stem.
    stem: Optional[str] = None

    # Device-resident pool budget override (bytes): None defers to the
    # arg pool's TrainConfig.resident_scoring_bytes, whose default is
    # AUTO — sized from live HBM headroom at round start, so pools that
    # fit the chip pin in HBM by default and every later query/eval pass
    # is on-device gathers (no per-batch host->device image traffic).
    # Pass an explicit integer to pin the budget, 0 to disable residency.
    resident_scoring_bytes: Optional[int] = None

    # Train-feed override ("auto"/"resident"/"host"): None defers to the
    # arg pool's TrainConfig.train_feed.  See TrainConfig.train_feed for
    # the feed hierarchy (resident-gather > prefetched-host >
    # serial-host); every feed is bit-identical at the same seeds.
    train_feed: Optional[str] = None

    # Fused optimizer-update override ("auto"/"on"/"off"): None defers
    # to the arg pool's TrainConfig.fused_optimizer.  Bit-identical to
    # the optax chain at f32 optimizer state.
    fused_optimizer: Optional[str] = None

    # Momentum-buffer dtype override ("f32"/"bf16") for the fused
    # optimizer path: None defers to the arg pool.  bf16 halves
    # optimizer HBM (bounded-delta; f32 is bit-parity with optax).
    optim_state_dtype: Optional[str] = None

    # Gradient all-reduce precision override
    # ("f32"/"int8"/"int8_rs"/"auto"): None defers to the arg pool
    # (default f32 = the bit-exact psum).  The quantized modes
    # (EQuARX-style block-scaled sync) are bounded-delta, default-off,
    # OFF on single-device meshes, and gated on the multichip learning
    # probe at run start (a failed probe degrades to f32 loudly —
    # journaled, sticky across resume).  The WIRE form is resolved per
    # mesh (parallel/mesh.resolve_int8_wire): the all-gather form on
    # 2-8 device meshes, the pod-tier reduce-scatter form
    # (int8_reduce_scatter, ~2n bytes regardless of device count) above
    # the crossover; "int8_rs" forces reduce-scatter, "auto" =
    # quantized wherever a multi-device mesh makes it worth probing.
    grad_allreduce: Optional[str] = None

    # Large-batch scaling ("auto"/"off"/None=off, DESIGN.md §15): auto
    # applies the large-batch ConvNet scaling rules as the mesh grows —
    # train batch x ndev (the arg pool's batch becomes PER-CHIP),
    # linear lr x ndev, and a >=5-epoch gradual cosine warmup — so the
    # pod-scale global batch doesn't silently cost accuracy.  Off keeps
    # the arg pool's batch as the reference's global batch.
    scale_batch: Optional[str] = None

    # Resident-pool layout override ("auto"/"replicated"/"row"): None
    # defers to the arg pool's TrainConfig.pool_sharding, whose default
    # auto row-shards pool rows over any single-process multi-device
    # mesh (per-chip residency = rows/ndev).  Scores, batches, and
    # k-center picks are bit-identical across layouts.
    pool_sharding: Optional[str] = None

    # Host train-feed gather/decode worker threads: None defers to the
    # arg pool (TrainConfig.feed_workers -> loader_tr.num_workers, the
    # reference's DataLoader num_workers row).
    feed_workers: Optional[int] = None

    # Pool storage backend override ("auto"/"memory"/"disk"): None
    # defers to the arg pool's TrainConfig.pool_backend, whose default
    # auto keeps the in-memory pool until it would cross the host-RAM
    # watermark, then takes the demand-paged disk tier (DESIGN.md §16).
    # Bit-identical picks/scores/experiment_state across backends.
    pool_backend: Optional[str] = None

    # Pipelined AL round (experiment/pipeline.py, DESIGN.md §8):
    # "speculative" overlaps the next query's pool-scoring pass with the
    # current fit's early-stop patience tail (chunks scored from each
    # published best checkpoint, invalidated when a later epoch improves
    # best) and prefetches the coming fit's train feed while selection
    # runs — round wall moves from sum(train, score, select) toward
    # max(train, score).  "off" is the reference's strictly sequential
    # loop.  "auto" (the default) picks speculative on any
    # single-process multi-device mesh.  Picks, scores, and
    # experiment_state are bit-identical across modes at the same seeds
    # (tests/test_pipeline.py) — this is a wall-clock choice only.
    round_pipeline: str = "auto"

    # Coreset / BADGE partitioning (parser.py:74-79)
    subset_labeled: Optional[int] = None
    subset_unlabeled: Optional[int] = None
    partitions: int = 1
    # Batched greedy k-center: provisionally-farthest picks folded into
    # the min-distance vector per pool pass, with an exact in-batch
    # re-check so the selection is pick-for-pick identical to q=1
    # (strategies/kcenter.py).  8 = the f32 sublane tile; 1 restores
    # the sequential scan.  Randomized (BADGE D^2) selection always
    # draws one pick at a time regardless.
    kcenter_batch: int = 8

    # Persistent XLA compilation-cache directory: round N+1 and run M+1
    # reuse round N's compiled executables from disk instead of paying
    # the cold-compile tax again (experiment/driver.py applies it
    # process-wide at run start).  None = ~/.cache/al_tpu_xla_cache
    # (or $JAX_COMPILATION_CACHE_DIR); "" disables.
    compilation_cache_dir: Optional[str] = None

    # Deterministic fault injection (active_learning_tpu/faults/,
    # DESIGN.md §10): a comma-separated arming spec like
    # "h2d_upload:raise@3,ckpt_write:torn@1,spec_scorer:die@0.5" —
    # site:action[@arg] with int args = Nth-hit triggers (fire once),
    # float args = seeded per-hit probabilities, "delay" args = seconds.
    # None defers to $AL_FAULT_SPEC; unset leaves every site a
    # zero-cost no-op.  Chaos tests arm this to make every recovery
    # claim replayable (tests/test_faults.py).
    fault_spec: Optional[str] = None

    # VAAL
    vaal: VAALConfig = dataclasses.field(default_factory=VAALConfig)

    # Run-wide telemetry (heartbeat/spans/per-step metrics/Prometheus).
    telemetry: TelemetryConfig = dataclasses.field(
        default_factory=TelemetryConfig)

    # Seeds (reference hard-codes eval split seed 99 and init pool seed 98,
    # main_al.py:71,83; the rest of the run uses the global np.random state —
    # here everything is explicit).
    eval_split_seed: int = 99
    init_pool_seed: int = 98
    run_seed: int = 0

    # Mesh / parallelism (replaces world_size = torch.cuda.device_count(),
    # main_al.py:96; -1 = all local devices)
    num_devices: int = -1

    # Multi-host (DCN): jax.distributed rendezvous, the run-once equivalent
    # of the reference's per-round NCCL process group (strategy.py:288-315).
    # All None = single process, or TPU-pod auto-discovery when only
    # num_processes is given.  ckpt_path must be a shared filesystem on
    # multi-host runs (only process 0 writes; every process reads).
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    def resolved_init_pool_size(self) -> int:
        if self.init_pool_size == -1:
            return int(self.round_budget)
        return int(self.init_pool_size)


def config_to_dict(cfg: Any) -> Dict[str, Any]:
    """Flatten a (possibly nested) dataclass config into a plain dict for
    metric-parameter logging (reference logs vars(args) at main_al.py:114)."""
    out: Dict[str, Any] = {}

    def _walk(prefix: str, obj: Any) -> None:
        if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            for f in dataclasses.fields(obj):
                _walk(f"{prefix}{f.name}.", getattr(obj, f.name))
        else:
            out[prefix[:-1]] = obj

    _walk("", cfg)
    return out
