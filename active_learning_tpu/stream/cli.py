"""The ``stream`` CLI verb.

    python -m active_learning_tpu stream --dataset cifar10 \\
        --strategy MarginSampler --round_budget 1000 \\
        --stream_port 8008 --watermark_rows 2048 --drift_psi 0.25 \\
        --max_interval_s 1800

Every experiment flag of the batch CLI applies unchanged (the streaming
loop runs the same driver phases over the same stack); the stream-
specific flags configure the ingest listener, the WAL, and the trigger
policy.  ``--rounds`` is ignored in favor of ``--max_rounds`` (0 = run
indefinitely; SIGTERM checkpoint-and-exits and ``--resume_training``
continues, replaying the ingest WAL so no accepted row is lost).
"""

from __future__ import annotations

from typing import List, Optional

from ..config import StreamConfig


def extend_parser(p):
    g = p.add_argument_group("stream", "streaming-service flags")
    g.add_argument("--stream_host", type=str, default="127.0.0.1")
    g.add_argument("--stream_port", type=int, default=8008,
                   help="ingest listener port; 0 = ephemeral (logged)")
    g.add_argument("--max_request_rows", type=int, default=512,
                   help="rows one POST /v1/pool may carry (413 above)")
    g.add_argument("--max_backlog_rows", type=int, default=65536,
                   help="accepted-but-undrained row bound (429 beyond)")
    g.add_argument("--wal_rotate_bytes", type=int, default=64 << 20,
                   help="ingest-WAL segment rotation bound")
    g.add_argument("--watermark_rows", type=int, default=1024,
                   help="trigger: pending new rows that fire a round "
                        "(0 disables)")
    g.add_argument("--drift_psi", type=float, default=0.25,
                   help="trigger: ServeScoreDrift PSI of fresh-row "
                        "scores vs the checkpoint baseline (0 disables)")
    g.add_argument("--max_interval_s", type=float, default=3600.0,
                   help="trigger: max wall seconds between rounds while "
                        "any work remains (0 disables)")
    g.add_argument("--stream_poll_s", type=float, default=0.5,
                   help="scheduler poll cadence between rounds")
    g.add_argument("--max_rounds", type=int, default=0,
                   help="stop after this many total rounds; 0 = run "
                        "indefinitely")
    g.add_argument("--extent_floor", type=int, default=256,
                   help="pool-growth extent floor (bucket_size floor)")
    return p


def args_to_stream_config(args) -> StreamConfig:
    return StreamConfig(
        host=args.stream_host, port=args.stream_port,
        max_request_rows=args.max_request_rows,
        max_backlog_rows=args.max_backlog_rows,
        wal_rotate_bytes=args.wal_rotate_bytes,
        watermark_rows=args.watermark_rows, drift_psi=args.drift_psi,
        max_interval_s=args.max_interval_s, poll_s=args.stream_poll_s,
        max_rounds=args.max_rounds, extent_floor=args.extent_floor)


def main(argv: Optional[List[str]] = None) -> int:
    from ..experiment.cli import args_to_config, get_parser
    from ..faults.preempt import PreemptionRequested
    from .service import run_stream

    parser = extend_parser(get_parser())
    parser.prog = "python -m active_learning_tpu stream"
    args = parser.parse_args(argv)
    cfg = args_to_config(args)
    try:
        run_stream(cfg, args_to_stream_config(args))
    except PreemptionRequested:
        # Graceful preemption: WAL + experiment state are durable and
        # consistent — exit 0 so orchestrators treat it as clean;
        # --resume_training continues with zero accepted-row loss.
        return 0
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
