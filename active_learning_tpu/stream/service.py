"""The streaming service: serving-side ingest and the AL loop as ONE
long-lived process on one persistent mesh (DESIGN.md §14).

Lifecycle:

  1. replay the ingest WAL (accepted-but-undrained records re-enter the
     pending queue — a mid-ingest kill loses nothing acked);
  2. build the experiment stack through the SAME wiring the batch
     driver uses (experiment/driver.build_experiment), over growable
     datasets backed by the pool store;
  3. open the ingest HTTP listener (own asyncio thread; handlers are
     host-pure and never touch the pool);
  4. loop: probe drift over freshly-ingested rows (incremental,
     chunk-aligned — scoring.chunk_row_slices), ask the trigger policy,
     and when it fires DRAIN the queue into the pool (the only place
     the pool mutates — pool state is a pure function of WAL order +
     the round schedule) and run ONE full AL round through the driver's
     phases, round journal, degradation ladder, and SIGTERM
     checkpoint-and-exit.

Round bodies deliberately mirror experiment/driver._run_round verb for
verb (query -> update -> init -> train -> load_best -> test -> save):
a stream run with zero ingest produces an ``experiment_state`` BIT-
IDENTICAL to the batch driver at the same seeds (pinned in
tests/test_stream.py), which is what makes every batch-mode claim
(resume, ladder, pipelining) carry over to the streaming loop.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time
import uuid
from typing import Dict, Optional

import numpy as np

from .. import faults
from ..config import ExperimentConfig, StreamConfig, TrainConfig, \
    config_to_dict
from ..data.core import ArrayDataset
from ..experiment import pipeline as pipeline_lib
from ..experiment import resume as resume_lib
from ..experiment.driver import (_emit_round_gauges, _emit_round_telemetry,
                                 _labeled_crc, _restore_round_snapshot,
                                 _round_snapshot, build_experiment,
                                 enable_compilation_cache)
from ..faults import ladder as ladder_lib
from ..faults import preempt as preempt_lib
from ..parallel import mesh as mesh_lib
from ..parallel import resident as resident_lib
from ..serve.metrics import ServeMetrics
from ..strategies import scoring
from ..telemetry import diagnostics as diag_lib
from ..telemetry import runtime as tele_runtime
from ..telemetry import spans as tele_spans
from ..utils.logging import get_logger, setup_logging
from ..utils.metrics import MetricsSink, make_sink
from ..utils.tracing import phase_timer
from . import ingest as ingest_lib
from . import store as store_lib
from .scheduler import TriggerPolicy
from .server import StreamIngestServer
from .wal import IngestWAL, iter_payloads, replay_wal
from .wal import prune_sealed as wal_prune_sealed

WAL_DIR = "ingest_wal"
POOL_DIR = "stream_pool"


class StreamService:
    """One streaming experiment.  ``run()`` blocks until ``max_rounds``
    complete (or forever when 0), raising PreemptionRequested on
    SIGTERM/SIGINT exactly like the batch driver — the CLI maps it to
    exit 0."""

    def __init__(self, cfg: ExperimentConfig, stream_cfg: StreamConfig,
                 sink: Optional[MetricsSink] = None, data=None,
                 train_cfg: Optional[TrainConfig] = None, model=None):
        self.cfg = cfg
        self.stream_cfg = stream_cfg
        self._sink = sink
        self._data = data
        self._train_cfg = train_cfg
        self._model = model
        self.logger = get_logger()
        # Populated by run(); tests read them.
        self.strategy = None
        self.store: Optional[store_lib.PoolStore] = None
        self.wal: Optional[IngestWAL] = None
        self.queue: Optional[ingest_lib.PendingQueue] = None
        self.drift: Optional[diag_lib.ServeScoreDrift] = None
        self.server: Optional[StreamIngestServer] = None
        self.port: Optional[int] = None
        self.ready = threading.Event()  # listener up, loop entered
        self.rounds_run = 0
        self.last_trigger: Dict = {"cause": None, "ts": None}
        self._cause_counts: Dict[str, int] = {}
        self._probed_rows = 0
        self._applied_seq = 0
        self._loop_thread: Optional[threading.Thread] = None
        self._aio: Optional[asyncio.AbstractEventLoop] = None

    # -- lifecycle --------------------------------------------------------

    def run(self):
        cfg, scfg = self.cfg, self.stream_cfg
        mesh_lib.initialize_distributed(cfg.coordinator_address,
                                        cfg.num_processes, cfg.process_id)
        enable_compilation_cache(cfg.compilation_cache_dir)
        fault_spec = cfg.fault_spec or os.environ.get("AL_FAULT_SPEC")
        if fault_spec:
            faults.configure(fault_spec, seed=cfg.run_seed)
        if cfg.exp_hash is None:
            cfg.exp_hash = uuid.uuid4().hex[:9]
        logger = setup_logging(
            cfg.log_dir, f"stream_{cfg.exp_hash}_{os.getpid()}.log")
        self.logger = logger

        resuming = cfg.resume_training and \
            resume_lib.has_saved_experiment(cfg)
        preempted_round0 = False
        if cfg.resume_training and not resuming:
            # Mirror the driver's round-0 preemption rule: a journaled
            # round-0 preemption of THIS experiment replays round 0;
            # anything else refuses rather than silently restarting.
            # The PRIOR journal must be read before this run's journal
            # writes anything — a merge-writer starts from empty fields,
            # so the first write would clobber the preemption record.
            prior = faults.read_journal(
                os.path.join(cfg.log_dir, faults.JOURNAL_FILE))
            if (prior is not None and prior.get("status") == "preempted"
                    and prior.get("exp_hash") == cfg.exp_hash
                    and prior.get("exp_name") == cfg.exp_name
                    and int(prior.get("round", -1)) == 0):
                preempted_round0 = True
            else:
                raise FileNotFoundError(
                    f"--resume_training: no saved experiment state for "
                    f"exp_name={cfg.exp_name!r} exp_hash={cfg.exp_hash!r} "
                    f"under {cfg.ckpt_path!r}; pass the original "
                    "--exp_hash/--ckpt_path")
        journal = faults.RoundJournal(
            os.path.join(cfg.log_dir, faults.JOURNAL_FILE),
            enabled=mesh_lib.is_coordinator())
        journal.write(exp_name=cfg.exp_name, exp_hash=cfg.exp_hash,
                      stream=True)
        if self._sink is None:
            key = (resume_lib.saved_experiment_key(cfg) if resuming
                   else cfg.exp_hash)
            self._sink = make_sink(
                cfg.enable_metrics and mesh_lib.is_coordinator(),
                cfg.log_dir, experiment_key=key,
                backend=cfg.metrics_backend,
                rotate_bytes=cfg.metrics_rotate_bytes)
        sink = self._sink

        telemetry = tele_runtime.start_run(
            cfg.telemetry, log_dir=cfg.log_dir, logger=logger)
        status = "crashed"
        pipeline = None
        preempt_lib.reset()
        prev_handlers = preempt_lib.install(logger)
        run_retries0 = faults.retry_counters()["total"]
        try:
            start_round = self._build(journal, resuming, preempted_round0)
            strategy = self.strategy
            # The streaming-aware run report (DESIGN.md §13 + §14):
            # the driver's per-round label-efficiency rows, each joined
            # by a ``stream`` block — ingest totals, the trigger cause,
            # WAL backlog, ack latency — so `report` renders what the
            # SERVICE did between rounds, not just what the rounds
            # cost.  Atomic per round, resume-merged like the driver's.
            self._report_path = os.path.join(cfg.log_dir,
                                             diag_lib.RUN_REPORT_FILE)
            self._write_report = (mesh_lib.is_coordinator()
                                  and cfg.enable_metrics)
            self._report_rows = []
            self._report_wall_base = 0.0
            if self._write_report and start_round > 0:
                self._report_rows, self._report_wall_base = \
                    diag_lib.resume_report_rows(self._report_path,
                                                cfg.exp_hash,
                                                start_round)
            self._report_header = {
                "exp_name": cfg.exp_name, "exp_hash": cfg.exp_hash,
                "strategy": cfg.strategy, "dataset": cfg.dataset,
                "model": cfg.model, "run_seed": cfg.run_seed,
                "round_budget": cfg.round_budget,
                "init_pool_size": cfg.resolved_init_pool_size(),
                "stream": True,
            }
            self._run_t0 = time.monotonic()
            pipeline_mode = pipeline_lib.resolve_round_pipeline(
                cfg.round_pipeline, strategy.mesh)
            if pipeline_mode == "speculative":
                pipeline = pipeline_lib.RoundPipeline(strategy)
                strategy.pipeline = pipeline
            logger.info(f"Round pipeline: {pipeline_mode}")
            ladder = ladder_lib.DegradationLadder(strategy, logger=logger,
                                                  sink=sink,
                                                  journal=journal)
            save_retry = faults.RetryPolicy(
                site="experiment_save", classify=faults.classify_exception)
            self._serve_start()
            self.ready.set()
            self._loop(start_round, journal, telemetry, sink, ladder,
                       save_retry, run_retries0)
            status = "finished"
            journal.write(status="finished")
            return strategy
        except preempt_lib.PreemptionRequested as exc:
            status = "preempted"
            journal.write(status="preempted", signal=int(exc.signum))
            logger.info(
                "stream: preemption — WAL + experiment state durable; "
                "re-run with --resume_training to continue")
            raise
        finally:
            self._serve_stop()
            if self.wal is not None:
                self.wal.close()
            if self.store is not None:
                self.store.flush()
            if fault_spec:
                faults.configure(None)
            preempt_lib.uninstall(prev_handlers)
            if pipeline is not None:
                pipeline.shutdown()
            telemetry.finish(status)
            tele_runtime.uninstall(telemetry)

    # -- construction -----------------------------------------------------

    def _build(self, journal, resuming: bool,
               preempted_round0: bool) -> int:
        cfg, scfg = self.cfg, self.stream_cfg
        if self._train_cfg is None:
            from ..experiment import arg_pools as arg_pools_lib
            self._train_cfg = arg_pools_lib.get_train_config(
                cfg.arg_pool, cfg.dataset,
                pretrained_root=cfg.pretrained_root)
        if self._data is None:
            from ..data import get_data
            self._data = get_data(cfg.dataset, data_path=cfg.dataset_dir,
                                  debug_mode=cfg.debug_mode,
                                  imbalance_args=cfg.imbalance,
                                  download=cfg.download_data)
        base_train, test_set, base_al = self._data
        images = getattr(base_train, "images", None)
        if not isinstance(images, np.ndarray):
            raise ValueError(
                "stream: the base dataset must be in-memory (images "
                "array) — disk-backed base pools are future work "
                "(DESIGN.md §14)")
        n_base = len(base_train)
        self.store = store_lib.PoolStore(
            os.path.join(cfg.log_dir, POOL_DIR), base_train.image_shape,
            base_al.num_classes, base_images=images[:n_base],
            base_targets=base_train.targets[:n_base],
            extent_floor=scfg.extent_floor, reuse=True)
        if self.store.applied_seq > 0 and not resuming:
            # Compaction trades replay-from-scratch for a bounded WAL:
            # the pruned prefix's pool bookkeeping (which rows carried
            # oracle labels, which were absorbed) lives only in the
            # saved experiment state.  A fresh run over a compacted
            # log_dir cannot rebuild that timeline — refuse rather than
            # silently diverge from what a full replay would produce.
            raise ValueError(
                f"stream: {cfg.log_dir!r} holds a compacted pool store "
                f"(WAL prefix through seq {self.store.applied_seq} "
                "absorbed into sealed extents); pass --resume_training "
                "to continue that experiment, or use a fresh log_dir")
        # Build-time datasets span the BASE rows only: the eval split
        # and init pool are seeded over data round 0 of ANY timeline
        # can see, so every ingest schedule shares them.
        self._train_sd, self._al_sd = self.store.make_datasets(
            base_train.view, base_al.view, length=n_base)

        # WAL replay BEFORE the strategy exists: replayed records enter
        # the pending queue and drain at the next round start exactly
        # like live ingest — a mid-ingest kill loses no accepted row.
        wal_dir = os.path.join(cfg.log_dir, WAL_DIR)
        records, dropped = replay_wal(wal_dir)
        if dropped:
            self.logger.info(
                f"stream: WAL replay dropped {dropped} torn un-acked "
                "tail record")
        # Compaction consistency: the store's manifest names the WAL
        # prefix its sealed extents absorb.  Surviving records may
        # overlap that prefix (a prune interrupted mid-delete) — those
        # are skipped below — but a replay that STARTS past
        # applied_seq + 1 means a sealed segment the manifest never
        # absorbed is gone, and no amount of replay can paper over it.
        if records and records[0]["seq"] > self.store.applied_seq + 1:
            raise ValueError(
                f"stream: WAL starts at seq {records[0]['seq']} but the "
                f"pool store only absorbs through seq "
                f"{self.store.applied_seq} — a sealed WAL segment is "
                "missing")
        # The appender reuses this replay (one full-WAL read per start);
        # base_seq continues the chain when compaction pruned every
        # segment.
        self.wal = IngestWAL(wal_dir, rotate_bytes=scfg.wal_rotate_bytes,
                             replayed=records,
                             base_seq=self.store.applied_seq)
        self.queue = ingest_lib.PendingQueue(scfg.max_backlog_rows)
        self._applied_seq = self.store.applied_seq
        replayed_rows = 0
        skipped = 0
        for rec in iter_payloads(records):
            if rec["seq"] <= self.store.applied_seq:
                # Already sealed into the store's extents (and counted
                # in its n_rows) — re-queueing would double-apply.
                skipped += 1
                continue
            if rec.get("kind") == "pool":
                n = int(rec["shape"][0])
                self.queue.push(rec, n_rows=n, n_labels=0)
                replayed_rows += n
            else:
                self.queue.push(rec, n_rows=0,
                                n_labels=len(rec.get("ids", ())))
        if records:
            self.logger.info(
                f"stream: replayed {len(records)} WAL records "
                f"({replayed_rows} pool rows) into the pending queue"
                + (f"; {skipped} compacted record(s) skipped"
                   if skipped else ""))

        strategy = build_experiment(
            cfg, sink=self._sink,
            data=(self._train_sd, test_set, self._al_sd),
            train_cfg=self._train_cfg, model=self._model,
            skip_init_pool=resuming)
        self.strategy = strategy
        # The acked-id space the handlers validate against: base + every
        # replayed pool row, with the eval split unlabelable — a label
        # the drain could never absorb must be a 400 BEFORE the WAL
        # write, or it would replay into the same failure forever.
        # ``store.n_rows`` covers base + any compacted extents; only the
        # still-pending replay rows ride on top.
        self.ids = ingest_lib.IdSpace(self.store.n_rows + replayed_rows,
                                      unlabelable=strategy.pool.eval_idxs)
        self.drift = diag_lib.ServeScoreDrift(key="margin")
        if resuming:
            start_round = resume_lib.load_experiment(strategy, cfg)
            strategy.resume_next_fit = True
            # The restored pool may already span extents a previous
            # segment drained; the datasets must present that capacity.
            # Un-compacted growth refills at the first drain's replay,
            # but COMPACTED extents never re-enter the queue — the store
            # reopened them directly, so the dataset snapshots must be
            # retaken here or the restored pool would outsize its
            # datasets.
            if self.store.capacity > n_base:
                self._al_sd.refresh()
                self._train_sd.refresh()
        else:
            start_round = 0
            self._sink.log_parameters(config_to_dict(cfg))
            if preempted_round0:
                self.logger.info(
                    "stream resume: journal records a round-0 "
                    "preemption; replaying round 0 with its mid-fit "
                    "state")
                strategy.resume_next_fit = True
        return start_round

    # -- ingest listener (asyncio thread) ---------------------------------

    def _serve_start(self) -> None:
        scfg = self.stream_cfg
        self.metrics = ServeMetrics()
        self.server = StreamIngestServer(
            self.wal, self.queue, self.ids, self.store.image_shape,
            host=scfg.host, port=scfg.port,
            max_request_rows=scfg.max_request_rows, drift=self.drift,
            metrics=self.metrics, extra_status=self._status_fields)
        self._aio = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=lambda: (asyncio.set_event_loop(self._aio),
                            self._aio.run_forever()),
            daemon=True, name="al-stream-ingest-loop")
        self._loop_thread.start()
        asyncio.run_coroutine_threadsafe(
            self.server.start(), self._aio).result(60)
        self.port = self.server.port

    def _serve_stop(self) -> None:
        if self._aio is None:
            return
        try:
            if self.server is not None:
                asyncio.run_coroutine_threadsafe(
                    self.server.drain(), self._aio).result(30)
        finally:
            self._aio.call_soon_threadsafe(self._aio.stop)
            if self._loop_thread is not None:
                self._loop_thread.join(timeout=10)
            self._aio = None

    def _status_fields(self) -> Dict:
        return {
            "stream": {
                "rounds_run": self.rounds_run,
                "last_trigger_cause": self.last_trigger["cause"],
                "last_trigger_ts": self.last_trigger["ts"],
            }
        }

    # -- the trigger loop -------------------------------------------------

    def _loop(self, start_round: int, journal, telemetry, sink, ladder,
              save_retry, run_retries0) -> None:
        cfg, scfg = self.cfg, self.stream_cfg
        strategy = self.strategy
        policy = TriggerPolicy(watermark_rows=scfg.watermark_rows,
                               drift_psi=scfg.drift_psi,
                               max_interval_s=scfg.max_interval_s)
        last_round_t = time.monotonic()
        rd = start_round
        last_journal_t = 0.0
        with tele_spans.get_tracer().span(
                "experiment", args={"exp_name": cfg.exp_name,
                                    "exp_hash": cfg.exp_hash,
                                    "stream": True}):
            while True:
                preempt_lib.check()
                if scfg.max_rounds and rd >= scfg.max_rounds:
                    self.logger.info(
                        f"stream: max_rounds={scfg.max_rounds} reached")
                    return
                counters = self.queue.counters()
                if rd == 0:
                    # Bootstrap: the first round needs no trigger — a
                    # model must exist before scores (and drift) can.
                    cause = "bootstrap"
                else:
                    self._probe_drift()
                    psi = self.drift.snapshot().get("psi")
                    cause = policy.decide(
                        counters["pending_rows"],
                        counters["pending_labels"], psi,
                        time.monotonic() - last_round_t,
                        int(strategy.pool.available_mask().sum()))
                now = time.monotonic()
                if cause is None:
                    telemetry.tick(phase="stream_wait",
                                   round=rd - 1 if rd else 0)
                    # Idle journal cadence is bounded below: an idle
                    # service must not rewrite the journal 20x/s just
                    # because the trigger poll is fast.
                    if now - last_journal_t >= max(scfg.poll_s, 2.0):
                        self._journal_stream(journal, counters)
                        last_journal_t = now
                    time.sleep(scfg.poll_s)
                    continue
                self.logger.info(
                    f"stream: round {rd} triggered by {cause} "
                    f"(backlog {counters['pending_rows']} rows, "
                    f"{counters['pending_labels']} labels)")
                self._drain()
                ladder.relax(rd)
                snapshot = _round_snapshot(strategy)
                t_round0 = time.monotonic()
                for attempt in range(ladder.max_attempts()):
                    try:
                        self._run_round(rd, attempt, cause, journal,
                                        telemetry, sink, ladder,
                                        save_retry)
                        break
                    except preempt_lib.PreemptionRequested:
                        raise
                    except ladder_lib.DegradeRequested as exc:
                        if ladder.escalate(exc, rd) is None:
                            raise
                        _restore_round_snapshot(strategy, snapshot, rd)
                    except (Exception, faults.ThreadDeath) as exc:
                        if strategy.pipeline is not None:
                            strategy.pipeline.disarm()
                        if ladder.escalate(exc, rd) is None:
                            raise
                        _restore_round_snapshot(strategy, snapshot, rd)
                # Warm the incremental row updater against the freshly
                # (re-)pinned pool BEFORE this round's jit-delta read:
                # its one compile lands in the round that already paid
                # the pin/growth tax, so the first in-extent drain
                # dispatches warm and rounds after an append stay at
                # delta 0 (tests/test_compile_reuse.py).  Best-effort:
                # a failed warm-up (a transient dummy allocation at the
                # HBM budget edge, say) costs one compile at the next
                # drain, never the service.
                try:
                    resident_lib.prewarm_update(
                        strategy.trainer.resident_pool, self._al_sd,
                        strategy.mesh)
                except Exception:  # noqa: BLE001 - warm-up only
                    self.logger.warning(
                        "stream: incremental-updater warm-up failed; "
                        "the first in-extent drain will pay its "
                        "compile", exc_info=True)
                _emit_round_telemetry(telemetry, sink, rd, strategy,
                                      ladder,
                                      retries_baseline=run_retries0)
                self._emit_stream_gauges(telemetry, sink, rd, cause)
                self._write_report_row(rd, cause, t_round0)
                # What the outgoing checkpoint scored over its ingest
                # window becomes the drift reference for the new one —
                # the ServeScoreDrift hot-reload semantics, driven by
                # round completion instead of a file watcher.
                self.drift.rebaseline(rd)
                self.rounds_run += 1
                self._cause_counts[cause] = \
                    self._cause_counts.get(cause, 0) + 1
                self.last_trigger = {"cause": cause, "ts": time.time()}
                self._journal_stream(journal, self.queue.counters())
                last_round_t = time.monotonic()
                rd += 1
                if int(strategy.pool.available_mask().sum()) == 0 \
                        and scfg.max_rounds == 0 \
                        and self.queue.pending_rows == 0:
                    self.logger.info(
                        "stream: pool exhausted and backlog empty — "
                        "idling for new rows")

    # -- one round (the driver's loop body, verb for verb) ----------------

    def _run_round(self, rd: int, attempt: int, cause: str, journal,
                   telemetry, sink, ladder, save_retry) -> None:
        cfg = self.cfg
        strategy = self.strategy
        init_pool_size = cfg.resolved_init_pool_size()
        with tele_spans.get_tracer().span(
                "round", args={"round": rd, "attempt": attempt,
                               "cause": cause}):
            strategy.round = rd
            telemetry.tick(force=True, round=rd, phase="round_start",
                           epoch=0, step=0)
            journal.write(status="running", round=rd, phase="round_start",
                          attempt=attempt,
                          labeled=strategy.pool.num_labeled,
                          labeled_crc=_labeled_crc(strategy.pool),
                          degrade=list(ladder.active),
                          pipeline_armed=bool(strategy.pipeline),
                          stream_trigger_cause=cause)
            self.logger.info(f"Active Learning Round {rd} start "
                             f"(stream, cause={cause}).")
            strategy.trainer.refresh_resident_budget()
            al_round_0 = rd == 0 and init_pool_size == 0
            if rd > 0 or al_round_0:
                if al_round_0:
                    strategy.init_network_weights()
                with phase_timer("query_time", rd, sink, self.logger):
                    labeled_idxs, cur_cost = strategy.query(
                        cfg.round_budget)
                strategy.update(labeled_idxs, cur_cost)
                self._boundary(rd, "query", journal, ladder)
            with phase_timer("init_network_weights_time", rd, sink,
                             self.logger):
                strategy.init_network_weights()
            self._boundary(rd, "init", journal, ladder)
            if strategy.pipeline is not None and (
                    self.stream_cfg.max_rounds == 0
                    or rd + 1 < self.stream_cfg.max_rounds):
                strategy.pipeline.arm(rd)
            with phase_timer("train_time", rd, sink, self.logger):
                strategy.train()
            self._boundary(rd, "train", journal, ladder)
            with phase_timer("load_best_ckpt_time", rd, sink,
                             self.logger):
                strategy.load_best_ckpt()
            with phase_timer("test_time", rd, sink, self.logger):
                strategy.test()
            if mesh_lib.is_coordinator():
                save_retry.call(resume_lib.save_experiment, strategy, cfg)
                # The experiment state trained on this round's pool is
                # durable — NOW the drained WAL prefix may compact into
                # sealed extents and its segments go (DESIGN.md §16).
                # Best-effort: a failed compaction costs replay work at
                # the next start, never correctness (the WAL it would
                # have pruned is still whole).
                try:
                    self.store.compact(self._applied_seq)
                    pruned = wal_prune_sealed(
                        os.path.join(cfg.log_dir, WAL_DIR),
                        self.store.applied_seq)
                    if pruned:
                        self.logger.info(
                            f"stream: compacted WAL through seq "
                            f"{self.store.applied_seq}; pruned {pruned} "
                            "sealed segment(s)")
                except OSError:
                    self.logger.warning(
                        "stream: WAL compaction failed; will retry "
                        "next round", exc_info=True)
            cfg.resume_training = True
            journal.write(round=rd, phase="round_end",
                          labeled=strategy.pool.num_labeled,
                          labeled_crc=_labeled_crc(strategy.pool))

    def _boundary(self, rd: int, phase: str, journal, ladder) -> None:
        journal.write(round=rd, phase=phase)
        preempt_lib.check()
        ladder.check_stall()

    # -- drain: the ONLY pool mutation point ------------------------------

    def _drain(self) -> int:
        """Apply every pending ingest record to the store + pool state,
        in WAL order.  Idempotent under resume replay: rows the restored
        pool already counts re-validate in place, labels it already
        absorbed are skipped.  Returns the number of appended rows."""
        records = self.queue.drain()
        if not records:
            return 0
        faults.site("stream_drain")
        strategy = self.strategy
        if strategy.pipeline is not None:
            # Quiesce the speculative scorer BEFORE any pool mutation:
            # the incremental update DONATES the pinned buffer (a
            # dispatch against a deleted array would kill the scorer
            # thread), and the appended rows invalidate the speculative
            # plan regardless — disarm waits out the in-flight chunk,
            # establishing update_rows' no-in-flight-consumers
            # contract; the next round re-arms.
            strategy.pipeline.disarm()
        pool = strategy.pool
        appended = 0
        oracle_ids = []
        label_batches = []
        pre_capacity = self.store.capacity
        pre_rows = self.store.n_rows
        for rec in records:
            if rec.get("kind") == "pool":
                ids = self.store.apply_pool_record(rec)
                appended += len(ids)
                if rec.get("labels") is not None:
                    oracle_ids.append(ids)
            else:
                label_batches.append(self.store.apply_label_record(rec))
        # The high-water mark of applied WAL records: what the round-end
        # compaction may seal into the store's extents (and prune from
        # the WAL) once the experiment state trained on them is durable.
        self._applied_seq = max(
            [self._applied_seq]
            + [int(r["seq"]) for r in records if "seq" in r])
        trainer = strategy.trainer
        grew = self.store.capacity != pre_capacity
        if grew:
            # Extent boundary: the pinned SHAPE changed — drop the
            # entries so the round re-uploads at the new extent (at
            # most one growth tax per boundary, pinned in
            # tests/test_compile_reuse.py).
            resident_lib.release(trainer.resident_pool, self._al_sd)
            resident_lib.release(trainer.resident_pool, self._train_sd)
        if appended:
            pool.grow(self.store.capacity)
            for ids in oracle_ids:
                pool.mark_valid(ids)
            self._al_sd.refresh()
            self._train_sd.refresh()
        if not grew:
            # In-extent drain: ONLY the new rows ride h2d — fixed-width
            # dynamic_update_slice blocks into the pinned extent
            # (labels re-upload whole: a tiny device_put, which also
            # covers label-only records) instead of dropping +
            # re-uploading the whole pinned pool per drain (the
            # ROADMAP item 3 remnant this closes).  The al/train views
            # share storage, so ONE update covers both consumers; an
            # entry not pinned yet, a pool smaller than one window, OR
            # any update failure (update_rows already dropped the
            # possibly-donated entry) falls back to the release +
            # re-upload path — where the round's pool_arrays re-pins
            # under the ONE upload RetryPolicy and the degradation
            # ladder, exactly like the pre-incremental behavior.
            try:
                updated = resident_lib.update_rows(
                    trainer.resident_pool, self._al_sd, strategy.mesh,
                    pre_rows, self.store.n_rows)
            except Exception:  # noqa: BLE001 - fall back, never crash
                self.logger.exception(
                    "stream: incremental resident update failed; "
                    "falling back to release + re-upload")
                updated = False
            if not updated:
                resident_lib.release(trainer.resident_pool, self._al_sd)
                resident_lib.release(trainer.resident_pool,
                                     self._train_sd)
        for ids, _labels in label_batches:
            fresh = ids[~pool.labeled[ids]]
            # Defense in depth behind the handler's 400 guard: a WAL
            # label record the pool cannot absorb (a record written
            # before the eval-split validation existed, say) must
            # degrade to a logged skip — raising here would crash-loop
            # the service on every replay of the same record.
            if pool.eval_idxs.size:
                held = fresh[np.isin(fresh, pool.eval_idxs)]
                if held.size:
                    self.logger.warning(
                        f"stream: skipping {held.size} label(s) naming "
                        "validation rows (un-absorbable; the handler "
                        "now rejects these before the WAL)")
                    fresh = fresh[~np.isin(fresh, pool.eval_idxs)]
            if len(fresh):
                pool.absorb_labels(fresh)
        self._probed_rows = 0
        self.logger.info(
            f"stream: drained {len(records)} records — {appended} rows "
            f"appended (pool {self.store.n_rows}/{self.store.capacity} "
            f"rows/capacity), {sum(len(i) for i, _ in label_batches)} "
            "labels attached")
        return appended

    # -- incremental drift scoring ----------------------------------------

    def _probe_drift(self) -> None:
        """Score rows ingested since the last probe with the CURRENT
        best weights and fold them into the live drift histogram — the
        consumer of the ServeScoreDrift signal.  Incremental and
        chunk-aligned: only new rows are scored, in chunk_row_slices
        plans, so splice(chunks) == the monolithic pass bit for bit
        (the PR 7 contract, extended to appended extents).  Consumes no
        rng — probing can never perturb the round chain."""
        strategy = self.strategy
        if strategy is None or strategy.state is None:
            return
        rows = self._pending_pool_rows(self._probed_rows)
        if rows is None or len(rows) == 0:
            return
        ds = ArrayDataset(rows, np.zeros(len(rows), dtype=np.int64),
                          strategy.num_classes, self._al_sd.view)
        bs = strategy._score_batch_size()
        step = strategy._get_score_step("prob_stats")
        chunks = []
        idxs = np.arange(len(rows), dtype=np.int64)
        for sl in scoring.chunk_row_slices(
                len(rows), bs, self.stream_cfg.chunk_batches):
            chunks.append(scoring.collect_pool(
                ds, idxs[sl], bs, step, strategy.state.variables,
                strategy.mesh, keys=("margin",),
                dispatch_lock=strategy.trainer.dispatch_lock))
        out = scoring.splice_chunks(chunks)
        self.drift.observe(out["margin"])
        self._probed_rows += len(rows)

    def _pending_pool_rows(self, skip: int) -> Optional[np.ndarray]:
        """Decoded pending pool rows past the first ``skip`` (the rows
        already probed this drain window)."""
        records = self.queue.snapshot_records()
        rows = []
        seen = 0
        for rec in records:
            if rec.get("kind") != "pool":
                continue
            n = int(rec["shape"][0])
            if seen + n <= skip:
                seen += n
                continue
            decoded, _ = store_lib.decode_pool_payload(
                rec, self.store.image_shape)
            rows.append(decoded[max(0, skip - seen):])
            seen += n
        if not rows:
            return None
        return np.concatenate(rows, axis=0)

    # -- observability ----------------------------------------------------

    def _journal_stream(self, journal, counters: Dict) -> None:
        journal.write(
            stream_pool_rows=self.store.n_rows,
            stream_wal_backlog=counters["pending_rows"],
            stream_wal_seq=self.wal.last_seq,
            stream_rounds_run=self.rounds_run,
            stream_last_trigger_cause=self.last_trigger["cause"],
            stream_last_trigger_ts=self.last_trigger["ts"])

    def _write_report_row(self, rd: int, cause: str,
                          t_round0: float) -> None:
        """One streaming-aware run_report.json row: the driver's
        label-efficiency fields + the ``stream`` block (ingest totals,
        trigger cause, backlog, ack latency) — atomically rewritten per
        round so a killed service still leaves a renderable artifact
        (`python -m active_learning_tpu report <log_dir>`)."""
        if not getattr(self, "_write_report", False):
            return
        strategy = self.strategy
        counters = self.queue.counters()
        lat = self.metrics.snapshot().get("latency_ms") or {}
        now = time.monotonic()
        row = {
            "round": rd,
            "labeled": int(strategy.pool.num_labeled),
            "cumulative_budget": float(strategy.pool.cumulative_cost),
            "test_accuracy": strategy.last_test_acc,
            "round_time_s": round(now - t_round0, 3),
            "wall_clock_s": round(
                self._report_wall_base + (now - self._run_t0), 3),
            "stream": {
                "trigger_cause": cause,
                "ingest_rows_total": counters["accepted_rows_total"],
                "ingest_labels_total": counters["accepted_labels_total"],
                "pool_rows": self.store.n_rows,
                "wal_backlog_rows": counters["pending_rows"],
                "ack_ms_p50": lat.get("p50"),
                "ack_ms_p99": lat.get("p99"),
            },
        }
        diag = getattr(strategy, "diagnostics", None)
        if diag is not None:
            row.update(diag.last_row)
        self._report_rows.append(row)
        diag_lib.write_run_report(self._report_path, self._report_header,
                                  self._report_rows)

    def _emit_stream_gauges(self, telemetry, sink, rd: int,
                            cause: str) -> None:
        counters = self.queue.counters()
        lat = self.metrics.snapshot().get("latency_ms") or {}
        cause_count = self._cause_counts.get(cause, 0) + 1
        gauges = {
            "ingest_rows_total": counters["accepted_rows_total"],
            "ingest_labels_total": counters["accepted_labels_total"],
            "pool_rows_total": self.store.n_rows,
            "wal_backlog_rows": counters["pending_rows"],
            "rounds_triggered_total": self.rounds_run + 1,
            f"rounds_triggered{{cause={cause}}}": cause_count,
            "ingest_ack_ms_p50": lat.get("p50"),
            "ingest_ack_ms_p99": lat.get("p99"),
        }
        _emit_round_gauges(telemetry, sink, rd, gauges)
        telemetry.write_prometheus()


def run_stream(cfg: ExperimentConfig, stream_cfg: StreamConfig,
               sink: Optional[MetricsSink] = None, data=None,
               train_cfg: Optional[TrainConfig] = None, model=None):
    """Build + run one streaming service; returns the Strategy (the
    programmatic mirror of the ``stream`` CLI verb)."""
    return StreamService(cfg, stream_cfg, sink=sink, data=data,
                         train_cfg=train_cfg, model=model).run()
