"""The trigger policy: when does the streaming loop fire an AL round?

Three independent conditions, whichever fires first (DESIGN.md §14's
trigger table):

  watermark   enough NEW rows accepted since the last round — the
              throughput trigger (amortize the round's fixed cost over
              a worthwhile batch of candidates);
  drift       the ``ServeScoreDrift`` PSI of freshly-ingested rows'
              scores vs the checkpoint-time baseline crossed the
              threshold — the DISTRIBUTION trigger (the model's view of
              the incoming data moved, so the current picks/weights are
              going stale regardless of volume).  This is the consumer
              of the online drift signal PR 12 shipped;
  interval    a max wall-clock bound so a trickle of rows (or a pool
              with labeling budget left) still gets served — the
              STALENESS backstop.  Gated on there being any work at all
              (pending ingest or queryable rows): an exhausted, silent
              pool must idle, not spin rounds that re-pick nothing.

Pure host logic, zero jax, trivially unit-testable
(tests/test_stream.py); the service evaluates it once per poll tick.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class TriggerPolicy:
    # Fire when this many new pool rows are pending (0 disables).
    watermark_rows: int = 1024
    # Fire when the ingest-score PSI vs the checkpoint baseline reaches
    # this (0 disables).
    drift_psi: float = 0.25
    # Fire at most this long after the previous round, given any work
    # (0 disables).
    max_interval_s: float = 3600.0

    def decide(self, pending_rows: int, pending_labels: int,
               psi: Optional[float], since_last_round_s: float,
               n_queryable: int) -> Optional[str]:
        """The cause that fires now, or None.  Priority order is
        watermark > drift > interval only for ATTRIBUTION (the journal
        records one cause); any true condition fires the round."""
        if 0 < self.watermark_rows <= pending_rows:
            return "watermark"
        if (self.drift_psi > 0 and psi is not None
                and psi >= self.drift_psi):
            return "drift"
        if (self.max_interval_s > 0
                and since_last_round_s >= self.max_interval_s
                and (pending_rows > 0 or pending_labels > 0
                     or n_queryable > 0)):
            return "interval"
        return None
