"""The growable candidate pool behind the streaming service.

``PoolStore`` owns two ``data/cache.GrowableRowStore`` memmaps (uint8
image rows + int64 targets) seeded from the base dataset and grown by
``pool.bucket_size``-aligned extents as ingest records are applied.
Targets of rows whose oracle label is unknown hold ``UNKNOWN_LABEL``;
such rows are scoreable but not queryable until ``/v1/label`` attaches
their label (PoolState's ``invalid`` mask carries that distinction).

``StreamDataset`` is the Dataset view the Strategy/Trainer stack
consumes.  It reads a SNAPSHOT (rows memmap ref, targets ref, length)
taken at the last ingest drain, so a round in flight never observes
mid-round growth: the ingest thread appends to the store, but the
datasets the round is scoring/training over are frozen until the
service's next drain calls ``refresh()``.  Because growth is ftruncate
(data/cache.py), the snapshot's mapping stays valid even while the file
grows underneath it.

Thread contract: ``apply_pool_record``/``apply_label_record``/
``refresh`` run on the SERVICE thread only (drain points); the ingest
thread never touches the store — handlers queue records
(stream/ingest.py), which is what makes the pool's mutation order a
pure function of WAL order and the round schedule (the bit-identical
resume contract).
"""

from __future__ import annotations

import base64
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..data.cache import GrowableRowStore
from ..data.core import Dataset, ViewSpec

UNKNOWN_LABEL = -1


class PoolStore:
    def __init__(self, directory: str, image_shape: Tuple[int, int, int],
                 num_classes: int,
                 base_images: Optional[np.ndarray] = None,
                 base_targets: Optional[np.ndarray] = None,
                 extent_floor: int = 256):
        self.image_shape = tuple(int(d) for d in image_shape)
        self.num_classes = int(num_classes)
        n0 = len(base_images) if base_images is not None else 0
        self._rows = GrowableRowStore(
            os.path.join(directory, "pool_rows.u8"), self.image_shape,
            dtype=np.uint8, capacity=n0, extent_floor=extent_floor)
        self._targets = GrowableRowStore(
            os.path.join(directory, "pool_targets.i64"), (),
            dtype=np.int64, capacity=n0, extent_floor=extent_floor)
        self.n_rows = 0
        self.n_base = 0
        if base_images is not None:
            assert base_images.dtype == np.uint8
            self._rows.rows[:n0] = base_images[:n0]
            self._targets.rows[:n0] = np.asarray(base_targets,
                                                 dtype=np.int64)[:n0]
            self.n_rows = self.n_base = n0
        # Fresh capacity slots are zero-filled by the sparse create; the
        # targets of padding slots must read UNKNOWN, not class 0.
        self._targets.rows[self.n_rows:] = UNKNOWN_LABEL

    @property
    def capacity(self) -> int:
        return self._rows.capacity

    # -- record application (service thread, drain points only) ----------

    def apply_pool_record(self, record: Dict[str, Any]) -> np.ndarray:
        """Append the record's rows; returns their pool ids.  Ids are a
        pure function of arrival order, which is WAL order — replay
        reproduces them exactly."""
        rows, labels = decode_pool_payload(record, self.image_shape)
        start = self.n_rows
        n = len(rows)
        grew = self._rows.ensure_capacity(start + n)
        self._targets.ensure_capacity(start + n)
        if grew:
            self._targets.rows[start + n:] = UNKNOWN_LABEL
        self._rows.rows[start:start + n] = rows
        self._targets.rows[start:start + n] = (
            np.asarray(labels, dtype=np.int64) if labels is not None
            else UNKNOWN_LABEL)
        self.n_rows = start + n
        return np.arange(start, start + n, dtype=np.int64)

    def apply_label_record(self, record: Dict[str, Any]
                           ) -> Tuple[np.ndarray, np.ndarray]:
        """Attach labels to existing rows; returns (ids, labels).  Range
        errors raise — a label for a row that never existed is a client
        bug the WAL must not have admitted (the handler validates
        against the acked id space before the WAL write)."""
        ids = np.asarray(record["ids"], dtype=np.int64)
        labels = np.asarray(record["labels"], dtype=np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= self.n_rows):
            raise ValueError(
                f"label record names rows outside [0, {self.n_rows})")
        self._targets.rows[ids] = labels
        return ids, labels

    # -- dataset views ----------------------------------------------------

    def make_datasets(self, train_view: ViewSpec, score_view: ViewSpec,
                      length: Optional[int] = None
                      ) -> Tuple["StreamDataset", "StreamDataset"]:
        """(train_set, al_set) over shared storage — the with_view pair
        of the offline path.  ``length`` defaults to the current valid
        row count (build time uses the BASE length so eval/init-pool
        seeds see only base rows)."""
        train = StreamDataset(self, train_view, length=length)
        al = StreamDataset(self, score_view, length=length)
        return train, al

    def snapshot(self) -> Tuple[np.ndarray, np.ndarray, int, int]:
        """(rows_mm, targets_mm, capacity, n_rows) — what a drain
        publishes into the datasets."""
        return self._rows.rows, self._targets.rows, self.capacity, \
            self.n_rows

    def flush(self) -> None:
        self._rows.flush()
        self._targets.flush()


class StreamDataset(Dataset):
    """Frozen-snapshot Dataset view over a PoolStore.  ``images`` is the
    FULL extent-capacity array (the resident upload's shape stays on the
    bucket ladder); ``len`` is the capacity too, with padding slots
    carried as PoolState ``invalid`` entries rather than a shorter
    dataset — every consumer that compiles against the leading dim then
    only ever sees ladder shapes."""

    def __init__(self, store: PoolStore, view: ViewSpec,
                 length: Optional[int] = None):
        self.store = store
        self.view = view
        self.num_classes = store.num_classes
        self.image_shape = store.image_shape
        self._images, self._targets, self._capacity, self._n_valid = \
            store.snapshot()
        if length is not None:
            self._len = int(length)
        else:
            self._len = self._capacity

    def refresh(self, length: Optional[int] = None) -> None:
        """Re-snapshot after a drain (service thread only).  The default
        length becomes the new extent capacity — padding rides as
        PoolState.invalid, keeping the upload shape on the ladder."""
        self._images, self._targets, self._capacity, self._n_valid = \
            self.store.snapshot()
        self._len = int(length) if length is not None else self._capacity

    @property
    def images(self) -> np.ndarray:
        return self._images

    @property
    def targets(self) -> np.ndarray:
        return self._targets

    @property
    def n_valid(self) -> int:
        return self._n_valid

    def __len__(self) -> int:
        return self._len

    def gather(self, idxs: np.ndarray) -> np.ndarray:
        idxs = np.asarray(idxs, dtype=np.int64)
        return np.asarray(self._images[idxs])

    def with_view(self, view: ViewSpec) -> "StreamDataset":
        return StreamDataset(self.store, view, length=self._len)


def decode_pool_payload(record: Dict[str, Any],
                        image_shape: Tuple[int, int, int]
                        ) -> Tuple[np.ndarray, Optional[List[int]]]:
    """{"rows_b64", "shape", "labels"} -> (uint8 rows, labels|None),
    validated against the pool's row shape.  Shared by the WAL-replay
    path and the handler's admission validation (one decoder — the two
    can never disagree on what a record means)."""
    shape = record.get("shape")
    if (not isinstance(shape, (list, tuple)) or len(shape) != 4
            or not all(isinstance(d, int) and not isinstance(d, bool)
                       and d >= 0 for d in shape)):
        raise ValueError("pool record needs shape [n, h, w, c] of "
                         "non-negative integers")
    if tuple(shape[1:]) != tuple(image_shape):
        raise ValueError(
            f"rows of shape {list(shape[1:])} do not match the pool's "
            f"row shape {list(image_shape)}")
    n = int(shape[0])
    if n <= 0:
        raise ValueError("empty pool record")
    raw = base64.b64decode(record["rows_b64"], validate=True)
    if len(raw) != int(np.prod(shape)):
        raise ValueError(f"payload of {len(raw)} bytes does not match "
                         f"shape {list(shape)}")
    rows = np.frombuffer(raw, dtype=np.uint8).reshape(shape)
    labels = record.get("labels")
    if labels is not None:
        if (not isinstance(labels, list) or len(labels) != n
                or not all(isinstance(v, int) and not isinstance(v, bool)
                           and v >= 0 for v in labels)):
            raise ValueError("labels must be one non-negative int per row")
    return rows, labels
