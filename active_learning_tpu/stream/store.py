"""The growable candidate pool behind the streaming service.

``PoolStore`` owns two ``data/cache.GrowableRowStore`` memmaps (uint8
image rows + int64 targets) seeded from the base dataset and grown by
``pool.bucket_size``-aligned extents as ingest records are applied.
Targets of rows whose oracle label is unknown hold ``UNKNOWN_LABEL``;
such rows are scoreable but not queryable until ``/v1/label`` attaches
their label (PoolState's ``invalid`` mask carries that distinction).

``StreamDataset`` is the Dataset view the Strategy/Trainer stack
consumes.  It reads a SNAPSHOT (rows memmap ref, targets ref, length)
taken at the last ingest drain, so a round in flight never observes
mid-round growth: the ingest thread appends to the store, but the
datasets the round is scoring/training over are frozen until the
service's next drain calls ``refresh()``.  Because growth is ftruncate
(data/cache.py), the snapshot's mapping stays valid even while the file
grows underneath it.

Thread contract: ``apply_pool_record``/``apply_label_record``/
``refresh`` run on the SERVICE thread only (drain points); the ingest
thread never touches the store — handlers queue records
(stream/ingest.py), which is what makes the pool's mutation order a
pure function of WAL order and the round schedule (the bit-identical
resume contract).
"""

from __future__ import annotations

import base64
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..data.cache import GrowableRowStore
from ..data.core import Dataset, ViewSpec

UNKNOWN_LABEL = -1

# The compaction manifest (DESIGN.md §16): {applied_seq, n_rows, n_base,
# capacity, image_shape, num_classes}, written tmp+fsync+rename AFTER
# both row stores range-flushed — so a manifest on disk ALWAYS describes
# extents whose bytes are durable.  Its applied_seq is the WAL prefix
# the sealed extents absorb: replay skips records at or below it, and
# wal.prune_sealed may delete segments wholly at or below it.
MANIFEST_FILE = "compact.json"


def read_manifest(directory: str) -> Optional[Dict[str, Any]]:
    """The compaction manifest, or None when absent/unreadable (an
    unreadable manifest reads as nothing-to-reuse — the store rebuilds
    from base + WAL replay, same as the torn-checkpoint rule)."""
    path = os.path.join(directory, MANIFEST_FILE)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            m = json.load(fh)
        if not isinstance(m, dict):
            return None
        for k in ("applied_seq", "n_rows", "n_base", "capacity",
                  "image_shape", "num_classes"):
            if k not in m:
                return None
        return m
    except (OSError, ValueError):
        return None


class PoolStore:
    def __init__(self, directory: str, image_shape: Tuple[int, int, int],
                 num_classes: int,
                 base_images: Optional[np.ndarray] = None,
                 base_targets: Optional[np.ndarray] = None,
                 extent_floor: int = 256, reuse: bool = False):
        self.directory = directory
        self.image_shape = tuple(int(d) for d in image_shape)
        self.num_classes = int(num_classes)
        n0 = len(base_images) if base_images is not None else 0
        # Sealed-extent reuse (``reuse``): a compaction manifest that
        # matches this pool's identity re-opens the extents as they were
        # sealed — no base copy, no replay of the absorbed WAL prefix.
        # Any mismatch (different base length, row shape, classes, or a
        # store file that does not cover the manifest's capacity) falls
        # back to a FRESH build and deletes the stale manifest: a
        # manifest describing extents we just truncated must never be
        # believed by the next open.
        manifest = read_manifest(directory) if reuse else None
        if manifest is not None and (
                tuple(manifest["image_shape"]) != self.image_shape
                or int(manifest["num_classes"]) != self.num_classes
                or int(manifest["n_base"]) != n0):
            manifest = None
        cap0 = int(manifest["capacity"]) if manifest is not None else n0
        self._rows = GrowableRowStore(
            os.path.join(directory, "pool_rows.u8"), self.image_shape,
            dtype=np.uint8, capacity=cap0, extent_floor=extent_floor,
            reuse=manifest is not None)
        self._targets = GrowableRowStore(
            os.path.join(directory, "pool_targets.i64"), (),
            dtype=np.int64, capacity=cap0, extent_floor=extent_floor,
            reuse=manifest is not None)
        if manifest is not None and not (self._rows.reused
                                         and self._targets.reused):
            # Half a reuse is corruption waiting to replay: rebuild both
            # stores fresh and drop the manifest they no longer match.
            self._rows = GrowableRowStore(
                os.path.join(directory, "pool_rows.u8"),
                self.image_shape, dtype=np.uint8, capacity=n0,
                extent_floor=extent_floor)
            self._targets = GrowableRowStore(
                os.path.join(directory, "pool_targets.i64"), (),
                dtype=np.int64, capacity=n0, extent_floor=extent_floor)
            manifest = None
        if manifest is None:
            try:
                os.remove(os.path.join(directory, MANIFEST_FILE))
            except OSError:
                pass
        self.applied_seq = (int(manifest["applied_seq"])
                            if manifest is not None else 0)
        if manifest is not None:
            self.n_rows = int(manifest["n_rows"])
            self.n_base = int(manifest["n_base"])
            return
        self.n_rows = 0
        self.n_base = 0
        if base_images is not None:
            assert base_images.dtype == np.uint8
            self._rows.rows[:n0] = base_images[:n0]
            self._rows.note_written(0, n0)
            self._targets.rows[:n0] = np.asarray(base_targets,
                                                 dtype=np.int64)[:n0]
            self.n_rows = self.n_base = n0
        # Fresh capacity slots are zero-filled by the sparse create; the
        # targets of padding slots must read UNKNOWN, not class 0.
        self._targets.rows[self.n_rows:] = UNKNOWN_LABEL
        self._targets.note_written(0, self._targets.capacity)

    @property
    def capacity(self) -> int:
        return self._rows.capacity

    # -- record application (service thread, drain points only) ----------

    def apply_pool_record(self, record: Dict[str, Any]) -> np.ndarray:
        """Append the record's rows; returns their pool ids.  Ids are a
        pure function of arrival order, which is WAL order — replay
        reproduces them exactly."""
        rows, labels = decode_pool_payload(record, self.image_shape)
        start = self.n_rows
        n = len(rows)
        grew = self._rows.ensure_capacity(start + n)
        self._targets.ensure_capacity(start + n)
        if grew:
            self._targets.rows[start + n:] = UNKNOWN_LABEL
            self._targets.note_written(start + n, self._targets.capacity)
        self._rows.rows[start:start + n] = rows
        self._rows.note_written(start, start + n)
        self._targets.rows[start:start + n] = (
            np.asarray(labels, dtype=np.int64) if labels is not None
            else UNKNOWN_LABEL)
        self._targets.note_written(start, start + n)
        self.n_rows = start + n
        return np.arange(start, start + n, dtype=np.int64)

    def apply_label_record(self, record: Dict[str, Any]
                           ) -> Tuple[np.ndarray, np.ndarray]:
        """Attach labels to existing rows; returns (ids, labels).  Range
        errors raise — a label for a row that never existed is a client
        bug the WAL must not have admitted (the handler validates
        against the acked id space before the WAL write)."""
        ids = np.asarray(record["ids"], dtype=np.int64)
        labels = np.asarray(record["labels"], dtype=np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= self.n_rows):
            raise ValueError(
                f"label record names rows outside [0, {self.n_rows})")
        self._targets.rows[ids] = labels
        if ids.size:
            self._targets.note_written(int(ids.min()), int(ids.max()) + 1)
        return ids, labels

    # -- dataset views ----------------------------------------------------

    def make_datasets(self, train_view: ViewSpec, score_view: ViewSpec,
                      length: Optional[int] = None
                      ) -> Tuple["StreamDataset", "StreamDataset"]:
        """(train_set, al_set) over shared storage — the with_view pair
        of the offline path.  ``length`` defaults to the current valid
        row count (build time uses the BASE length so eval/init-pool
        seeds see only base rows)."""
        train = StreamDataset(self, train_view, length=length)
        al = StreamDataset(self, score_view, length=length)
        return train, al

    def snapshot(self) -> Tuple[np.ndarray, np.ndarray, int, int]:
        """(rows_mm, targets_mm, capacity, n_rows) — what a drain
        publishes into the datasets."""
        return self._rows.rows, self._targets.rows, self.capacity, \
            self.n_rows

    def flush(self) -> None:
        self._rows.flush()
        self._targets.flush()

    def compact(self, applied_seq: int) -> None:
        """Seal the pool's current state into the disk extents: range-
        flush both stores (msync of exactly the written regions — the
        PR's flush-granularity rule), THEN atomically publish the
        manifest naming the WAL prefix those bytes absorb.  Write order
        is the correctness: a crash between flush and rename leaves the
        OLD manifest, so replay re-applies the un-manifested records
        idempotently (apply_* write the same bytes to the same rows —
        ``n_rows`` comes from the manifest, not the file size).  Called
        at round end AFTER save_experiment succeeds: the experiment
        state and the pool prefix it was trained on go durable together,
        which is what keeps WAL-replay resume bit-identical."""
        applied_seq = int(applied_seq)
        if applied_seq <= self.applied_seq:
            return
        self._rows.flush()
        self._targets.flush()
        manifest = {"applied_seq": applied_seq,
                    "n_rows": int(self.n_rows),
                    "n_base": int(self.n_base),
                    "capacity": int(self.capacity),
                    "image_shape": list(self.image_shape),
                    "num_classes": int(self.num_classes)}
        path = os.path.join(self.directory, MANIFEST_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        self.applied_seq = applied_seq


class StreamDataset(Dataset):
    """Frozen-snapshot Dataset view over a PoolStore.  ``images`` is the
    FULL extent-capacity array (the resident upload's shape stays on the
    bucket ladder); ``len`` is the capacity too, with padding slots
    carried as PoolState ``invalid`` entries rather than a shorter
    dataset — every consumer that compiles against the leading dim then
    only ever sees ladder shapes."""

    def __init__(self, store: PoolStore, view: ViewSpec,
                 length: Optional[int] = None):
        self.store = store
        self.view = view
        self.num_classes = store.num_classes
        self.image_shape = store.image_shape
        self._images, self._targets, self._capacity, self._n_valid = \
            store.snapshot()
        if length is not None:
            self._len = int(length)
        else:
            self._len = self._capacity

    def refresh(self, length: Optional[int] = None) -> None:
        """Re-snapshot after a drain (service thread only).  The default
        length becomes the new extent capacity — padding rides as
        PoolState.invalid, keeping the upload shape on the ladder."""
        self._images, self._targets, self._capacity, self._n_valid = \
            self.store.snapshot()
        self._len = int(length) if length is not None else self._capacity

    @property
    def images(self) -> np.ndarray:
        return self._images

    @property
    def targets(self) -> np.ndarray:
        return self._targets

    @property
    def n_valid(self) -> int:
        return self._n_valid

    def __len__(self) -> int:
        return self._len

    def gather(self, idxs: np.ndarray) -> np.ndarray:
        idxs = np.asarray(idxs, dtype=np.int64)
        return np.asarray(self._images[idxs])

    def with_view(self, view: ViewSpec) -> "StreamDataset":
        return StreamDataset(self.store, view, length=self._len)


def decode_pool_payload(record: Dict[str, Any],
                        image_shape: Tuple[int, int, int]
                        ) -> Tuple[np.ndarray, Optional[List[int]]]:
    """{"rows_b64", "shape", "labels"} -> (uint8 rows, labels|None),
    validated against the pool's row shape.  Shared by the WAL-replay
    path and the handler's admission validation (one decoder — the two
    can never disagree on what a record means)."""
    shape = record.get("shape")
    if (not isinstance(shape, (list, tuple)) or len(shape) != 4
            or not all(isinstance(d, int) and not isinstance(d, bool)
                       and d >= 0 for d in shape)):
        raise ValueError("pool record needs shape [n, h, w, c] of "
                         "non-negative integers")
    if tuple(shape[1:]) != tuple(image_shape):
        raise ValueError(
            f"rows of shape {list(shape[1:])} do not match the pool's "
            f"row shape {list(image_shape)}")
    n = int(shape[0])
    if n <= 0:
        raise ValueError("empty pool record")
    raw = base64.b64decode(record["rows_b64"], validate=True)
    if len(raw) != int(np.prod(shape)):
        raise ValueError(f"payload of {len(raw)} bytes does not match "
                         f"shape {list(shape)}")
    rows = np.frombuffer(raw, dtype=np.uint8).reshape(shape)
    labels = record.get("labels")
    if labels is not None:
        if (not isinstance(labels, list) or len(labels) != n
                or not all(isinstance(v, int) and not isinstance(v, bool)
                           and v >= 0 for v in labels)):
            raise ValueError("labels must be one non-negative int per row")
    return rows, labels
