"""The streaming service's asyncio HTTP front end.

Rides the serve stack's wire layer (serve/server.py's request parser,
response writer, and error envelope are imported, not reimplemented) so
the two online surfaces keep one HTTP dialect — same 400/413/429/503
semantics, same keep-alive behavior, same JSON error bodies.

Endpoints:

  POST /v1/pool    append unlabeled rows ({"b64"|"rows_b64", "shape",
                   optional "labels"}) -> {"ok", "seq", "ids"}
  POST /v1/label   attach labels ({"ids", "labels"}) -> {"ok", "seq"}
  GET  /healthz    liveness + pool shape (the loadgen reads
                   ``image_shape`` here, exactly as it does from serve)
  GET  /metrics    ingest counters + ack-latency percentiles + the
                   live score-drift snapshot (JSON, or
                   ``?format=prometheus`` through telemetry/prom)

The handlers the POST routes call live in stream/ingest.py (the closed
registry al_lint check 16 walks); this module only translates HTTP <->
handler calls and records ack latency.  The WAL fsync runs inside the
handler on this event-loop thread via ``run_in_executor`` — the loop
keeps serving reads while a slow disk syncs.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Tuple

from . import ingest as ingest_lib
from ..serve.metrics import ServeMetrics
from ..serve.server import (_HttpError, _parse_json, _read_request,
                            _write_response)
from ..utils.logging import get_logger


class StreamIngestServer:
    """One listener bound to the service's host/port; handlers share the
    service's WAL, pending queue, id space, and drift tracker."""

    def __init__(self, wal, queue: ingest_lib.PendingQueue,
                 ids: ingest_lib.IdSpace, image_shape,
                 host: str = "127.0.0.1", port: int = 0,
                 max_request_rows: int = 512, drift=None,
                 metrics: Optional[ServeMetrics] = None,
                 extra_status=None):
        self.wal = wal
        self.queue = queue
        self.ids = ids
        self.image_shape = tuple(image_shape)
        self.host = host
        self.cfg_port = int(port)
        self.max_request_rows = int(max_request_rows)
        self.drift = drift
        self.metrics = metrics or ServeMetrics()
        self.extra_status = extra_status or (lambda: {})
        self.logger = get_logger()
        self.port: Optional[int] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._draining = False

    # -- lifecycle -------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._client, self.host, self.cfg_port)
        self.port = self._server.sockets[0].getsockname()[1]
        self.logger.info(
            f"stream: ingest listening on http://{self.host}:{self.port} "
            f"(max_request_rows {self.max_request_rows}, backlog bound "
            f"{self.queue.max_backlog_rows} rows)")

    async def drain(self) -> None:
        """Stop accepting; in-flight requests complete (each either got
        its WAL fsync + ack or will answer 503)."""
        if self._draining:
            return
        self._draining = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self.logger.info("stream: ingest listener closed")

    # -- connection handling ---------------------------------------------

    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    req = await _read_request(reader)
                except _HttpError as e:
                    _write_response(writer, e.status, {"error": e.message},
                                    e.headers, keep_alive=False)
                    await writer.drain()
                    break
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if req is None:
                    break
                method, path, headers, body = req
                loop = asyncio.get_running_loop()
                t0 = loop.time()
                status, payload, extra = await self._route(method, path,
                                                           body)
                rows = payload.pop("__rows__", 0) if isinstance(
                    payload, dict) else 0
                self.metrics.record_response(
                    status, loop.time() - t0 if method == "POST" else None,
                    rows=rows)
                keep = (headers.get("connection", "").lower()
                        != "close") and not self._draining
                try:
                    _write_response(writer, status, payload, extra,
                                    keep_alive=keep)
                    await writer.drain()
                except (ConnectionError, OSError):
                    break
                if not keep:
                    break
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001 - peer may already be gone
                pass

    async def _route(self, method: str, path: str, body: bytes
                     ) -> Tuple[int, Dict, Dict[str, str]]:
        path, _, query = path.partition("?")
        try:
            if method == "GET" and path == "/healthz":
                return 200, self._healthz(), {}
            if method == "GET" and path == "/metrics":
                from urllib.parse import parse_qs
                fmt = (parse_qs(query).get("format") or [""])[0]
                if fmt == "prometheus":
                    return 200, self._metrics_prometheus(), {
                        "Content-Type":
                            "text/plain; version=0.0.4; charset=utf-8"}
                if fmt and fmt != "json":
                    raise _HttpError(400, f"unknown metrics format "
                                          f"{fmt!r}; use json or "
                                          "prometheus")
                return 200, self._metrics(), {}
            if method == "POST" and path in ("/v1/pool", "/v1/label"):
                self.metrics.record_request(path)
                if self._draining:
                    raise _HttpError(503, "service is draining")
                req = _parse_json(body)
                loop = asyncio.get_running_loop()
                # The WAL fsync blocks; a worker thread keeps the loop
                # serving reads.  The handlers' own locks (WAL, queue,
                # id space) serialize acceptance order.
                if path == "/v1/pool":
                    out = await loop.run_in_executor(
                        None, lambda: ingest_lib.handle_pool_append(
                            self.wal, self.queue, self.ids, req,
                            self.image_shape, self.max_request_rows))
                else:
                    out = await loop.run_in_executor(
                        None, lambda: ingest_lib.handle_label_attach(
                            self.wal, self.queue, self.ids, req))
                out["__rows__"] = out.get("accepted", 0) \
                    if path == "/v1/pool" else 0
                return 200, out, {}
            raise _HttpError(404, f"no route for {method} {path}")
        except _HttpError as e:
            return e.status, {"error": e.message}, e.headers
        except ingest_lib.IngestError as e:
            headers = ({"Retry-After": str(e.retry_after)}
                       if e.retry_after is not None else {})
            return e.status, {"error": e.message}, headers
        except Exception as e:  # noqa: BLE001 - request isolation
            self.logger.exception("stream: ingest request failed")
            return 500, {"error": repr(e)}, {}

    # -- views -----------------------------------------------------------

    def _healthz(self) -> Dict:
        return {
            "ok": True,
            "image_shape": list(self.image_shape),
            "pool_rows": self.ids.n_rows,
            "max_request_rows": self.max_request_rows,
            "draining": self._draining,
            **self.extra_status(),
        }

    def _metrics(self) -> Dict:
        snap = self.metrics.snapshot()
        snap["ingest"] = self.queue.counters()
        snap["pool_rows"] = self.ids.n_rows
        snap["wal_last_seq"] = self.wal.last_seq
        if self.drift is not None:
            snap["score_drift"] = self.drift.snapshot()
        snap.update(self.extra_status())
        return snap

    def _metrics_prometheus(self) -> str:
        from ..serve.metrics import prometheus_samples
        from ..telemetry import prom
        snap = self._metrics()
        samples = prometheus_samples(snap)
        ing = snap.get("ingest") or {}
        samples += [
            ("al_run_ingest_rows_total", None,
             ing.get("accepted_rows_total")),
            ("al_run_ingest_labels_total", None,
             ing.get("accepted_labels_total")),
            # Same spelling as the round-gauge channel (driver
            # STREAM_GAUGES) and the docs: one quantity, ONE name.
            ("al_run_wal_backlog_rows", None, ing.get("pending_rows")),
            ("al_run_pool_rows_total", None, snap.get("pool_rows")),
            ("al_run_wal_last_seq", None, snap.get("wal_last_seq")),
        ]
        lat = snap.get("latency_ms") or {}
        for q, key in (("0.5", "p50"), ("0.99", "p99")):
            if lat.get(key) is not None:
                samples.append(("al_run_ingest_ack_latency_ms",
                                {"quantile": q}, lat[key]))
        return prom.render(samples)
