"""Host-pure ingest handlers: validate -> WAL (fsync) -> queue -> ack.

This module is the closed handler registry al_lint check 16
(``wal-before-ack``) enforces two properties on:

  1. **WAL before ack** — no handler may construct its ack before the
     WAL append: the fsync inside ``IngestWAL.append`` is what makes
     the ack a durability promise, and an ack built first could be
     delivered by a code path that skips the write.
  2. **Host purity** — no jax import anywhere here.  The ack path must
     never wait on a device: admission, validation, the WAL fsync, and
     the queue push are numpy + stdlib, so ingest latency is disk
     latency, not dispatch latency.

Handlers do NOT touch the pool store.  Accepted records go into the
``PendingQueue``; the service thread drains it at round boundaries
(stream/service.py), which keeps the pool's mutation order a pure
function of WAL order + the round schedule — the property the
bit-identical resume contract rides on.

Admission semantics mirror serve/ (DESIGN.md §6): a request that could
NEVER be admitted (too many rows for one request, malformed payload) is
a 413/400 — non-retryable; a request the backlog can't take RIGHT NOW
is a 429 with Retry-After — explicit backpressure, never unbounded
queueing.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from .store import decode_pool_payload

# The closed registry: exactly the functions the HTTP front end may
# route an ingest request to, and exactly the functions al_lint check 16
# walks.  Appending here without satisfying the WAL-before-ack ordering
# fails the tier-1 lint.
_INGEST_HANDLERS = ("handle_pool_append", "handle_label_attach")

# Lock discipline, statically enforced (scripts/al_lint.py
# lock-discipline): the pending queue and id space are written by the
# ingest server's executor threads and read by the service thread —
# always under the owning object's _lock.
_GUARDED_BY = {"_records": "_lock", "_pending_rows": "_lock",
               "_pending_labels": "_lock", "_n_rows": "_lock"}

# ONE total acceptance order.  WAL seq, acked pool ids, and queue
# position are assigned in three different critical sections; without a
# serializing lock two concurrent requests could interleave them (seq 1
# acked with the ids of seq 2), and since replay applies records in SEQ
# order the resumed pool would disagree with the ids the live service
# promised.  Handlers hold this across admission + WAL append + id
# extension + queue push, making all four orders the same order.  The
# fsync inside append serializes on the disk anyway, so the lock costs
# no real concurrency — and holding admission (reserve) inside it also
# makes the backlog bound a hard bound instead of a racy check.
_INGEST_ORDER_LOCK = threading.Lock()


class IngestError(Exception):
    """Maps 1:1 onto an HTTP error response (the front end translates).
    ``retry_after``: set for backpressure (429) so compliant clients
    pace themselves."""

    def __init__(self, status: int, message: str,
                 retry_after: Optional[int] = None):
        super().__init__(message)
        self.status = int(status)
        self.message = message
        self.retry_after = retry_after


class PendingQueue:
    """Accepted-but-not-yet-drained ingest records, in seq order.

    The admission bound lives here: ``reserve`` is called by handlers
    BEFORE the WAL write (a record the pool can't absorb must be
    refused before it becomes durable), ``drain`` by the service thread
    at round boundaries.  Rows are counted for pool records only —
    label records are metadata-sized."""

    def __init__(self, max_backlog_rows: int):
        self.max_backlog_rows = int(max_backlog_rows)
        self._lock = threading.Lock()
        self._records: List[Dict[str, Any]] = []
        self._pending_rows = 0
        self._pending_labels = 0
        self.accepted_rows_total = 0
        self.accepted_labels_total = 0

    @property
    def pending_rows(self) -> int:
        with self._lock:
            return self._pending_rows

    @property
    def pending_records(self) -> int:
        with self._lock:
            return len(self._records)

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {"pending_rows": self._pending_rows,
                    "pending_labels": self._pending_labels,
                    "pending_records": len(self._records),
                    "accepted_rows_total": self.accepted_rows_total,
                    "accepted_labels_total": self.accepted_labels_total}

    def reserve(self, n_rows: int) -> None:
        """Admission check for ``n_rows`` more pool rows; raises the 429
        IngestError when the backlog bound would be exceeded."""
        with self._lock:
            if self._pending_rows + n_rows > self.max_backlog_rows:
                raise IngestError(
                    429, f"ingest backlog at {self._pending_rows} rows; "
                         f"admitting {n_rows} more would exceed the "
                         f"{self.max_backlog_rows}-row bound — retry "
                         "after the next round drains",
                    retry_after=1)

    def push(self, record: Dict[str, Any], n_rows: int,
             n_labels: int) -> None:
        with self._lock:
            self._records.append(record)
            self._pending_rows += n_rows
            self._pending_labels += n_labels
            self.accepted_rows_total += n_rows
            self.accepted_labels_total += n_labels

    def drain(self) -> List[Dict[str, Any]]:
        """All pending records in acceptance order; resets the backlog
        (service thread, round boundaries)."""
        with self._lock:
            records = self._records
            self._records = []
            self._pending_rows = 0
            self._pending_labels = 0
            return records

    def snapshot_records(self) -> List[Dict[str, Any]]:
        """A copy of the pending records WITHOUT draining — the service
        thread's incremental drift probe reads rows through this."""
        with self._lock:
            return list(self._records)


class IdSpace:
    """The acked pool-id space: base rows + every accepted pool record,
    BEFORE any of it is drained into the store.  Label requests validate
    against this (a label for an id the service never acked is a 400),
    and pool acks are computed from it — both without touching the
    store, which the ingest thread must never read.

    ``unlabelable``: ids that must never take an external label (the
    eval split).  Rejected HERE, before the WAL write: a durable label
    record the drain cannot absorb would replay into the same failure
    on every restart — a poison pill no amount of recovery fixes."""

    def __init__(self, n_rows: int, unlabelable=None):
        self._lock = threading.Lock()
        self._n_rows = int(n_rows)
        self._unlabelable = frozenset(
            int(i) for i in (unlabelable if unlabelable is not None
                             else ()))

    @property
    def n_rows(self) -> int:
        with self._lock:
            return self._n_rows

    def extend(self, n: int) -> Tuple[int, int]:
        with self._lock:
            start = self._n_rows
            self._n_rows += int(n)
            return start, self._n_rows

    def validate_ids(self, ids: List[int]) -> None:
        with self._lock:
            n = self._n_rows
        bad = [i for i in ids if not 0 <= i < n]
        if bad:
            raise IngestError(
                400, f"label ids {bad[:10]} outside the acked pool "
                     f"id space [0, {n})")
        held = [i for i in ids if i in self._unlabelable]
        if held:
            raise IngestError(
                400, f"label ids {held[:10]} are validation rows — the "
                     "eval split never takes external labels")


def ack_response(kind: str, seq: int, ids: List[int]) -> Dict[str, Any]:
    """The success payload.  Constructed ONLY after the WAL append in
    every handler (check 16's ordering rule keys on ack-named calls)."""
    return {"ok": True, "kind": kind, "seq": seq,
            "ids": [int(i) for i in ids], "accepted": len(ids)}


def handle_pool_append(wal, queue: PendingQueue, ids: IdSpace,
                       req: Dict[str, Any], image_shape,
                       max_request_rows: int) -> Dict[str, Any]:
    """POST /v1/pool: append unlabeled candidate rows.

    Body: {"rows_b64"|"b64": ..., "shape": [n,h,w,c],
           "labels": [...] optional oracle labels (simulated AL)}.
    """
    body = dict(req)
    if "b64" in body and "rows_b64" not in body:
        body["rows_b64"] = body.pop("b64")  # the serve wire spelling
    try:
        rows, labels = decode_pool_payload(body, image_shape)
    except (KeyError, ValueError, TypeError) as e:
        raise IngestError(400, f"invalid pool payload: {e}")
    n = len(rows)
    if n > max_request_rows:
        raise IngestError(
            413, f"request of {n} rows exceeds the service's "
                 f"max_request_rows={max_request_rows}; split the "
                 "request")
    record = {"kind": "pool", "shape": [int(d) for d in rows.shape],
              "rows_b64": body["rows_b64"],
              "labels": list(labels) if labels is not None else None}
    # One critical section for admission + durability + id assignment +
    # queue position (see _INGEST_ORDER_LOCK): seq order == acked-id
    # order == drain order == replay order.
    with _INGEST_ORDER_LOCK:
        queue.reserve(n)
        # Durable BEFORE the ack: the fsync inside append is the promise.
        seq = wal.append(record)
        start, _end = ids.extend(n)
        queue.push(dict(record, seq=seq), n_rows=n, n_labels=0)
    return ack_response("pool", seq, list(range(start, start + n)))


def handle_label_attach(wal, queue: PendingQueue, ids: IdSpace,
                        req: Dict[str, Any]) -> Dict[str, Any]:
    """POST /v1/label: attach labels to previously acked pool rows.
    The rows join the labeled set at the next drain (no budget charged —
    these labels arrived from outside the loop).

    Body: {"ids": [...], "labels": [...]}.
    """
    row_ids = req.get("ids")
    labels = req.get("labels")
    if (not isinstance(row_ids, list) or not isinstance(labels, list)
            or not row_ids or len(row_ids) != len(labels)
            or not all(isinstance(i, int) and not isinstance(i, bool)
                       for i in row_ids)
            or not all(isinstance(v, int) and not isinstance(v, bool)
                       and v >= 0 for v in labels)):
        raise IngestError(
            400, "label payload needs equal-length non-empty int lists "
                 "'ids' and 'labels' (labels non-negative)")
    if len(set(row_ids)) != len(row_ids):
        raise IngestError(400, "duplicate ids in one label request")
    record = {"kind": "label", "ids": list(row_ids),
              "labels": list(labels)}
    with _INGEST_ORDER_LOCK:
        ids.validate_ids(row_ids)
        seq = wal.append(record)
        queue.push(dict(record, seq=seq), n_rows=0,
                   n_labels=len(row_ids))
    return ack_response("label", seq, row_ids)
