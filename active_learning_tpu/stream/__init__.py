"""Streaming active learning: continual ingest -> score -> select as one
long-lived service on the persistent mesh (DESIGN.md §14, ROADMAP item 3).

The reference codebase and the paper both assume a frozen disk pool: the
AL loop is an offline batch job over data that all exists at round 0.
This package adds the run-indefinitely workload neither has — a process
that admits new unlabeled rows and labels over HTTP (``POST /v1/pool``,
``POST /v1/label``), re-scores the live pool incrementally, and fires
full AL rounds through the existing driver phases whenever a trigger
policy says so (new-row watermark, ``ServeScoreDrift`` PSI, or a max
wall interval — whichever first).

Module map:

  wal.py        the fsync'd append-only ingest WAL — the durability
                source of truth; written BEFORE the HTTP ack, replayed
                idempotently on ``--resume_training``
  ingest.py     HOST-PURE request handlers (closed ``_INGEST_HANDLERS``
                registry; statically enforced by al_lint check 16
                ``wal-before-ack``: no jax import, no ack before the
                WAL append)
  store.py      the growable candidate pool: memmap rows growing by
                ``pool.bucket_size``-aligned extents so the resident
                shape ladder stays enumerable
  scheduler.py  the trigger policy (watermark / drift / interval)
  server.py     the asyncio HTTP front end (serve/'s wire helpers,
                413/429 admission semantics)
  service.py    the long-lived loop: WAL replay -> bootstrap round ->
                {probe drift, decide, drain, run one driver round}*
  cli.py        the ``stream`` CLI verb

jax enters only in service.py (scoring/rounds); everything the ingest
ack path touches is numpy + stdlib, so the durability promise never
waits on a device.
"""

from .scheduler import TriggerPolicy  # noqa: F401
from .wal import IngestWAL, replay_wal  # noqa: F401
