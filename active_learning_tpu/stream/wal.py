"""The ingest write-ahead log: accepted rows must survive a kill at any
point.

One JSONL record per accepted ingest request, written + flushed +
**fsync'd BEFORE the HTTP ack** (stream/ingest.py is statically held to
that ordering by al_lint check 16).  The WAL is the streaming
subsystem's source of durability truth: the growable pool store
(stream/store.py) is derived state rebuilt from base data + WAL replay
at every service start, and ``--resume_training`` replays the WAL
idempotently — a mid-ingest kill loses only rows that were never acked.

Record schema (one JSON object per line):

  {"seq": n, "kind": "pool",  "crc": ..., "shape": [k,h,w,c],
   "rows_b64": ..., "labels": [...]|null}
  {"seq": n, "kind": "label", "crc": ..., "ids": [...], "labels": [...]}

``seq`` is a contiguous 1-based counter across segments — replay order
IS acceptance order, so applying records in file order reproduces the
pool bit-identically.  ``crc`` covers the payload (crc32 of the
rows_b64 / ids+labels text) so a torn-then-completed line can never
replay as a half-record.

Segments: the active file is ``wal.jsonl``; when it would exceed
``rotate_bytes`` it is SEALED by an atomic rename to
``wal_{first_seq:010d}.jsonl`` (the JsonlSink-rotation idiom: readers
see either the whole old segment or the new empty active file, never a
truncation) and a fresh active file opens.  Replay walks sealed
segments in name order, then the active file.

Torn-tail policy: only the LAST line of the LAST file may fail to parse
— that is the record a kill interrupted mid-write, and since the ack
only ever follows the fsync, dropping it loses nothing that was
promised.  A torn line anywhere else is real corruption and raises.

Failure semantics toward the client: an exception between the fsync and
the ack (or a crash there) leaves a durable record whose ack was never
delivered; a client that retries will append the rows again.  The WAL
contract is therefore at-least-once for un-acked requests and
exactly-once for acked ones — the standard WAL trade, documented in
DESIGN.md §14.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .. import faults

ACTIVE_FILE = "wal.jsonl"
SEALED_GLOB = "wal_*.jsonl"

# Lock discipline, statically enforced (scripts/al_lint.py
# lock-discipline): the appender's file handle and counters are shared
# between the ingest server's executor threads and the service thread's
# bookkeeping reads — always under the WAL's _lock.
_GUARDED_BY = {"_fh": "_lock", "_seq": "_lock",
               "_active_bytes": "_lock", "_first_active_seq": "_lock"}


def record_crc(record: Dict[str, Any]) -> int:
    """crc32 over the payload fields (everything but seq/crc), with
    sorted keys so the digest is layout-independent."""
    payload = {k: v for k, v in record.items() if k not in ("seq", "crc")}
    return zlib.crc32(
        json.dumps(payload, sort_keys=True).encode()) & 0xFFFFFFFF


class IngestWAL:
    """Appender for one service process.  Thread contract: ``append``
    runs on the ingest server's asyncio thread, ``backlog``/bookkeeping
    reads on the service thread — all under ``_lock``."""

    def __init__(self, directory: str, rotate_bytes: int = 64 << 20,
                 replayed=None, base_seq: int = 0):
        self.directory = directory
        self.rotate_bytes = int(rotate_bytes)
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._path = os.path.join(directory, ACTIVE_FILE)
        # Continue the seq chain across restarts: replay tells us the
        # last durable seq (torn tail excluded — it was never acked).
        # ``replayed``: a caller that already ran replay_wal on this
        # directory (the service's startup) hands its records in so a
        # gigabyte WAL is read + crc'd once per start, not twice.
        # ``base_seq``: the compacted prefix's last seq (PoolStore's
        # manifest, DESIGN.md §16) — when compaction pruned EVERY
        # segment, the chain must continue from the manifest, not
        # restart at 1 (a reused seq would alias a compacted record).
        records = (replayed if replayed is not None
                   else replay_wal(directory)[0])
        self._seq = records[-1]["seq"] if records else int(base_seq)
        self._first_active_seq: Optional[int] = None
        # A kill mid-append leaves a torn (newline-less) tail; replay
        # already refused to serve it, and appending AFTER it would glue
        # the next record onto the fragment — truncate back to the last
        # complete line before reopening for append.
        if os.path.exists(self._path):
            with open(self._path, "rb") as fh:
                raw = fh.read()
            if raw and not raw.endswith(b"\n"):
                keep = raw.rfind(b"\n") + 1
                with open(self._path, "r+b") as fh:
                    fh.truncate(keep)
        self._fh = open(self._path, "ab")
        if self._fh.tell() > 0:
            active = [r for r in records
                      if r.get("_file") == ACTIVE_FILE]
            if active:
                self._first_active_seq = active[0]["seq"]
        self._active_bytes = self._fh.tell()

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def append(self, record: Dict[str, Any]) -> int:
        """Durably append one record; returns its seq.  The fsync
        happens HERE, before control returns to the handler — the ack
        the handler builds afterwards is only ever sent for rows already
        on disk."""
        with self._lock:
            faults.site("wal_write")
            seq = self._seq + 1
            rec = dict(record, seq=seq)
            rec["crc"] = record_crc(rec)
            line = json.dumps(rec) + "\n"
            data = line.encode()
            if (self.rotate_bytes > 0 and self._active_bytes > 0
                    and self._active_bytes + len(data) > self.rotate_bytes):
                self._seal_locked()
            # Two-part write with the torn fault point between: a kill
            # here leaves a half line the replay's torn-tail rule drops
            # — the record was never acked, so nothing promised is lost.
            half = len(data) // 2
            self._fh.write(data[:half])
            self._fh.flush()
            faults.site("wal_write", point="torn")
            self._fh.write(data[half:])
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._seq = seq
            self._active_bytes += len(data)
            if self._first_active_seq is None:
                self._first_active_seq = seq
            return seq

    def _seal_locked(self) -> None:
        """Rotate the active file out under the held lock: close, atomic
        rename to its sealed name (keyed by its first seq so name order
        is replay order), reopen fresh."""
        self._fh.close()
        first = self._first_active_seq or (self._seq + 1)
        sealed = os.path.join(self.directory, f"wal_{first:010d}.jsonl")
        try:
            os.replace(self._path, sealed)
        except OSError:
            # Keep appending to the same path (past the cap, but alive):
            # a rotation hiccup must not cost durability.
            pass
        self._fh = open(self._path, "ab")
        self._active_bytes = 0
        self._first_active_seq = None

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except (OSError, ValueError):
                pass
            self._fh.close()


def _wal_files(directory: str) -> List[str]:
    sealed = sorted(glob.glob(os.path.join(directory, SEALED_GLOB)))
    active = os.path.join(directory, ACTIVE_FILE)
    return sealed + ([active] if os.path.exists(active) else [])


def replay_wal(directory: str) -> Tuple[List[Dict[str, Any]], int]:
    """All durable records in acceptance order, plus the count of
    dropped torn-tail lines (0 or 1).  Raises ValueError on corruption
    anywhere except the final line of the final file, and on seq gaps —
    a hole in the chain means a sealed segment went missing, which no
    amount of replay can paper over."""
    if not os.path.isdir(directory):
        return [], 0
    files = _wal_files(directory)
    records: List[Dict[str, Any]] = []
    dropped = 0
    for fi, path in enumerate(files):
        with open(path, "rb") as fh:
            raw = fh.read()
        lines = raw.split(b"\n")
        # A well-formed file ends with a newline -> last split is empty.
        if lines and lines[-1] == b"":
            lines.pop()
        for li, line in enumerate(lines):
            last = fi == len(files) - 1 and li == len(lines) - 1
            try:
                rec = json.loads(line.decode())
                if not isinstance(rec, dict) or "seq" not in rec:
                    raise ValueError("not a WAL record")
                if rec.get("crc") != record_crc(rec):
                    raise ValueError("crc mismatch")
            except (ValueError, UnicodeDecodeError) as e:
                if last:
                    dropped += 1
                    continue
                raise ValueError(
                    f"corrupt WAL record in {path} line {li + 1}: {e}")
            rec["_file"] = os.path.basename(path)
            records.append(rec)
    # Contiguity is checked relative to the FIRST surviving record, not
    # seq 1: compaction (stream/store.py) prunes whole sealed segments
    # the manifest's extents absorb, so a pruned WAL legitimately starts
    # past 1.  Whether the missing prefix is compacted-or-lost is the
    # caller's check (the service validates records[0] against the
    # manifest's applied_seq); a hole in the MIDDLE is always
    # corruption.
    first = records[0]["seq"] if records else 1
    for i, rec in enumerate(records):
        if rec["seq"] != first + i:
            raise ValueError(
                f"WAL seq gap: expected {first + i}, found {rec['seq']} "
                "— a sealed segment is missing or reordered")
    return records, dropped


def prune_sealed(directory: str, upto_seq: int) -> int:
    """Delete SEALED segments whose every record is at or below
    ``upto_seq`` — the compaction hook (stream/store.py writes the
    manifest first; only then may the absorbed prefix go).  A segment's
    coverage is read off its LAST parseable line (segments are
    seq-ordered by construction); a segment that straddles the boundary
    stays whole — replay skips its absorbed records by seq, losing
    nothing.  The ACTIVE file is never touched: the appender owns it.
    Returns the number of segments deleted; unreadable/undecodable
    segments are left alone (deleting what we cannot prove absorbed
    would turn a read hiccup into data loss)."""
    if not os.path.isdir(directory):
        return 0
    deleted = 0
    for path in sorted(glob.glob(os.path.join(directory, SEALED_GLOB))):
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
            lines = [ln for ln in raw.split(b"\n") if ln]
            if not lines:
                continue
            last = json.loads(lines[-1].decode())
            if not isinstance(last, dict) or "seq" not in last:
                continue
            if int(last["seq"]) <= int(upto_seq):
                os.remove(path)
                deleted += 1
            else:
                # Segments are seq-ordered; the first survivor ends it.
                break
        except (OSError, ValueError, UnicodeDecodeError):
            continue
    return deleted


def iter_payloads(records: List[Dict[str, Any]]) -> Iterator[Dict[str, Any]]:
    """Records with the replay-internal ``_file`` tag stripped."""
    for rec in records:
        yield {k: v for k, v in rec.items() if k != "_file"}
