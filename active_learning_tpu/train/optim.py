"""Optimizers and per-epoch LR schedules.

Replaces the reference's string-``eval`` optimizer/scheduler construction
(src/query_strategies/strategy.py:345-350) with explicit optax factories.

Semantics preserved:
  * torch ``SGD(lr, momentum, weight_decay)``: grad += wd * p, then
    heavy-ball momentum, then p -= lr * buf — optax chain
    ``add_decayed_weights -> trace -> scale(-lr)``.
  * Schedulers step once per EPOCH (``scheduler.step()`` at strategy.py:369):
    ``StepLR(step_size, gamma)`` and ``CosineAnnealingLR(T_max)``
    (arg_pools/default.py:41-42, ssp_finetuning.py:31-33).  The trainer
    computes ``lr_at_epoch(epoch)`` on host and feeds the scalar into the
    jitted step — no recompilation, exact per-epoch semantics.

The FUSED update path (``FusedSGD``, DESIGN.md §4 "The gradient path"):
the production optimizer is always SGD+momentum+weight-decay, and the
optax chain spells it as three tree traversals plus a fourth for
``apply_updates`` — four full passes over ~100 MB of ResNet-50 state
per step.  ``fused_sgd_update`` computes the WHOLE update per leaf in
one expression (decay -> momentum -> -lr -> apply), so XLA fuses it
into a single pass over each parameter with its momentum buffer, and
the train step donates the momentum alongside the params (the optax
path already donated the state pytree; the fused path also reuses those
buffers at ROUND boundaries — ``Trainer.reinit_optimizer`` zeroes the
donated tree in place instead of re-allocating + re-uploading a fresh
one).  ``state_dtype=bf16`` stores the momentum in bfloat16 (HALF the
optimizer HBM; read bf16 -> accumulate f32 -> round once on store —
the same discipline as the BN statistics), ``f32`` is BIT-identical to
the optax chain (pinned in tests/test_backward.py).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..config import OptimizerConfig, SchedulerConfig, TrainConfig
from ..registry import OPTIMIZERS, SCHEDULERS


def _sgd(cfg: OptimizerConfig) -> optax.GradientTransformation:
    parts = []
    if cfg.weight_decay:
        parts.append(optax.add_decayed_weights(cfg.weight_decay))
    if cfg.momentum:
        parts.append(optax.trace(decay=cfg.momentum, nesterov=False))
    return optax.chain(*parts) if parts else optax.identity()


def _adam(cfg: OptimizerConfig) -> optax.GradientTransformation:
    return optax.scale_by_adam()


OPTIMIZERS.register("sgd", _sgd)
OPTIMIZERS.register("SGD", _sgd)  # reference spelling (arg pools use "SGD")
OPTIMIZERS.register("adam", _adam)


def make_optimizer(cfg: OptimizerConfig) -> optax.GradientTransformation:
    """Learning-rate-agnostic transform; the lr is applied in the train step
    as ``updates * -lr`` so the host-side schedule stays exact."""
    return OPTIMIZERS.get(cfg.name)(cfg)


# ---------------------------------------------------------------------------
# The fused update path (see module docstring).
# ---------------------------------------------------------------------------

# Statically checked by scripts/trace_lint.py check 9: the fused update
# functions run INSIDE the jitted train step and must never materialize
# state on the host (no np.* references, no .asarray/device_get).
FUSED_UPDATE_FNS = ("fused_sgd_init", "fused_sgd_update")

OPTIM_STATE_DTYPES = ("f32", "bf16")


def resolve_optim_state_dtype(name: str) -> Any:
    if name not in OPTIM_STATE_DTYPES:
        raise ValueError(f"optim_state_dtype={name!r} is not one of "
                         f"{'/'.join(OPTIM_STATE_DTYPES)}")
    return jnp.bfloat16 if name == "bf16" else jnp.float32


def fused_sgd_init(params: Any, state_dtype: Any = jnp.float32) -> Any:
    """Momentum buffers for ``fused_sgd_update``: zeros shaped like the
    params in ``state_dtype`` (bf16 halves optimizer HBM)."""
    return {"trace": jax.tree.map(
        lambda p: jnp.zeros(p.shape, state_dtype), params)}


def fused_sgd_update(grads: Any, opt_state: Any, params: Any, lr,
                     momentum: float, weight_decay: float,
                     state_dtype: Any) -> Tuple[Any, Any]:
    """One fused SGD+momentum+weight-decay step: returns
    ``(new_params, new_opt_state)``.

    Per leaf, ONE expression — XLA fuses the whole update into a single
    pass over (param, momentum) instead of the optax chain's four tree
    traversals.  At f32 state the scalar op sequence is EXACTLY the
    chain's (``g + wd*p``, ``d + momentum*t``, ``p + (-lr)*t'`` with
    apply_updates' dtype cast), so the fused path is bit-identical to
    optax (pinned in tests/test_backward.py); at bf16 state the buffer
    is read bf16, accumulated f32, and rounded ONCE on store.
    """
    acc = jnp.float32

    def leaf(p, g, t):
        d = g + weight_decay * p if weight_decay else g
        if momentum:
            t_new = d + momentum * t.astype(acc)
            t_store = t_new.astype(state_dtype)
        else:
            t_new, t_store = d, t
        p_new = (p + (-lr) * t_new).astype(p.dtype)
        return p_new, t_store

    out = jax.tree.map(leaf, params, grads, opt_state["trace"])
    new_params = jax.tree.map(lambda o: o[0], out,
                              is_leaf=lambda o: isinstance(o, tuple))
    new_trace = jax.tree.map(lambda o: o[1], out,
                             is_leaf=lambda o: isinstance(o, tuple))
    return new_params, {"trace": new_trace}


class FusedSGD:
    """The fused update's hyperparameters + state factory, resolved once
    per Trainer (``make_fused_optimizer``)."""

    def __init__(self, momentum: float, weight_decay: float,
                 state_dtype: Any):
        self.momentum = float(momentum)
        self.weight_decay = float(weight_decay)
        self.state_dtype = state_dtype

    def init(self, params: Any) -> Any:
        if not self.momentum:
            # No momentum -> no buffers; reinit_optimizer's emptiness
            # check relies on the tree having zero leaves.
            return {"trace": {}}
        return fused_sgd_init(params, self.state_dtype)

    def update(self, grads: Any, opt_state: Any, params: Any, lr
               ) -> Tuple[Any, Any]:
        if not self.momentum:
            # Stateless fused decay+apply (no momentum buffer).
            def leaf(p, g):
                d = g + self.weight_decay * p if self.weight_decay else g
                return (p + (-lr) * d).astype(p.dtype)
            return jax.tree.map(leaf, params, grads), opt_state
        return fused_sgd_update(grads, opt_state, params, lr,
                                self.momentum, self.weight_decay,
                                self.state_dtype)


def make_fused_optimizer(train_cfg: TrainConfig) -> Optional[FusedSGD]:
    """The Trainer's ONE rule for whether the fused update path engages:
    ``fused_optimizer`` "on"/"auto" x an SGD-family optimizer.  "on"
    with a non-SGD optimizer fails fast (there is no fused Adam);
    "auto" quietly keeps the optax path for it.  Returns None when the
    optax path should run."""
    mode = getattr(train_cfg, "fused_optimizer", "auto") or "auto"
    if mode not in ("auto", "on", "off"):
        raise ValueError(
            f"fused_optimizer={mode!r} is not one of 'auto'/'on'/'off'")
    is_sgd = train_cfg.optimizer.name.lower() == "sgd"
    if mode == "off":
        return None
    if not is_sgd:
        if mode == "on":
            raise ValueError(
                f"fused_optimizer=on requires an SGD-family optimizer; "
                f"got {train_cfg.optimizer.name!r}")
        return None
    state_dtype = resolve_optim_state_dtype(
        getattr(train_cfg, "optim_state_dtype", "f32") or "f32")
    return FusedSGD(train_cfg.optimizer.momentum,
                    train_cfg.optimizer.weight_decay, state_dtype)


# ---------------------------------------------------------------------------
# Large-batch scaling (the pod tier, DESIGN.md §15).
# ---------------------------------------------------------------------------

# Gradual-warmup length for scaled batches (the large-batch ConvNet
# scaling rules, PAPERS.md: linear LR scaling needs the first epochs
# ramped or the run diverges at the now-k-times-larger step size).
LARGE_BATCH_WARMUP_EPOCHS = 5


def apply_batch_scaling(train_cfg: TrainConfig, scale: int
                        ) -> Tuple[TrainConfig, bool]:
    """The large-batch ConvNet scaling rules applied for a global batch
    grown ``scale``x with the mesh (``--scale_batch auto`` passes the
    device count): train batch x scale (the arg pool's batch becomes a
    PER-CHIP figure), learning rate x scale (linear scaling — the
    per-example gradient contribution to each step stays put), and the
    cosine warmup raised to a >=5-epoch gradual ramp (clamped below
    t_max — _cosine_lr rejects a ramp as long as the schedule).  Step
    schedules keep their milestones: they are epoch-keyed, and epochs
    see the same data under any batch size.  Identity at scale <= 1.
    Returns (config, whether anything changed)."""
    scale = int(scale)
    if scale <= 1:
        return train_cfg, False
    opt = dataclasses.replace(train_cfg.optimizer,
                              lr=train_cfg.optimizer.lr * scale)
    sched = train_cfg.scheduler
    if sched.name in ("cosine", "CosineAnnealingLR") and sched.t_max > 1:
        warm = max(sched.warmup_epochs,
                   min(LARGE_BATCH_WARMUP_EPOCHS, sched.t_max - 1))
        sched = dataclasses.replace(sched, warmup_epochs=warm)
    loader = dataclasses.replace(
        train_cfg.loader_tr,
        batch_size=train_cfg.loader_tr.batch_size * scale)
    return dataclasses.replace(train_cfg, loader_tr=loader,
                               optimizer=opt, scheduler=sched), True


def _step_lr(cfg: SchedulerConfig, base_lr: float) -> Callable[[int], float]:
    def lr_at(epoch0: int) -> float:
        return base_lr * cfg.gamma ** (epoch0 // cfg.step_size)
    return lr_at


def _cosine_lr(cfg: SchedulerConfig, base_lr: float) -> Callable[[int], float]:
    warm = max(0, cfg.warmup_epochs)
    if warm >= cfg.t_max:
        # A ramp as long as the whole schedule never reaches peak lr and
        # leaves no cosine phase — a silent degenerate schedule; callers
        # must clamp (e.g. min(3, epochs // 2)).
        raise ValueError(
            f"warmup_epochs ({warm}) must be < t_max ({cfg.t_max})")

    def lr_at(epoch0: int) -> float:
        if epoch0 < warm:
            # Linear ramp; epoch 0 starts at base_lr/warm, not 0 — an
            # all-zero first epoch would waste a whole epoch of a short
            # AL round.
            return base_lr * (epoch0 + 1) / warm
        span = max(1, cfg.t_max - warm)
        return base_lr * (1 + math.cos(math.pi * (epoch0 - warm) / span)) / 2

    return lr_at


def _constant_lr(cfg: SchedulerConfig, base_lr: float) -> Callable[[int], float]:
    return lambda epoch0: base_lr


SCHEDULERS.register("step", _step_lr)
SCHEDULERS.register("StepLR", _step_lr)
SCHEDULERS.register("cosine", _cosine_lr)
SCHEDULERS.register("CosineAnnealingLR", _cosine_lr)
SCHEDULERS.register("constant", _constant_lr)


def make_lr_schedule(cfg: SchedulerConfig, base_lr: float
                     ) -> Callable[[int], float]:
    """Returns lr_at(epoch0) where epoch0 is the number of completed
    scheduler steps (torch: epoch 1 trains at base_lr, i.e. lr_at(0))."""
    return SCHEDULERS.get(cfg.name)(cfg, base_lr)
