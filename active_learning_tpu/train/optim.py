"""Optimizers and per-epoch LR schedules.

Replaces the reference's string-``eval`` optimizer/scheduler construction
(src/query_strategies/strategy.py:345-350) with explicit optax factories.

Semantics preserved:
  * torch ``SGD(lr, momentum, weight_decay)``: grad += wd * p, then
    heavy-ball momentum, then p -= lr * buf — optax chain
    ``add_decayed_weights -> trace -> scale(-lr)``.
  * Schedulers step once per EPOCH (``scheduler.step()`` at strategy.py:369):
    ``StepLR(step_size, gamma)`` and ``CosineAnnealingLR(T_max)``
    (arg_pools/default.py:41-42, ssp_finetuning.py:31-33).  The trainer
    computes ``lr_at_epoch(epoch)`` on host and feeds the scalar into the
    jitted step — no recompilation, exact per-epoch semantics.
"""

from __future__ import annotations

import math
from typing import Callable

import optax

from ..config import OptimizerConfig, SchedulerConfig
from ..registry import OPTIMIZERS, SCHEDULERS


def _sgd(cfg: OptimizerConfig) -> optax.GradientTransformation:
    parts = []
    if cfg.weight_decay:
        parts.append(optax.add_decayed_weights(cfg.weight_decay))
    if cfg.momentum:
        parts.append(optax.trace(decay=cfg.momentum, nesterov=False))
    return optax.chain(*parts) if parts else optax.identity()


def _adam(cfg: OptimizerConfig) -> optax.GradientTransformation:
    return optax.scale_by_adam()


OPTIMIZERS.register("sgd", _sgd)
OPTIMIZERS.register("SGD", _sgd)  # reference spelling (arg pools use "SGD")
OPTIMIZERS.register("adam", _adam)


def make_optimizer(cfg: OptimizerConfig) -> optax.GradientTransformation:
    """Learning-rate-agnostic transform; the lr is applied in the train step
    as ``updates * -lr`` so the host-side schedule stays exact."""
    return OPTIMIZERS.get(cfg.name)(cfg)


def _step_lr(cfg: SchedulerConfig, base_lr: float) -> Callable[[int], float]:
    def lr_at(epoch0: int) -> float:
        return base_lr * cfg.gamma ** (epoch0 // cfg.step_size)
    return lr_at


def _cosine_lr(cfg: SchedulerConfig, base_lr: float) -> Callable[[int], float]:
    warm = max(0, cfg.warmup_epochs)
    if warm >= cfg.t_max:
        # A ramp as long as the whole schedule never reaches peak lr and
        # leaves no cosine phase — a silent degenerate schedule; callers
        # must clamp (e.g. min(3, epochs // 2)).
        raise ValueError(
            f"warmup_epochs ({warm}) must be < t_max ({cfg.t_max})")

    def lr_at(epoch0: int) -> float:
        if epoch0 < warm:
            # Linear ramp; epoch 0 starts at base_lr/warm, not 0 — an
            # all-zero first epoch would waste a whole epoch of a short
            # AL round.
            return base_lr * (epoch0 + 1) / warm
        span = max(1, cfg.t_max - warm)
        return base_lr * (1 + math.cos(math.pi * (epoch0 - warm) / span)) / 2

    return lr_at


def _constant_lr(cfg: SchedulerConfig, base_lr: float) -> Callable[[int], float]:
    return lambda epoch0: base_lr


SCHEDULERS.register("step", _step_lr)
SCHEDULERS.register("StepLR", _step_lr)
SCHEDULERS.register("cosine", _cosine_lr)
SCHEDULERS.register("CosineAnnealingLR", _cosine_lr)
SCHEDULERS.register("constant", _constant_lr)


def make_lr_schedule(cfg: SchedulerConfig, base_lr: float
                     ) -> Callable[[int], float]:
    """Returns lr_at(epoch0) where epoch0 is the number of completed
    scheduler steps (torch: epoch 1 trains at base_lr, i.e. lr_at(0))."""
    return SCHEDULERS.get(cfg.name)(cfg, base_lr)
