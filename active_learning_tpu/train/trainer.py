"""Per-round training engine.

Replaces the reference's ``Strategy.train`` / ``parallel_train_fn`` /
``_train`` / ``validation_and_early_stopping`` stack
(src/query_strategies/strategy.py:249-442).  Key differences by design:

  * ONE persistent JAX runtime for the whole experiment — no per-round
    ``mp.spawn`` + NCCL process-group setup (strategy.py:288-315).  The
    mesh exists once; each round just re-runs the jitted step.
  * The train step is a single jitted function over a data-sharded batch:
    gradient psum (DDP allreduce, strategy.py:336), global-batch BN stats
    (SyncBatchNorm, strategy.py:292), and the fused normalize/augment all
    come out of XLA's partitioner.
  * BN-freeze semantics preserved: the reference trains with the network in
    eval() mode whenever features are frozen OR a pretrained checkpoint is
    configured (strategy.py:366-367) — here ``train_bn=False`` selects
    running-average BN with no stats update while gradients still flow.
  * Early stopping keeps the best parameters both on disk (best_rd_{n},
    strategy.py:425-430) and in memory.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import serialization, struct

from .. import faults
from ..config import TrainConfig
from ..data.augment import apply_view
from ..faults import preempt as preempt_lib
from ..telemetry import runtime as tele_runtime
from ..telemetry import spans as tele_spans
from ..data.core import Dataset
from ..data.pipeline import (batch_index_lists, iterate_batches,
                             num_batches, padded_batch_layout,
                             train_feed_batches)
from ..parallel import mesh as mesh_lib
from ..utils.logging import get_logger
from . import checkpoint as ckpt_lib
from .evaluation import accumulate_metrics, make_eval_step
from .optim import make_fused_optimizer, make_lr_schedule, make_optimizer


# Checkpoint IO under the ONE retry policy (DESIGN.md §10): a transient
# write failure (full-for-a-moment disk, NFS hiccup, injected
# ckpt_write fault) retries with backoff instead of killing the fit —
# every write here is atomic (tmp + rename), so a retried call simply
# re-runs the whole publish and the pair lands consistent.
_CKPT_RETRY = faults.RetryPolicy(site="ckpt_write",
                                 classify=faults.classify_exception,
                                 max_attempts=3)

# Registered step-builders (scripts/al_lint.py recompile-hazard): every
# jax.jit in this module lives inside one of these — the zero-recompile
# warm-round invariant (tests/test_compile_reuse.py) is only auditable
# when the set of compile sites is enumerable.
_STEP_BUILDERS = ("_build_train_step", "_build_train_step_int8",
                  "_build_chained_train_step",
                  "_build_resident_batch_step", "_build_epoch_scan",
                  "reinit_optimizer")

# Donating callables stored on attributes (al_lint donation-safety):
# attribute name -> donate_argnums of the underlying jitted step.  Every
# non-traced call site must rebind the donated argument from the result
# in the same statement (``state, ... = self._train_step(state, ...)``)
# or the lint flags a use-after-donate of the deleted buffer — the bug
# class reinit_optimizer's out_shardings/zeroing work dodged by hand in
# PR 9.
_DONATES = {"_train_step": (0,),
            "_chained_train_step": (0, 2),
            "_resident_batch_step": (0, 5),
            "_epoch_scan": (0,),
            "_reinit_opt": (0,)}


class TrainState(struct.PyTreeNode):
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jnp.ndarray

    @property
    def variables(self) -> Dict[str, Any]:
        return {"params": self.params, "batch_stats": self.batch_stats}


@dataclasses.dataclass
class FitResult:
    state: TrainState
    best_epoch: int
    best_perf: float
    epochs_run: int
    history: List[Dict[str, float]]


def weighted_cross_entropy(logits, labels, sample_weights):
    """torch ``CrossEntropyLoss(weight=w, reduction='mean')`` semantics:
    sum(w_y * ce) / sum(w_y) (strategy.py:352-356); padding rows carry
    weight 0."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    ce = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32),
                              axis=1)[:, 0]
    denom = jnp.maximum(jnp.sum(sample_weights), 1e-12)
    return jnp.sum(ce * sample_weights) / denom


class Trainer:
    """Owns the jitted train/eval steps for one (model, train-config) pair."""

    def __init__(self, model, train_cfg: TrainConfig, mesh,
                 num_classes: int, train_bn: Optional[bool] = None,
                 current_ckpt_every: Optional[int] = None):
        self.model = model
        self.cfg = train_cfg
        self.mesh = mesh
        self.num_classes = num_classes
        if current_ckpt_every is None:
            current_ckpt_every = train_cfg.current_ckpt_every
        self.current_ckpt_every = max(1, int(current_ckpt_every))
        self.logger = get_logger()
        self.tx = make_optimizer(train_cfg.optimizer)
        # The fused update path (train/optim.FusedSGD, DESIGN.md §4):
        # one tree-fused SGD+momentum+wd+apply expression inside the
        # donated step instead of the optax chain's four traversals —
        # bit-identical to optax at f32 state, bf16 momentum optional.
        # None = the optax chain (non-SGD optimizers, fused "off").
        self.fused_tx = make_fused_optimizer(train_cfg)
        # Gradient-sync precision (parallel/mesh.resolve_grad_allreduce):
        # "f32" keeps the partitioner's bit-exact psum inside plain jit;
        # "int8"/"auto" build the shard_map step with the EQuARX-style
        # block-scaled quantized sync (multi-device meshes only), whose
        # WIRE form resolve_int8_wire picks per mesh: the proven
        # all-gather form through 8 devices, the pod-tier
        # reduce-scatter form above the crossover ("int8_rs" forces it
        # — A/B captures and the chaos matrix).
        _ar_mode = getattr(train_cfg, "grad_allreduce", "f32") or "f32"
        self.grad_allreduce = mesh_lib.resolve_grad_allreduce(_ar_mode,
                                                              mesh)
        self.grad_sync_form = (
            mesh_lib.resolve_int8_wire(_ar_mode, mesh)
            if self.grad_allreduce == "int8" else None)
        self.lr_at = make_lr_schedule(train_cfg.scheduler,
                                      train_cfg.optimizer.lr)
        # Reference quirk (strategy.py:366-367): BN runs in eval mode during
        # training whenever features are frozen or a pretrained ckpt is
        # configured.
        if train_bn is None:
            train_bn = not (model.freeze_feature or train_cfg.has_pretrained)
        self.train_bn = train_bn
        self.n_devices = mesh.devices.size
        # Host-side space-to-depth for streamed (host-batched) paths: the
        # s2d model accepts either layout, so resident/epoch-scan gathers
        # stay raw 3-channel and transform on device for free.
        self._host_s2d = getattr(model, "stem", "default") == "s2d"
        self._train_step = (self._build_train_step_int8()
                            if self.grad_allreduce == "int8"
                            else self._build_train_step())
        self._chained_train_step = self._build_chained_train_step()
        self._epoch_scan: Optional[Callable] = None  # built on first use
        # Donated round-boundary optimizer reset (fused path) — lazy.
        self._reinit_opt: Optional[Callable] = None
        # The resident feed's per-batch execution form (CPU meshes; see
        # _build_resident_batch_step) — also lazy.
        self._resident_batch_step: Optional[Callable] = None
        # The generalized jit-compile counter (telemetry/runtime.py): a
        # no-op unless a run installed telemetry, so unit-test Trainers
        # never accumulate in a process-global registry.
        rt = tele_runtime.get_run()
        rt.register_jit(f"train_step@{id(self):x}", self._train_step)
        rt.register_jit(f"chained_train_step@{id(self):x}",
                        self._chained_train_step)
        self._eval_steps: Dict[Any, Callable] = {}
        # ONE device-resident pool cache for the whole experiment, shared
        # between evaluation (here) and acquisition scoring (the Strategy
        # passes it into collect_pool): pools keyed by their UNDERLYING
        # images array, so al/train views sharing storage upload once and
        # the resident budget is per-array, not per-consumer.
        self.resident_pool: Dict[Any, Any] = {}
        # Concrete resident-pool byte budget: config None = AUTO-sized
        # from live HBM headroom (parallel/resident.resolve_budget);
        # refresh_resident_budget() re-sizes it at round start.
        from ..parallel import resident as resident_lib
        self.resident_budget = resident_lib.resolve_budget(
            train_cfg.resident_scoring_bytes)
        # True while the degradation ladder's feed_host rung holds the
        # budget at 0: the round-start AUTO refresh must not quietly
        # re-admit the resident path mid-degraded-round (set via
        # set_resident_budget(pin=True); relax() unpins).
        self._budget_pinned = False
        # Resident-pool LAYOUT, resolved ONCE for the experiment
        # (DESIGN.md §2b): "row" shards pool rows over the mesh's data
        # axis (per-chip residency = rows/ndev), "replicated" pins one
        # copy per chip.  _shard_ways feeds the eligibility math: under
        # row sharding a chip pins ceil(rows/ndev) rows, so the budget
        # admits pools ~ndev times larger.
        self.pool_sharding = resident_lib.resolve_sharding(
            getattr(train_cfg, "pool_sharding", "auto"), mesh)
        self._shard_ways = (self.n_devices
                            if self.pool_sharding == "row" else 1)
        # The feed the LAST fit actually used + its host-stall figures —
        # round-boundary telemetry (driver gauges) and bench attribution
        # read it; {"source": None} until a fit has run.
        self.last_feed: Dict[str, Any] = {"source": None}
        # ONE enqueue order for collective-bearing dispatches: the
        # pipelined round's speculative scorer dispatches pool chunks
        # from its own thread while fit/evaluate dispatch train and
        # validation steps here — two threads interleaving collective
        # computations with per-device reordering is how a mesh
        # deadlocks.  Every jitted dispatch below (and collect_pool's,
        # via Strategy/pipeline passing this gate) holds it around the
        # enqueue; on CPU meshes the pipelined round additionally flips
        # the gate's drain_mode so each computation COMPLETES before the
        # gate releases (XLA:CPU does not preserve enqueue order at
        # execution — mesh_lib.DispatchGate).  Sequential paths see an
        # uncontended lock and a no-op drain: nanoseconds.
        self.dispatch_lock = mesh_lib.DispatchGate()

    def refresh_resident_budget(self) -> int:
        """Re-size the AUTO resident budget from current HBM headroom
        (called by the driver at round start).  AUTO-budget pools already
        uploaded stay resident regardless — their bytes are already
        counted in bytes_in_use, so a post-upload refresh must not evict
        them (parallel/resident.cached).  An EXPLICIT budget is enforced
        instead: pools over it demote LRU-first (the clean-shrink path —
        a resumed run with a smaller --resident_scoring_bytes, or an
        in-process set_resident_budget)."""
        from ..parallel import resident as resident_lib
        if self._budget_pinned or self.cfg.resident_scoring_bytes is not None:
            # Pinned (the ladder's feed_host rung) or explicit: enforce
            # the held budget instead of re-auto-sizing — a degraded
            # round attempt must actually run degraded.
            resident_lib.enforce_budget(self.resident_pool,
                                        self.resident_budget)
        else:
            # Pass the cache: pinned pools sit inside bytes_in_use, so
            # the headroom-derived budget must add them back to stay a
            # TOTAL cap under the shared eligible() accounting.
            self.resident_budget = resident_lib.resolve_budget(
                None, cache=self.resident_pool)
        return self.resident_budget

    def set_resident_budget(self, budget: int, pin: bool = False) -> list:
        """Shrink (or grow) the resident budget mid-run: the new budget
        is enforced immediately — pinned pools over it demote LRU-first
        and every consumer (scoring, evaluation, the resident-gather
        train feed, including its auto-mode resident_copy fallback,
        whose private upload is charged against the same budget) falls
        back to its host path at the next call, without a batch-shape
        change or a recompile.  Only an EXPLICIT device_resident=True
        keeps the copy-scan path regardless (the operator forced it).
        Returns the demoted cache keys.  ``pin=True`` (the degradation
        ladder) additionally holds the value across the round-start AUTO
        refresh; the default unpins."""
        from ..parallel import resident as resident_lib
        self.resident_budget = int(budget)
        self._budget_pinned = bool(pin)
        return resident_lib.enforce_budget(self.resident_pool,
                                           self.resident_budget)

    # -- setup -----------------------------------------------------------

    def padded_batch_size(self, batch_size: int) -> int:
        """Round up so the batch axis divides evenly over the mesh; padding
        rows are masked out of every reduction."""
        n = self.n_devices
        return -(-batch_size // n) * n

    def eval_batch_size(self, dataset=None) -> int:
        """Global evaluation batch: the reference's test-loader batch (100)
        on CPU, raised on accelerators — the eval pass is per-example
        counts under eval-mode BN, so batch size is throughput-only (same
        policy as acquisition scoring, TrainConfig.score_batch_size).

        The accelerator floor scales with row size (v5e alt-batch probes,
        BENCH r5): 32px ResNet scoring gains +47% at 512 rows/chip over
        256, ImageNet-res scoring +11% at 256 over 128 — small images
        leave the MXU idle at small batches.  128 when the dataset (and
        so the row shape) is unknown."""
        bs = self.cfg.loader_te.batch_size
        if self.mesh.devices.flat[0].platform != "cpu":
            floor = 128
            shape = getattr(dataset, "image_shape", None)
            if shape:
                floor = 512 if shape[0] <= 64 else 256
            bs = max(bs, floor * self.n_devices)
        return bs

    def _opt_init(self, params) -> Any:
        return (self.fused_tx.init(params) if self.fused_tx is not None
                else self.tx.init(params))

    def init_state(self, rng: jax.Array, sample_input: np.ndarray
                   ) -> TrainState:
        variables = self.model.init(rng, jnp.asarray(sample_input),
                                    train=False)
        variables = mesh_lib.replicate(variables, self.mesh)
        opt_state = mesh_lib.replicate(self._opt_init(variables["params"]),
                                       self.mesh)
        return TrainState(params=variables["params"],
                          batch_stats=variables.get("batch_stats", {}),
                          opt_state=opt_state, step=jnp.zeros((), jnp.int32))

    @staticmethod
    def _opt_state_live(opt_state) -> bool:
        """True when every leaf is a live (non-donated) device array —
        the fused reinit may only zero buffers in place if the previous
        round actually left them alive (a crashed attempt's restore
        keeps the donated opt_state of the failed fit)."""
        try:
            return all(not leaf.is_deleted()
                       for leaf in jax.tree.leaves(opt_state)
                       if hasattr(leaf, "is_deleted"))
        except Exception:  # noqa: BLE001 - conservatively reallocate
            return False

    def reinit_optimizer(self, state: TrainState) -> TrainState:
        """Fresh optimizer state at the start of each round (the reference
        constructs a new optimizer per round, strategy.py:345).

        Fused path: the prior round's momentum buffers are DONATED into
        a jitted zeroing — XLA reuses the allocations in place, so the
        round boundary adds no optimizer allocation and no host->device
        upload (the optax path re-built the tree on host and re-uploaded
        it every round; pinned in tests/test_backward.py).  Falls back
        to a fresh init when the buffers are not live (first round, or a
        failed attempt's restore left donated arrays behind)."""
        if self.fused_tx is not None and self._opt_state_live(
                state.opt_state) and jax.tree.leaves(state.opt_state):
            if self._reinit_opt is None:
                # out_shardings pins the REPLICATED layout: without it
                # the zeroed tree comes back single-device, and the
                # next fit's first train step would recompile against
                # the changed input sharding (the zero-recompile
                # warm-round invariant).
                @functools.partial(
                    jax.jit, donate_argnums=(0,),
                    out_shardings=mesh_lib.replicated_sharding(self.mesh))
                def _zero(opt_state):
                    return jax.tree.map(jnp.zeros_like, opt_state)
                self._reinit_opt = _zero
                tele_runtime.get_run().register_jit(
                    f"reinit_opt@{id(self):x}", self._reinit_opt)
            return state.replace(opt_state=self._reinit_opt(state.opt_state),
                                 step=jnp.zeros((), jnp.int32))
        opt_state = mesh_lib.replicate(self._opt_init(state.params),
                                       self.mesh)
        return state.replace(opt_state=opt_state,
                             step=jnp.zeros((), jnp.int32))

    def replace_variables(self, state: TrainState, variables) -> TrainState:
        variables = mesh_lib.replicate(variables, self.mesh)
        return state.replace(params=variables["params"],
                             batch_stats=variables.get("batch_stats", {}))

    # -- jitted steps ----------------------------------------------------

    def _apply_optimizer(self, grads, state: TrainState, lr):
        """ONE optimizer-application rule shared by every step builder:
        the fused single-pass update (train/optim.FusedSGD — donated
        momentum, optional bf16 state) when enabled, else the optax
        chain exactly as before.  Bit-identical at f32 state (pinned in
        tests/test_backward.py).  Traced inside the jitted steps."""
        if self.fused_tx is not None:
            return self.fused_tx.update(grads, state.opt_state,
                                        state.params, lr)
        updates, new_opt_state = self.tx.update(grads, state.opt_state,
                                                state.params)
        updates = jax.tree.map(lambda u: -lr * u, updates)
        return optax.apply_updates(state.params, updates), new_opt_state

    def _build_train_step(self):
        model = self.model
        train_bn = self.train_bn
        apply_optimizer = self._apply_optimizer

        def loss_fn(params, batch_stats, x, labels, weights):
            variables = {"params": params, "batch_stats": batch_stats}
            if train_bn:
                logits, mutated = model.apply(
                    variables, x, train=True, mutable=["batch_stats"])
                new_stats = mutated["batch_stats"]
            else:
                logits = model.apply(variables, x, train=False)
                new_stats = batch_stats
            loss = weighted_cross_entropy(logits, labels, weights)
            return loss, new_stats

        @functools.partial(jax.jit, static_argnames=("view",),
                           donate_argnums=(0,))
        def train_step(state, batch, key, lr, class_weights, view):
            x = apply_view(batch["image"], view, key=key, train=True)
            weights = class_weights[batch["label"]] * batch["mask"]
            (loss, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params, state.batch_stats, x,
                                       batch["label"], weights)
            # Telemetry rider: the global gradient norm, computed where
            # the grads already exist (~|params| FLOPs vs the backward
            # pass's billions) and fetched in the SAME deferred bulk
            # materialization as the loss — zero extra device syncs.
            # Params/opt updates are untouched, so path equality
            # (tests/test_trainer_parallel.py) is unaffected.
            gnorm = optax.global_norm(grads)
            params, new_opt_state = apply_optimizer(grads, state, lr)
            return state.replace(params=params, batch_stats=new_stats,
                                 opt_state=new_opt_state,
                                 step=state.step + 1), loss, gnorm

        return train_step

    def _build_train_step_int8(self):
        """The quantized-gradient-sync train step (DESIGN.md §4): the
        same signature and contract as ``_build_train_step`` — every
        wrapper (chained/resident/epoch-scan) composes unchanged — but
        built over ``shard_map`` so the gradient reduction is OURS, not
        the partitioner's: each device computes grads of its batch
        shard's slice of the global loss, then syncs them through the
        EQuARX-style block-scaled int8 sync in whichever WIRE form the
        mesh resolved (mesh_lib.int8_allreduce on 2-8 device meshes;
        mesh_lib.int8_reduce_scatter — the pod-tier form whose wire
        bytes stay ~2n regardless of device count — above the
        crossover, DESIGN.md §15).  BatchNorm keeps GLOBAL-batch
        statistics via explicitly
        pmean'd means (the model is cloned with ``axis_name`` when it
        supports one; BN-free models run as-is).  This path is
        BOUNDED-DELTA vs the f32 step, never bit-exact — it only builds
        when ``--grad_allreduce int8`` survives the resolve rule and
        the driver's learning probe."""
        axis = mesh_lib.DATA_AXIS
        mesh = self.mesh
        ndev = self.n_devices
        sync_form = self.grad_sync_form
        train_bn = self.train_bn
        apply_optimizer = self._apply_optimizer
        try:
            model = self.model.clone(axis_name=axis)
            self._int8_axis_fallback = False
        except TypeError:
            # Models without an axis_name field carry no way to sync
            # cross-device statistics.  Fine for BN-free models (the
            # test classifiers); a train-mode-BN model here would
            # silently compute per-shard statistics — fit() refuses
            # that combination loudly (the batch_stats tree tells it
            # whether mutable statistics actually exist).
            model = self.model
            self._int8_axis_fallback = True
        from jax.experimental.shard_map import shard_map

        def loss_fn(params, batch_stats, x, labels, weights):
            variables = {"params": params, "batch_stats": batch_stats}
            if train_bn:
                logits, mutated = model.apply(
                    variables, x, train=True, mutable=["batch_stats"])
                new_stats = mutated["batch_stats"]
            else:
                logits = model.apply(variables, x, train=False)
                new_stats = batch_stats
            # The global weighted CE, written shard-locally: local
            # numerator over the GLOBAL (psum'd) denominator — the
            # per-shard losses SUM to the global loss, so summed local
            # grads == global grads (the DDP contract).
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            ce = -jnp.take_along_axis(
                logp, labels[:, None].astype(jnp.int32), axis=1)[:, 0]
            denom = jnp.maximum(
                jax.lax.psum(jnp.sum(weights), axis), 1e-12)
            return jnp.sum(ce * weights) / denom, new_stats

        def body(state, batch, key, lr, class_weights, view):
            # Decorrelate per-shard augmentation draws: each shard sees
            # a fold_in'd key (the f32 path draws one batch-wide key;
            # int8 is bounded-delta, not bit-exact, by contract).
            aug_key = jax.random.fold_in(key, jax.lax.axis_index(axis))
            x = apply_view(batch["image"], view, key=aug_key, train=True)
            weights = class_weights[batch["label"]] * batch["mask"]
            (loss_local, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params, state.batch_stats, x,
                                       batch["label"], weights)
            if sync_form == "reduce_scatter":
                grads = mesh_lib.int8_reduce_scatter(grads, ndev, axis)
            else:
                grads = mesh_lib.int8_allreduce(grads, axis)
            loss = jax.lax.psum(loss_local, axis)
            gnorm = optax.global_norm(grads)
            params, new_opt_state = apply_optimizer(grads, state, lr)
            return state.replace(params=params, batch_stats=new_stats,
                                 opt_state=new_opt_state,
                                 step=state.step + 1), loss, gnorm

        @functools.partial(jax.jit, static_argnames=("view",),
                           donate_argnums=(0,))
        def train_step(state, batch, key, lr, class_weights, view):
            sharded = shard_map(
                functools.partial(body, view=view), mesh=mesh,
                in_specs=(mesh_lib.P(), mesh_lib.P(axis), mesh_lib.P(),
                          mesh_lib.P(), mesh_lib.P()),
                out_specs=(mesh_lib.P(), mesh_lib.P(), mesh_lib.P()),
                check_rep=False)
            return sharded(state, batch, key, lr, class_weights)

        return train_step

    def _build_chained_train_step(self):
        """The host-batched fit path's step with the per-batch PRNG split
        folded into the same jitted call — ONE dispatch per batch instead
        of two (an eager ``jax.random.split`` is its own device dispatch,
        a measurable round-trip per step on remote backends).  Key
        consumption is identical to ``split`` + ``_train_step``, i.e. the
        exact chain the device-resident epoch scan replicates, so all
        three paths stay bit-identical (tests/test_trainer_parallel.py)."""
        train_step = self._train_step

        @functools.partial(jax.jit, static_argnames=("view",),
                           donate_argnums=(0, 2))
        def chained(state, batch, key, lr, class_weights, view):
            new_key, sub = jax.random.split(key)
            new_state, loss, gnorm = train_step(state, batch, sub, lr,
                                                class_weights, view=view)
            return new_state, new_key, loss, gnorm

        return chained

    def _get_eval_step(self, view):
        if view not in self._eval_steps:
            self._eval_steps[view] = make_eval_step(
                self.model, view, self.num_classes)
        return self._eval_steps[view]

    def _build_resident_batch_step(self):
        """The resident-gather feed's PER-BATCH execution form: one
        jitted dispatch = on-device gather from the pinned pool + the
        chained PRNG split + the train step.  Key consumption and batch
        bytes are exactly the epoch scan's (and the host path's), so all
        forms produce the same batch stream; this form exists because
        XLA:CPU executes large conv bodies INSIDE ``lax.scan`` several
        times slower than the same ops dispatched directly (measured 6x
        on ResNet-18 at 112px), while on accelerators the scan's
        one-dispatch-per-epoch wins.  Compiles once per experiment AND
        POOL LAYOUT (the pool shape is constant and the index vector is
        [batch]-sized — no step bucketing involved; ``sharded`` is
        static and fixed per experiment, so warm rounds still add zero
        compiles).  With a row-sharded pool the gather goes through
        resident.sharded_pool_gather (owner psum into the batch
        sharding) instead of a full-array index — same bytes, same
        batch sharding, bit-identical training."""
        train_step = self._train_step
        mesh = self.mesh
        from ..parallel import resident as resident_lib

        @functools.partial(jax.jit, static_argnames=("view", "sharded"),
                           donate_argnums=(0, 5))
        def resident_batch_step(state, images, labels, ids, mask, key,
                                lr, class_weights, view, sharded=False):
            if sharded:
                img, lab = resident_lib.sharded_pool_gather(
                    images, ids, mesh, labels=labels)
            else:
                img = jax.lax.with_sharding_constraint(
                    images[ids], mesh_lib.batch_sharding(mesh))
                lab = labels[ids]
            batch = {"image": img, "label": lab, "mask": mask}
            new_key, sub = jax.random.split(key)
            new_state, loss, gnorm = train_step(state, batch, sub, lr,
                                                class_weights, view=view)
            return new_state, new_key, loss, gnorm

        return resident_batch_step

    def _build_epoch_scan(self):
        """One jitted call = one full epoch over device-resident data.

        The host-batched path dispatches one jitted step per batch — fine
        when gather/decode is the bottleneck (disk datasets), pure dispatch
        overhead when the whole labeled set already sits in HBM (CIFAR
        scale: 50k x 32x32x3 uint8 = 150 MB).  Here the epoch is a single
        ``lax.scan`` over a [steps, batch] index matrix: per step an
        on-device gather + sharding constraint reproduces exactly what
        ``shard_batch`` commits on the host path, and the PRNG-key chain
        (split once per batch) matches it bit for bit, so both paths give
        identical parameters.
        """
        train_step = self._train_step
        mesh = self.mesh
        from ..parallel import resident as resident_lib

        @functools.partial(jax.jit, static_argnames=("view", "sharded"),
                           donate_argnums=(0,))
        def epoch_scan(state, images, labels, idx_mat, mask_mat, valid,
                       key, lr, class_weights, view, sharded=False):
            batch_sharding = mesh_lib.batch_sharding(mesh)

            def body(carry, inp):
                state, key = carry
                idxs, mask, v = inp
                new_key, sub = jax.random.split(key)
                if sharded:
                    # Row-sharded pool: batch rows assembled from their
                    # owning shards (resident.sharded_pool_gather) into
                    # the SAME batch sharding the constraint below
                    # commits — bit-identical batches, shard_map
                    # composes inside the scan body.
                    img, lab = resident_lib.sharded_pool_gather(
                        images, idxs, mesh, labels=labels)
                else:
                    img = jax.lax.with_sharding_constraint(
                        images[idxs], batch_sharding)
                    lab = labels[idxs]
                batch = {"image": img, "label": lab, "mask": mask}
                new_state, loss, gnorm = train_step(state, batch, sub, lr,
                                                    class_weights, view=view)
                # Bucket-padding steps (v == 0) are fully selected away —
                # state, key chain, and loss — so the scan is numerically
                # identical to running exactly the real steps.
                state = jax.tree.map(
                    lambda n, o: jnp.where(v > 0, n, o), new_state, state)
                key = jnp.where(v > 0, new_key, key)
                return (state, key), (loss * v, gnorm * v)

            (state, key), (losses, gnorms) = jax.lax.scan(
                body, (state, key), (idx_mat, mask_mat, valid))
            return state, key, losses, gnorms

        return epoch_scan

    # Steps (and uploaded rows) are bucketed so the epoch scan compiles
    # once per BUCKET, not once per AL round as the labeled set grows:
    # up to STEP_BUCKET steps everything lands on the one floor bucket,
    # beyond it steps round up to a bounded-waste geometric bucket
    # (pool.bucket_size, 1/8-octave granularity).  Padded steps are
    # masked out of the RESULTS (``valid``) but still execute the train
    # step, so the bucket rule bounds that recurring per-epoch waste
    # (25% worst-case, typically a few %) — pure power-of-two buckets
    # would re-spend up to ~2x compute every epoch just past a boundary
    # to save one recompile per round.  Bucket size never changes
    # numerics.
    STEP_BUCKET = 16

    @classmethod
    def bucket_steps(cls, steps_real: int) -> int:
        from ..pool import bucket_size
        return bucket_size(steps_real, floor=cls.STEP_BUCKET)

    # -- the train-feed hierarchy ----------------------------------------

    def resolve_train_feed(self, train_set: Dataset,
                           labeled_idxs: np.ndarray,
                           batch_hook=None) -> str:
        """Pick one feed for a whole fit (resolved ONCE, at fit start —
        a feed must never change mid-fit or a warm round would recompile):

          "resident"      on-device gather of labeled indices from the
                          SAME pinned pool that serves scoring and
                          evaluation — zero host image copies, augment
                          on device inside the epoch scan;
          "resident_copy" the legacy labeled-subset upload + epoch scan
                          (now the special case of resident-gather for
                          pools whose full array doesn't fit the budget
                          while the labeled slice does);
          "host_prefetch" multi-worker gather/decode behind the
                          double-buffered device prefetch
                          (data/pipeline.train_feed_batches);
          "host_serial"   the plain per-batch gather->shard->step loop
                          (always the path under a VAAL batch_hook,
                          which consumes host-ordered sharded batches).

        Every feed yields a bit-identical batch stream at the same rng /
        PRNG-key state (tests/test_trainer_parallel.py) — this decision
        is throughput-only.  cfg.train_feed forces a leg ("resident" /
        "host"); "auto" walks the hierarchy top-down.  cfg.device_resident
        keeps its meaning as the epoch-scan gate: False pins the host
        leg, None applies the measured auto rule (always on accelerators,
        >= 2048 labeled rows on CPU — the scan's extra compile must
        amortize)."""
        from ..parallel import resident as resident_lib
        mode = getattr(self.cfg, "train_feed", "auto") or "auto"
        if mode not in ("auto", "resident", "host"):
            # Fail fast on the first fit: argparse guards the CLI, but a
            # programmatic config with a typo'd mode must not silently
            # train on a different feed than the caller believes.
            raise ValueError(
                f"train_feed={mode!r} is not one of 'auto'/'resident'/"
                "'host'")
        images = getattr(train_set, "images", None)
        in_mem = isinstance(images, np.ndarray)
        # The disk tier (data/diskpool.py, DESIGN.md §16): a paged pool
        # exposes no whole-pool array (``.images`` raises — the static
        # no-materialization contract), but its ``gather`` pages the
        # LABELED rows in bucket-aligned blocks, so the hot tier — the
        # private labeled-subset HBM copy — still applies.  Excluded on
        # multi-process meshes: the copy gathers GLOBAL labeled rows,
        # and each host's disk tier holds only its own row range.
        paged = bool(getattr(train_set, "paged_backend", False)) \
            and not mesh_lib.is_multiprocess(self.mesh)
        hook_free = batch_hook is None

        prefetched = hook_free and (self._feed_workers() > 0
                                    or self.cfg.loader_tr.prefetch > 0)
        host = "host_prefetch" if prefetched else "host_serial"

        scan_possible = hook_free and (in_mem or paged) \
            and self.cfg.device_resident is not False
        resident_ok = scan_possible and resident_lib.eligible(
            train_set, self.resident_budget, cache=self.resident_pool,
            shard_ways=self._shard_ways)
        if mode == "resident":
            if resident_ok:
                return "resident"
            self.logger.warning(
                "train_feed=resident requested but the pool cannot pin "
                "(disk-backed, batch_hook, device_resident=False, or "
                "over the resident budget); falling back down the feed "
                "hierarchy")
            mode = "auto"
        if mode == "host":
            return host
        # auto: the epoch scan must be worthwhile before any resident leg
        # engages (on CPU a small fit's scan compile costs more than it
        # saves; on accelerators per-batch h2d + dispatch always loses).
        on_accel = self.mesh.devices.flat[0].platform != "cpu"
        scan_worthwhile = scan_possible and (
            self.cfg.device_resident is True
            or (self.cfg.device_resident is None
                and (on_accel or len(labeled_idxs) >= 2048)))
        if scan_worthwhile:
            if resident_ok:
                return "resident"
            bs = self.padded_batch_size(self.cfg.loader_tr.batch_size)
            # Backend-agnostic row bytes: a paged pool has no whole
            # array to read shape/itemsize off (uint8 rows by the disk
            # tier's storage contract).
            row_bytes = (int(np.prod(images.shape[1:])) * images.itemsize
                         if in_mem
                         else int(np.prod(train_set.image_shape)))
            copy_bytes = (self.bucket_steps(num_batches(len(labeled_idxs),
                                                        bs)) * bs
                          * row_bytes)
            # The legacy whole-array size guard applies to what actually
            # materializes: the full pool on the in-memory backend, only
            # the hot labeled copy on the paged one (the pool itself is
            # deliberately bigger than any host's RAM there).
            size_guard = (images.nbytes if in_mem else copy_bytes)
            if size_guard <= 2 ** 31 and (
                    # Explicit device_resident=True keeps its legacy
                    # meaning (force the scan path regardless of the
                    # residency budget); under AUTO the private labeled
                    # copy is HBM like any pinned array and must fit the
                    # shared budget — after a mid-run demote, "fall back
                    # to the host path" must mean the host path, not an
                    # unaccounted re-upload.
                    self.cfg.device_resident is True
                    or resident_lib.pinned_bytes(self.resident_pool)
                    + copy_bytes <= self.resident_budget):
                return "resident_copy"
        return host

    def _ensure_exec_form(self, feed: str) -> bool:
        """ONE rule for which jitted execution form a resident-feed fit
        uses — shared by fit and the select-time prefetch
        (prepare_next_fit), so the prefetch can never warm a form the
        fit won't pick.  Lazily builds + registers the chosen form and
        returns ``use_scan``: one scan dispatch per epoch on
        accelerators (and when the scan is explicitly forced), one
        jitted gather+step dispatch per batch on CPU meshes — XLA:CPU
        runs conv bodies inside lax.scan several times slower than
        directly-dispatched ops (_build_resident_batch_step), and the
        per-batch form also skips the step-bucket padding entirely."""
        scan_form = (self.mesh.devices.flat[0].platform != "cpu"
                     or self.cfg.device_resident is True)
        use_scan = (feed == "resident_copy"
                    or (feed == "resident" and scan_form))
        if use_scan and self._epoch_scan is None:
            self._epoch_scan = self._build_epoch_scan()
            tele_runtime.get_run().register_jit(
                f"epoch_scan@{id(self):x}", self._epoch_scan)
        if (feed == "resident" and not use_scan
                and self._resident_batch_step is None):
            self._resident_batch_step = self._build_resident_batch_step()
            tele_runtime.get_run().register_jit(
                f"resident_batch_step@{id(self):x}",
                self._resident_batch_step)
        return use_scan

    def prepare_next_fit(self, train_set: Dataset, labeled_now: np.ndarray,
                         expected_labeled: int) -> Optional[str]:
        """Select-time train prefetch (the pipelined round, DESIGN.md
        §8): while k-center/BADGE selection runs its collective scans on
        the main thread, pre-resolve the feed the COMING fit will take
        — sized at the post-selection labeled count, which is known
        before the picks are — and warm what it touches, so ``fit``
        starts with zero feed stall at step 0:

          * resident-gather: ensure the shared pool is pinned (an upload
            here is one the fit no longer pays) and pre-build the jitted
            execution form the fit will pick, so its first step is a
            cache lookup;
          * host feeds: warm the gather/decode path (memmap cache, page
            cache) over the rows ALREADY labeled — the new picks don't
            exist until selection returns, but they are ``round_budget``
            of ``expected_labeled`` rows; the rest re-decode warm.

        rng-free and state-free by contract: everything here is work the
        fit would do anyway, done early — pipelined and sequential
        rounds stay bit-identical.  Returns the resolved feed (None on
        failure; prefetch is best-effort)."""
        expected = np.arange(max(0, int(expected_labeled)), dtype=np.int64)
        feed = self.resolve_train_feed(train_set, expected, None)
        if feed == "resident":
            self._resident_feed_arrays(train_set)
        if feed in ("resident", "resident_copy"):
            # The SAME form rule + lazy build the fit runs — shared so
            # the prefetch can never warm a form the fit won't use.
            self._ensure_exec_form(feed)
        elif len(labeled_now):
            # Bounded warm-up of the host gather/decode path; the rows
            # land in the memmap/page cache and are dropped here.
            cap = min(len(labeled_now), 4096)
            train_set.gather(np.asarray(labeled_now[:cap], dtype=np.int64))
        return feed

    def _feed_workers(self) -> int:
        """Gather/decode worker threads for the host train feed:
        TrainConfig.feed_workers, deferring to the train loader's
        num_workers (the reference DataLoader row) when unset.  ONE
        resolution shared by the feed decision and the feed itself."""
        if self.cfg.feed_workers is not None:
            return int(self.cfg.feed_workers)
        return int(self.cfg.loader_tr.num_workers)

    def _resident_feed_arrays(self, train_set: Dataset):
        """The resident-gather feed's arrays: the SAME pinned (pool,
        labels) pair scoring and evaluation use — one upload for the
        whole experiment, no second HBM copy, and NOTHING host-side
        beyond the shared-cache lookup.  Uploaded in the experiment's
        resolved pool layout (row-sharded = rows/ndev per chip).  The
        zero-host-copy invariant is enforced statically:
        scripts/trace_lint.py forbids any np.* or .gather()
        materialization inside this function."""
        from ..parallel import resident as resident_lib
        return resident_lib.pool_arrays(self.resident_pool, train_set,
                                        self.mesh,
                                        sharding=self.pool_sharding)

    def _device_resident_arrays(self, train_set: Dataset,
                                labeled_idxs: np.ndarray, batch_size: int):
        """Upload the labeled subset once, padded up to the row bucket so
        consecutive rounds reuse the same compiled scan (replicated; the
        per-step gather output is what gets data-sharded)."""
        images = train_set.gather(labeled_idxs)
        labels = train_set.targets[labeled_idxs].astype(np.int32)
        padded = self.bucket_steps(
            num_batches(len(labeled_idxs), batch_size)) * batch_size
        pad = padded - len(labeled_idxs)
        if pad:
            images = np.concatenate(
                [images, np.zeros((pad, *images.shape[1:]), images.dtype)])
            labels = np.concatenate([labels, np.zeros(pad, labels.dtype)])
        return (mesh_lib.replicate(jnp.asarray(images), self.mesh),
                mesh_lib.replicate(jnp.asarray(labels), self.mesh))

    @classmethod
    def _epoch_index_matrix(cls, n: int, batch_size: int,
                            rng: np.random.Generator):
        """Shuffled fixed-shape [steps, batch] LOCAL index matrix, padding
        mask, and per-step validity — consuming the rng exactly like the
        host path's batch_index_lists(shuffle=True)."""
        perm = rng.permutation(np.arange(n))
        steps_real = num_batches(n, batch_size)
        pad = steps_real * batch_size - n
        if pad:
            # Pad with the last batch's first row — the exact rows
            # gather_batch pads with, so BN batch statistics match the
            # host-batched path bit for bit.
            perm = np.concatenate(
                [perm, np.repeat(perm[(steps_real - 1) * batch_size], pad)])
        mask = np.ones(steps_real * batch_size, dtype=np.float32)
        if pad:
            mask[n:] = 0.0
        steps = cls.bucket_steps(steps_real)
        idx_mat = np.zeros((steps, batch_size), dtype=np.int32)
        mask_mat = np.zeros((steps, batch_size), dtype=np.float32)
        idx_mat[:steps_real] = perm.reshape(steps_real, batch_size)
        mask_mat[:steps_real] = mask.reshape(steps_real, batch_size)
        valid = np.zeros(steps, dtype=np.float32)
        valid[:steps_real] = 1.0
        return idx_mat, mask_mat, valid, steps_real

    # -- per-epoch telemetry ----------------------------------------------

    # EMA smoothing for the loss/grad-norm telemetry series (per-epoch
    # cadence; ~10-epoch effective window).
    TELEMETRY_EMA_ALPHA = 0.2

    @staticmethod
    def _emit_epoch_telemetry(metric_cb, round_idx: int, epoch: int,
                              n_epoch: int, n_images: int,
                              dispatch_wall: float, synced_wall: float,
                              synced: bool, steps: int,
                              step_times: List[float]) -> None:
        """Step-time p50/p99 and imgs/sec for one epoch, through the
        caller's metric sink — with nothing dishonest on async backends
        (jax dispatch returns before the device finishes, and this path
        deliberately adds NO device sync of its own):

          * host-batched path (``step_times`` non-empty): loop-cadence
            percentiles — each delta spans gather + dispatch, and the
            donated-buffer backpressure makes steady-state cadence track
            real step time;
          * epoch-scan path (ONE dispatch per epoch): the only honest
            anchor is the validation fetch that follows the scan, so the
            per-step mean is derived from the SYNCED train+val wall
            (p50 == p99 labels it as a mean; slightly over-counting val
            beats under-counting the scan by orders of magnitude);
          * epoch-scan without early stopping: no sync exists anywhere
            in the epoch — nothing trustworthy to emit, so nothing is.

        Step axis: the same round-folded epoch counter set_epoch uses,
        so multi-round runs keep a monotonic x-axis."""
        if metric_cb is None or steps <= 0:
            return
        from ..telemetry.runtime import percentile
        if step_times:
            p50 = percentile(step_times, 0.50)
            p99 = percentile(step_times, 0.99)
            wall = dispatch_wall
        elif synced:
            wall = synced_wall
            p50 = p99 = wall / steps
        else:
            return
        if wall <= 0:
            return
        tele_step = round_idx * (n_epoch + 1) + epoch
        metric_cb("step_time_ms_p50", round(p50 * 1000.0, 3), tele_step)
        metric_cb("step_time_ms_p99", round(p99 * 1000.0, 3), tele_step)
        metric_cb("imgs_per_sec", round(n_images / wall, 1), tele_step)

    def _emit_feed_telemetry(self, metric_cb, tele_step: int,
                             host_waits: List[float],
                             train_wall: float) -> None:
        """Per-epoch feed-boundedness: ``feed_stall_frac`` (fraction of
        the epoch's train wall spent blocked on the host feed) and
        ``host_wait_ms_p50`` (per-batch wait median) — a host-bound
        epoch reads off ``status``/the sink without a profiler.  The
        resident/epoch-scan legs have NO host feed and emit explicit
        zeros: "the feed costs nothing" is a statement, not an absence.
        Both also land in ``last_feed`` for the driver's round gauges
        (Prometheus) and bench attribution."""
        from ..telemetry.runtime import percentile
        if host_waits and train_wall > 0:
            stall = min(1.0, sum(host_waits) / train_wall)
            p50_ms = percentile(host_waits, 0.50) * 1000.0
        else:
            stall, p50_ms = 0.0, 0.0
        self.last_feed["feed_stall_frac"] = round(stall, 4)
        self.last_feed["host_wait_ms_p50"] = round(p50_ms, 3)
        if metric_cb is not None:
            metric_cb("feed_stall_frac", round(stall, 4), tele_step)
            metric_cb("host_wait_ms_p50", round(p50_ms, 3), tele_step)

    # -- class weights ---------------------------------------------------

    def class_weights(self, labels: np.ndarray) -> np.ndarray:
        """Imbalanced-training class weights (strategy.py:444-457):
        observed classes get total/count, unobserved keep 1, normalized to
        sum 1.  Identity (all ones) when imbalanced_training is off."""
        if not self.cfg.imbalanced_training:
            return np.ones(self.num_classes, dtype=np.float32)
        uniq, counts = np.unique(labels, return_counts=True)
        weights = np.ones(self.num_classes, dtype=np.float64)
        weights[uniq] = counts.sum() / counts
        weights /= weights.sum()
        return weights.astype(np.float32)

    # -- evaluation ------------------------------------------------------

    def evaluate(self, state: TrainState, dataset: Dataset,
                 idxs: np.ndarray) -> Dict[str, np.ndarray]:
        """Top-1/top-5/per-class metrics over ``dataset[idxs]``
        (replaces evaluation.py:11-105)."""
        eval_step = self._get_eval_step(dataset.view)
        bs = self.padded_batch_size(self.eval_batch_size(dataset))
        variables = state.variables

        from ..parallel import resident as resident_lib
        if resident_lib.eligible(dataset, self.resident_budget,
                                 cache=self.resident_pool,
                                 shard_ways=self._shard_ways):
            # Device-resident path: on-device row gather per batch, count
            # totals accumulated ON DEVICE (one host fetch at the end) so
            # async dispatch pipelines the whole eval pass; see
            # parallel/resident.py for the shared cache and the
            # virtual-CPU-mesh caveat.  resident_scoring_bytes=0 disables.
            # The runner follows the ENTRY's actual layout (an entry
            # uploaded row-sharded stays row-sharded for every consumer).
            images_dev, labels_dev = resident_lib.pool_arrays(
                self.resident_pool, dataset, self.mesh,
                sharding=self.pool_sharding)
            run = resident_lib.get_runner(
                self.resident_pool, eval_step, self.mesh, with_labels=True,
                sharded=mesh_lib.is_row_sharded(images_dev))
            totals = None
            for b in batch_index_lists(np.asarray(idxs), bs):
                ids, mask = padded_batch_layout(b, bs)
                with self.dispatch_lock:
                    small = mesh_lib.replicate((ids.astype(np.int32), mask),
                                               self.mesh)
                    counts = run(variables, images_dev, labels_dev, *small)
                    totals = (counts if totals is None
                              else jax.tree.map(jnp.add, totals, counts))
                    self.dispatch_lock.drain(totals)
            return accumulate_metrics(iter(() if totals is None
                                           else (totals,)))

        local = mesh_lib.process_local_rows(self.mesh, bs)

        def counts():
            for batch in iterate_batches(
                    dataset, idxs, bs,
                    num_threads=self.cfg.loader_te.num_workers,
                    prefetch=self.cfg.loader_te.prefetch, local=local,
                    s2d=self._host_s2d):
                # Dispatch under the lock, yield outside it: the lock
                # orders enqueues only and must never be held across the
                # consumer's (possibly fetching) work.
                with self.dispatch_lock:
                    out = eval_step(variables,
                                    mesh_lib.shard_batch(batch, self.mesh))
                    self.dispatch_lock.drain(out)
                yield out

        return accumulate_metrics(counts())

    # -- the fit loop ----------------------------------------------------

    def fit(
        self,
        state: TrainState,
        train_set: Dataset,
        labeled_idxs: np.ndarray,
        al_set: Dataset,
        eval_idxs: np.ndarray,
        n_epoch: int,
        es_patience: int,
        rng: np.random.Generator,
        round_idx: int = 0,
        weight_paths: Optional[Dict[str, str]] = None,
        metric_cb: Optional[Callable[[str, float, int], None]] = None,
        batch_hook: Optional[Callable[[int, Dict[str, np.ndarray]], None]]
        = None,
        resume_fit_state: bool = True,
        on_best: Optional[Callable[[int, int, Dict[str, Any]], None]]
        = None,
    ) -> FitResult:
        """Train on the labeled subset with per-epoch validation + early
        stopping (parallel_train_fn, strategy.py:304-381).

        ``es_patience == 0`` disables early stopping (parser.py:66-69); in
        that case the final parameters become the "best" (the reference
        would crash in load_best_ckpt — deliberate fix).

        ``batch_hook(epoch, host_batch)`` runs after each classifier step —
        the seam that lets VAAL co-train its VAE/discriminator inside the
        same epoch loop (the reference overrides the whole
        parallel_train_fn, vaal_sampler.py:77-183).

        ``on_best(round_idx, epoch, variables)`` fires whenever a new
        best-validation snapshot is taken — the in-process publish leg
        of the best-ckpt bus (the pipelined round's speculative scorer
        subscribes; experiment/pipeline.py).  The variables tree is the
        fresh device-side copy, never donated afterwards, so the
        subscriber may keep using it.  A failing callback is logged and
        ignored: speculation must never take a fit down."""
        use_es = es_patience != 0 and len(eval_idxs) > 0
        from ..data.cache import CachedEvalRows, DecodedPoolCache
        if (use_es and self.cfg.cache_eval and hasattr(al_set, "paths")
                and not al_set.train_transform
                and not isinstance(al_set, DecodedPoolCache)):
            # Disk-backed eval rows decode identically every epoch (the
            # val view is deterministic) — decode each once per round.
            # Skipped when the experiment-lifetime memmap cache already
            # wraps the pool: rows then stream from the page cache and a
            # second RAM copy buys nothing.
            al_set = CachedEvalRows(al_set,
                                    max_bytes=self.cfg.cache_eval_bytes)
        labels = train_set.targets[labeled_idxs]
        class_weights = jnp.asarray(self.class_weights(labels))
        if (self.grad_allreduce == "int8"
                and getattr(self, "_int8_axis_fallback", False)
                and self.train_bn
                and jax.tree.leaves(state.batch_stats)):
            # The int8 step could not thread the mesh axis into this
            # model (no axis_name field) AND the model carries mutable
            # batch statistics that would train PER-SHARD inside the
            # shard_map body — divergent, silently-wrong BN.  Refuse
            # loudly; grad_allreduce=f32 (or an axis_name-capable
            # model) is the fix.
            raise ValueError(
                "grad_allreduce=int8 with a train-mode-BatchNorm model "
                "that has no axis_name field: cross-device statistics "
                "cannot be synced inside the quantized step — use "
                "--grad_allreduce f32 or a model exposing axis_name")
        state = self.reinit_optimizer(state)
        bs = self.padded_batch_size(self.cfg.loader_tr.batch_size)

        # The train feed, resolved ONCE for the whole fit (DESIGN.md §2a:
        # resident-gather > prefetched-host > serial-host).  On the
        # resident legs each epoch is ONE jitted scan whose per-step
        # on-device gather + augment reproduce the host stream bit for
        # bit (tests/test_trainer_parallel.py); "resident" draws from the
        # SAME pinned pool scoring/evaluation use (zero host image
        # copies), "resident_copy" from a private labeled-subset upload.
        feed = self.resolve_train_feed(train_set, labeled_idxs, batch_hook)
        use_scan = self._ensure_exec_form(feed)
        self.last_feed = {"source": feed, "feed_stall_frac": None,
                          "host_wait_ms_p50": None,
                          "form": ("scan" if use_scan else
                                   "step" if feed == "resident" else
                                   "loop")}
        feed_map = None
        dr_sharded = False
        if feed == "resident":
            # Local epoch-matrix positions -> GLOBAL pool rows.  int32:
            # resident pools are bounded by HBM, far under 2^31 rows.
            feed_map = np.asarray(labeled_idxs, dtype=np.int32)
            dr_images, dr_labels = self._resident_feed_arrays(train_set)
            # Execution follows the entry's ACTUAL layout (a pool pinned
            # replicated before a config change stays replicated): the
            # flag is static on the jitted forms, fixed per experiment.
            dr_sharded = mesh_lib.is_row_sharded(dr_images)
        elif feed == "resident_copy":
            # The legacy private labeled-subset copy stays replicated
            # (it is bucket-padded per round; sharding it would buy
            # little and cost a layout axis on the step bucketing).
            dr_images, dr_labels = self._device_resident_arrays(
                train_set, labeled_idxs, bs)
            if getattr(train_set, "paged_backend", False):
                # The disk tier's HBM leg: the hot copy joins the shared
                # budget accounting (pinned_bytes/enforce_budget) under
                # one per-trainer slot — re-pinned each fit, so the
                # previous round's copy is replaced, never accumulated.
                from ..parallel import resident as resident_lib
                resident_lib.pin_hot(self.resident_pool,
                                     f"hot_rows@{id(self):x}",
                                     dr_images, dr_labels)
        best_perf, best_epoch, es_count = 0.0, 0, 0
        best_variables = None  # device tree after an improvement this fit
        best_dirty = False  # True = best_variables newer than best_ckpt
        history: List[Dict[str, float]] = []
        key = jax.random.PRNGKey(int(rng.integers(0, 2 ** 31 - 1)))

        # Mid-round resume: if a fit-state checkpoint for THIS round exists
        # (written periodically below, deleted when the round completes), a
        # crashed/preempted fit continues from its last completed epoch
        # bit-for-bit instead of restarting the round — epoch-granularity
        # recovery the reference lacks (its rd_{n}.pth is written every
        # epoch and never read back, strategy.py:440).  VAAL's co-trained
        # VAE/discriminator state is not covered: with a batch_hook the
        # resumed fit restarts from epoch 1.
        start_epoch = 1
        if weight_paths and batch_hook is None and not resume_fit_state:
            # This fit starts from scratch by the caller's decision (a
            # fresh, non-resumed experiment run).  A fit state on disk here
            # is from an OLDER dead run of the same experiment directory —
            # consuming it would silently splice two runs together.
            if os.path.exists(weight_paths["fit_state"] + ".json"):
                self.logger.warning(
                    "Discarding a stale mid-round fit state from a "
                    "previous run (start this run with --resume_training "
                    "to consume it)")
            ckpt_lib.delete_fit_state(weight_paths["fit_state"])
        if weight_paths and batch_hook is None and resume_fit_state:
            saved = ckpt_lib.load_fit_state(weight_paths["fit_state"],
                                            round_idx)
            if saved is not None:
                try:
                    opt_state = serialization.from_state_dict(
                        jax.tree.map(np.asarray, state.opt_state),
                        saved["opt_state"])
                except Exception:  # noqa: BLE001 - layout drift
                    # The saved optimizer state has a different pytree
                    # layout than this Trainer's (the fused path's
                    # {"trace": ...} vs the optax chain's tuple state —
                    # a --fused_optimizer change, or a pre-fused-era
                    # checkpoint resumed under the new default).  The
                    # fit state is all-or-nothing (its rng chain and
                    # epoch counter assume the whole restore): discard
                    # it and restart the round from scratch rather than
                    # crash the resume.
                    self.logger.warning(
                        "mid-round fit state holds an incompatible "
                        "optimizer-state layout (the optimizer path "
                        "changed between runs); discarding it — round "
                        f"{round_idx} restarts from its first epoch")
                    ckpt_lib.delete_fit_state(weight_paths["fit_state"])
                    saved = None
            if saved is not None:
                host = jax.tree.map(np.asarray, state.variables)
                variables = serialization.from_state_dict(
                    host, saved["variables"])
                state = TrainState(
                    params=mesh_lib.replicate(variables["params"],
                                              self.mesh),
                    batch_stats=mesh_lib.replicate(
                        variables.get("batch_stats", {}), self.mesh),
                    opt_state=mesh_lib.replicate(opt_state, self.mesh),
                    step=jnp.asarray(saved["step"], jnp.int32))
                best_perf = float(saved["best_perf"])
                best_epoch = int(saved["best_epoch"])
                es_count = int(saved["es_count"])
                key = jnp.asarray(np.asarray(saved["key"], dtype=np.uint32))
                rng.bit_generator.state = saved["rng_state"]
                start_epoch = int(saved["epoch"]) + 1
                if best_epoch > 0:
                    # The COORDINATOR's view of best_ckpt decides for every
                    # process: this branch resets early-stopping control
                    # state (es_count), and a per-process filesystem check
                    # (NFS attribute-cache lag on a pod) could send
                    # processes down different epoch counts — mismatched
                    # collectives hang the job.
                    have_best = os.path.exists(weight_paths["best_ckpt"])
                    if mesh_lib.is_multiprocess(self.mesh):
                        from jax.experimental import multihost_utils
                        have_best = bool(multihost_utils.broadcast_one_to_all(
                            np.uint8(have_best)))
                    if have_best:
                        best_variables = ckpt_lib.load_variables(
                            weight_paths["best_ckpt"], like=host)
                    else:
                        # The weights best_perf refers to are gone; keeping
                        # the stale score would make the no-improvement
                        # fallback report it over final-epoch weights.
                        self.logger.warning(
                            f"fit-state references best epoch {best_epoch} "
                            "but best_ckpt is missing; restarting "
                            "best-model tracking")
                        best_perf, best_epoch, es_count = 0.0, 0, 0
                self.logger.info(
                    f"Resuming round {round_idx} training from epoch "
                    f"{start_epoch} (mid-round fit state)")

        # Per-step/per-epoch telemetry (DESIGN.md §7).  ``collect`` False
        # (no run installed, or telemetry off) must add NO per-step work:
        # every perf_counter call and list append below is gated on it.
        rt = tele_runtime.get_run()
        tracer = tele_spans.get_tracer()
        collect = rt.train_metrics
        n_real = len(labeled_idxs)

        epochs_run = 0
        for epoch in range(start_epoch, n_epoch + 1):
            epochs_run = epoch
            t_epoch0 = time.perf_counter() if collect else 0.0
            step_times: List[float] = []
            if hasattr(train_set, "set_epoch"):
                # Advance disk datasets' per-(seed, epoch, index) crop RNG
                # (data/imagenet.py); fold the round in so AL rounds don't
                # replay the same augmentation sequence.
                train_set.set_epoch(round_idx * (n_epoch + 1) + epoch)
            lr = jnp.float32(self.lr_at(epoch - 1))
            # train_loss stays a DEVICE scalar until the end of the fit:
            # fetching it here would block the host on the epoch's compute
            # before validation could even be dispatched — one avoidable
            # host round-trip per epoch, which on a remote-tunneled
            # backend is a measurable slice of a small-round epoch.  The
            # history is materialized to floats right before returning;
            # mid-fit history entries hold live device arrays, so history
            # must never be added to the fit-state payload as-is.
            host_waits: List[float] = []
            if use_scan:
                idx_mat, mask_mat, valid, steps_real = \
                    self._epoch_index_matrix(len(labeled_idxs), bs, rng)
                if feed_map is not None:
                    # Resident-gather: the SAME shuffled layout the host
                    # path commits, re-expressed as global pool rows —
                    # index math only, never an image byte.
                    idx_mat = feed_map[idx_mat]
                with self.dispatch_lock:
                    state, key, losses, gnorms = self._epoch_scan(
                        state, dr_images, dr_labels, jnp.asarray(idx_mat),
                        jnp.asarray(mask_mat), jnp.asarray(valid), key, lr,
                        class_weights, view=train_set.view,
                        sharded=dr_sharded)
                    self.dispatch_lock.drain(losses)
                epoch_loss = jnp.sum(losses) / steps_real
                epoch_gnorm = jnp.sum(gnorms) / steps_real
                steps_run = steps_real
            elif feed == "resident":
                # Per-batch execution form: the SAME shuffled global
                # layout (batch_index_lists consumes the rng exactly
                # like the scan's _epoch_index_matrix and the host
                # path), each batch one jitted on-device gather + step —
                # the only h2d per step is the [batch] index vector.
                losses, gnorms = [], []
                t_step = time.perf_counter() if collect else 0.0
                for b in batch_index_lists(labeled_idxs, bs,
                                           shuffle=True, rng=rng):
                    ids, mask = padded_batch_layout(b, bs)
                    with self.dispatch_lock:
                        small = mesh_lib.replicate(
                            (ids.astype(np.int32), mask), self.mesh)
                        state, key, loss, gnorm = \
                            self._resident_batch_step(  # al-lint: donated-ok positions 3-4 are the *small (ids, mask) splat; the donated key at 5 is rebound by this statement's own targets
                                state, dr_images, dr_labels, *small, key,
                                lr, class_weights, view=train_set.view,
                                sharded=dr_sharded)
                        self.dispatch_lock.drain(loss)
                    losses.append(loss)
                    gnorms.append(gnorm)
                    if collect:
                        now = time.perf_counter()
                        step_times.append(now - t_step)
                        t_step = now
                        rt.tick(epoch=epoch, step=len(losses))
                epoch_loss = (jnp.mean(jnp.stack(losses))
                              if losses else 0.0)
                epoch_gnorm = (jnp.mean(jnp.stack(gnorms))
                               if gnorms else 0.0)
                steps_run = len(losses)
            else:
                losses, gnorms = [], []
                workers = self._feed_workers()
                # host_prefetch: worker-threaded gather/decode behind the
                # double-buffered device prefetch — the loop below then
                # receives already-sharded device batches and host_wait
                # measures pure feed stall.  host_serial (always under a
                # batch_hook): the classic gather->shard->step loop.
                put = ((lambda b: mesh_lib.shard_batch(b, self.mesh))
                       if feed == "host_prefetch" else None)
                # Host-side s2d only without a batch_hook: VAAL's hook
                # feeds the same sharded batch to its 3-channel VAE.
                feed_iter = iter(train_feed_batches(
                    train_set, labeled_idxs, bs, rng=rng, shuffle=True,
                    num_workers=workers,
                    prefetch=self.cfg.loader_tr.prefetch,
                    local=mesh_lib.process_local_rows(self.mesh, bs),
                    s2d=self._host_s2d and batch_hook is None,
                    put=put, depth=self.cfg.loader_tr.prefetch))
                t_step = time.perf_counter() if collect else 0.0
                while True:
                    t_wait = time.perf_counter() if collect else 0.0
                    item = next(feed_iter, None)
                    if item is None:
                        break
                    if collect:
                        # Time blocked on the feed (gather/decode on the
                        # serial leg, queue wait on the prefetched one):
                        # the numerator of feed_stall_frac.
                        host_waits.append(time.perf_counter() - t_wait)
                    with self.dispatch_lock:
                        sharded = (item if put is not None
                                   else mesh_lib.shard_batch(item,
                                                             self.mesh))
                        state, key, loss, gnorm = self._chained_train_step(
                            state, sharded, key, lr, class_weights,
                            view=train_set.view)
                        self.dispatch_lock.drain(loss)
                    losses.append(loss)
                    gnorms.append(gnorm)
                    if batch_hook is not None:
                        # Receives the already-sharded device batch — no
                        # second host->device transfer on the hot path.
                        batch_hook(epoch, sharded)
                    if collect:
                        # Loop-cadence deltas (gather + dispatch; the
                        # donated-buffer backpressure makes steady-state
                        # cadence track real step time) — host-side, no
                        # sync.
                        now = time.perf_counter()
                        step_times.append(now - t_step)
                        t_step = now
                        rt.tick(epoch=epoch, step=len(losses))
                epoch_loss = (jnp.mean(jnp.stack(losses))
                              if losses else 0.0)
                epoch_gnorm = (jnp.mean(jnp.stack(gnorms))
                               if gnorms else 0.0)
                steps_run = len(losses)
            record = {"epoch": epoch, "lr": float(lr),
                      "train_loss": epoch_loss, "grad_norm": epoch_gnorm}
            if collect:
                t_train_end = time.perf_counter()
                tracer.complete("epoch", t_epoch0, t_train_end,
                                args={"round": round_idx, "epoch": epoch,
                                      "steps": steps_run})

            if use_es:
                perf = self.evaluate(state, al_set, eval_idxs)
                eval_acc = float(perf["accuracy"])
                eval_top5 = float(perf["top_5_accuracy"])
                record.update(val_accuracy=eval_acc, val_top5=eval_top5)
                self.logger.info(
                    f"\tValidation performance on round {round_idx} at "
                    f"epoch {epoch} is {eval_acc * 100:.2f}%")
                # Per-epoch validation curves, like the reference's comet
                # logging (strategy.py:419-422) — the paper's curves need
                # every epoch, not a subsample.
                if metric_cb:
                    metric_cb(f"rd_{round_idx}_validation_accuracy",
                              eval_acc, epoch)
                    metric_cb(f"rd_{round_idx}_validation_top5_accuracy",
                              eval_top5, epoch)
                # >= : later epochs win ties (strategy.py:425-430).
                if eval_acc >= best_perf:
                    best_perf, best_epoch, es_count = eval_acc, epoch, 0
                    # Device-side snapshot (explicit copies: the train
                    # step donates its input buffers, so a bare reference
                    # would be invalidated next epoch).  The reference
                    # writes best_rd_{n}.pth on EVERY improvement
                    # (strategy.py:425-430); a full-variable device->host
                    # fetch + disk write per improving epoch dominates
                    # small-round epochs, so the host fetch is deferred
                    # to the periodic checkpoint cadence below and to the
                    # end of the fit — the on-disk best a resume consumes
                    # stays coherent with the fit state saved alongside.
                    best_variables = jax.tree.map(jnp.copy,
                                                  state.variables)
                    best_dirty = True
                    if on_best is not None:
                        try:
                            on_best(round_idx, epoch, best_variables)
                        except Exception:  # noqa: BLE001 - best-effort bus
                            self.logger.exception(
                                "on_best subscriber failed; continuing fit")
                else:
                    es_count += 1
                # The reference writes the latest ckpt every epoch
                # (strategy.py:440) and never consumes it; a full-variable
                # host transfer per epoch would dominate small-model epochs
                # on TPU, so write it periodically + on exit instead.
                if (weight_paths and mesh_lib.is_coordinator()
                        and epoch % self.current_ckpt_every == 0):
                    if best_dirty:
                        # Rank-0-style write guard (strategy.py:425-430);
                        # on a pod the ckpt_path must be a shared
                        # filesystem so every process can read it back.
                        # publish_best = atomic write + monotonic
                        # (round, best_epoch) tag for the concurrent
                        # readers (serve hot-reload, speculative scorer).
                        _CKPT_RETRY.call(
                            ckpt_lib.publish_best,
                            weight_paths["best_ckpt"],
                            jax.tree.map(np.asarray, best_variables),
                            round_idx=round_idx, epoch=best_epoch)
                        best_dirty = False
                    _CKPT_RETRY.call(
                        ckpt_lib.save_variables,
                        weight_paths["current_ckpt"],
                        jax.tree.map(np.asarray, state.variables))
            if collect:
                # AFTER validation on purpose: on the epoch-scan path the
                # eval-accuracy fetch above is the sync that makes the
                # epoch wall real (see _emit_epoch_telemetry).
                self._emit_epoch_telemetry(
                    metric_cb, round_idx, epoch, n_epoch, n_real,
                    t_train_end - t_epoch0,
                    time.perf_counter() - t_epoch0, use_es,
                    steps_run, step_times)
                self._emit_feed_telemetry(
                    metric_cb, round_idx * (n_epoch + 1) + epoch,
                    host_waits, t_train_end - t_epoch0)
                rt.tick(epoch=epoch, feed=feed)
            history.append(record)
            if use_es and es_count > es_patience:
                # Break BEFORE the periodic fit-state save: a state whose
                # es_count is already past patience must never persist —
                # resuming from it would train past the point where the
                # uninterrupted run stopped.
                self.logger.info("Early stopping criterion reached. ")
                break
            preempted = preempt_lib.requested() is not None
            if (weight_paths and batch_hook is None
                    and mesh_lib.is_coordinator()
                    and (epoch % self.current_ckpt_every == 0 or preempted)
                    and epoch < n_epoch):
                if preempted and best_dirty:
                    # The fit state about to be saved references
                    # best_epoch; without this publish the resumed fit
                    # would find best_ckpt missing and restart best-model
                    # tracking — diverging from the uninterrupted run.
                    _CKPT_RETRY.call(
                        ckpt_lib.publish_best, weight_paths["best_ckpt"],
                        jax.tree.map(np.asarray, best_variables),
                        round_idx=round_idx, epoch=best_epoch)
                    best_dirty = False
                _CKPT_RETRY.call(
                    ckpt_lib.save_fit_state,
                    weight_paths["fit_state"], variables=state.variables,
                    opt_state=state.opt_state, step=state.step, epoch=epoch,
                    round_idx=round_idx, best_perf=best_perf,
                    best_epoch=best_epoch, es_count=es_count, key=key,
                    rng=rng)
            if preempted:
                # Preemption (SIGTERM/SIGINT recorded by the driver's
                # handler): the epoch boundary is the safe point — the
                # fit state just saved (or the round-granular experiment
                # state, when this was the final epoch) resumes
                # bit-identically.  Raised AFTER the early-stop break
                # above, so a state past patience still never persists.
                preempt_lib.check()

        if best_variables is None:
            best_epoch = epochs_run
            best_variables = jax.tree.map(np.asarray, state.variables)
            best_dirty = True
        if best_dirty and weight_paths and mesh_lib.is_coordinator():
            _CKPT_RETRY.call(ckpt_lib.publish_best,
                             weight_paths["best_ckpt"],
                             jax.tree.map(np.asarray, best_variables),
                             round_idx=round_idx, epoch=best_epoch)
        if weight_paths and mesh_lib.is_coordinator():
            _CKPT_RETRY.call(ckpt_lib.save_variables,
                             weight_paths["current_ckpt"],
                             jax.tree.map(np.asarray, state.variables))
            # The round completed: a later restart must re-run it from
            # scratch (the experiment-level resume owns cross-round state).
            ckpt_lib.delete_fit_state(weight_paths["fit_state"])
        if mesh_lib.is_multiprocess(self.mesh):
            # Non-writer processes must not race ahead to read best_ckpt
            # (strategy.load_best_ckpt) before process 0 finishes writing.
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("fit_ckpts_written")
        self.logger.info(
            f"Sanity Check: Best ckpt occurs on epoch {best_epoch}")
        ema_loss = ema_gnorm = None
        for rec in history:
            # Deferred train-loss fetch (see the epoch loop): one bulk
            # materialization here instead of one host sync per epoch.
            # The loss/grad-norm EMAs piggyback on this SAME fetch — the
            # telemetry rider costs no additional device sync.
            rec["train_loss"] = float(rec["train_loss"])
            rec["grad_norm"] = float(rec.get("grad_norm", 0.0))
            if collect and metric_cb is not None:
                a = self.TELEMETRY_EMA_ALPHA
                ema_loss = (rec["train_loss"] if ema_loss is None
                            else a * rec["train_loss"] + (1 - a) * ema_loss)
                ema_gnorm = (rec["grad_norm"] if ema_gnorm is None
                             else a * rec["grad_norm"] + (1 - a) * ema_gnorm)
                tele_step = round_idx * (n_epoch + 1) + rec["epoch"]
                metric_cb("train_loss_ema", round(ema_loss, 6), tele_step)
                metric_cb("grad_norm_ema", round(ema_gnorm, 6), tele_step)
        return FitResult(state=state, best_epoch=best_epoch,
                         best_perf=best_perf, epochs_run=epochs_run,
                         history=history)
