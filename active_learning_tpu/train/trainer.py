"""Per-round training engine.

Replaces the reference's ``Strategy.train`` / ``parallel_train_fn`` /
``_train`` / ``validation_and_early_stopping`` stack
(src/query_strategies/strategy.py:249-442).  Key differences by design:

  * ONE persistent JAX runtime for the whole experiment — no per-round
    ``mp.spawn`` + NCCL process-group setup (strategy.py:288-315).  The
    mesh exists once; each round just re-runs the jitted step.
  * The train step is a single jitted function over a data-sharded batch:
    gradient psum (DDP allreduce, strategy.py:336), global-batch BN stats
    (SyncBatchNorm, strategy.py:292), and the fused normalize/augment all
    come out of XLA's partitioner.
  * BN-freeze semantics preserved: the reference trains with the network in
    eval() mode whenever features are frozen OR a pretrained checkpoint is
    configured (strategy.py:366-367) — here ``train_bn=False`` selects
    running-average BN with no stats update while gradients still flow.
  * Early stopping keeps the best parameters both on disk (best_rd_{n},
    strategy.py:425-430) and in memory.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from ..config import TrainConfig
from ..data.augment import apply_view
from ..data.core import Dataset
from ..data.pipeline import iterate_batches
from ..parallel import mesh as mesh_lib
from ..utils.logging import get_logger
from . import checkpoint as ckpt_lib
from .evaluation import accumulate_metrics, make_eval_step
from .optim import make_lr_schedule, make_optimizer


class TrainState(struct.PyTreeNode):
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jnp.ndarray

    @property
    def variables(self) -> Dict[str, Any]:
        return {"params": self.params, "batch_stats": self.batch_stats}


@dataclasses.dataclass
class FitResult:
    state: TrainState
    best_epoch: int
    best_perf: float
    epochs_run: int
    history: List[Dict[str, float]]


def weighted_cross_entropy(logits, labels, sample_weights):
    """torch ``CrossEntropyLoss(weight=w, reduction='mean')`` semantics:
    sum(w_y * ce) / sum(w_y) (strategy.py:352-356); padding rows carry
    weight 0."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    ce = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32),
                              axis=1)[:, 0]
    denom = jnp.maximum(jnp.sum(sample_weights), 1e-12)
    return jnp.sum(ce * sample_weights) / denom


class Trainer:
    """Owns the jitted train/eval steps for one (model, train-config) pair."""

    def __init__(self, model, train_cfg: TrainConfig, mesh,
                 num_classes: int, train_bn: Optional[bool] = None,
                 current_ckpt_every: int = 25):
        self.model = model
        self.cfg = train_cfg
        self.mesh = mesh
        self.num_classes = num_classes
        self.current_ckpt_every = max(1, int(current_ckpt_every))
        self.logger = get_logger()
        self.tx = make_optimizer(train_cfg.optimizer)
        self.lr_at = make_lr_schedule(train_cfg.scheduler,
                                      train_cfg.optimizer.lr)
        # Reference quirk (strategy.py:366-367): BN runs in eval mode during
        # training whenever features are frozen or a pretrained ckpt is
        # configured.
        if train_bn is None:
            train_bn = not (model.freeze_feature or train_cfg.has_pretrained)
        self.train_bn = train_bn
        self.n_devices = mesh.devices.size
        self._train_step = self._build_train_step()
        self._eval_steps: Dict[Any, Callable] = {}

    # -- setup -----------------------------------------------------------

    def padded_batch_size(self, batch_size: int) -> int:
        """Round up so the batch axis divides evenly over the mesh; padding
        rows are masked out of every reduction."""
        n = self.n_devices
        return -(-batch_size // n) * n

    def init_state(self, rng: jax.Array, sample_input: np.ndarray
                   ) -> TrainState:
        variables = self.model.init(rng, jnp.asarray(sample_input),
                                    train=False)
        variables = mesh_lib.replicate(variables, self.mesh)
        opt_state = self.tx.init(variables["params"])
        opt_state = mesh_lib.replicate(opt_state, self.mesh)
        return TrainState(params=variables["params"],
                          batch_stats=variables.get("batch_stats", {}),
                          opt_state=opt_state, step=jnp.zeros((), jnp.int32))

    def reinit_optimizer(self, state: TrainState) -> TrainState:
        """Fresh optimizer state at the start of each round (the reference
        constructs a new optimizer per round, strategy.py:345)."""
        opt_state = mesh_lib.replicate(self.tx.init(state.params), self.mesh)
        return state.replace(opt_state=opt_state,
                             step=jnp.zeros((), jnp.int32))

    def replace_variables(self, state: TrainState, variables) -> TrainState:
        variables = mesh_lib.replicate(variables, self.mesh)
        return state.replace(params=variables["params"],
                             batch_stats=variables.get("batch_stats", {}))

    # -- jitted steps ----------------------------------------------------

    def _build_train_step(self):
        model = self.model
        tx = self.tx
        train_bn = self.train_bn

        def loss_fn(params, batch_stats, x, labels, weights):
            variables = {"params": params, "batch_stats": batch_stats}
            if train_bn:
                logits, mutated = model.apply(
                    variables, x, train=True, mutable=["batch_stats"])
                new_stats = mutated["batch_stats"]
            else:
                logits = model.apply(variables, x, train=False)
                new_stats = batch_stats
            loss = weighted_cross_entropy(logits, labels, weights)
            return loss, new_stats

        @functools.partial(jax.jit, static_argnames=("view",),
                           donate_argnums=(0,))
        def train_step(state, batch, key, lr, class_weights, view):
            x = apply_view(batch["image"], view, key=key, train=True)
            weights = class_weights[batch["label"]] * batch["mask"]
            (loss, new_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params, state.batch_stats, x,
                                       batch["label"], weights)
            updates, new_opt_state = tx.update(grads, state.opt_state,
                                               state.params)
            updates = jax.tree.map(lambda u: -lr * u, updates)
            params = optax.apply_updates(state.params, updates)
            return state.replace(params=params, batch_stats=new_stats,
                                 opt_state=new_opt_state,
                                 step=state.step + 1), loss

        return train_step

    def _get_eval_step(self, view):
        if view not in self._eval_steps:
            self._eval_steps[view] = make_eval_step(
                self.model, view, self.num_classes)
        return self._eval_steps[view]

    # -- class weights ---------------------------------------------------

    def class_weights(self, labels: np.ndarray) -> np.ndarray:
        """Imbalanced-training class weights (strategy.py:444-457):
        observed classes get total/count, unobserved keep 1, normalized to
        sum 1.  Identity (all ones) when imbalanced_training is off."""
        if not self.cfg.imbalanced_training:
            return np.ones(self.num_classes, dtype=np.float32)
        uniq, counts = np.unique(labels, return_counts=True)
        weights = np.ones(self.num_classes, dtype=np.float64)
        weights[uniq] = counts.sum() / counts
        weights /= weights.sum()
        return weights.astype(np.float32)

    # -- evaluation ------------------------------------------------------

    def evaluate(self, state: TrainState, dataset: Dataset,
                 idxs: np.ndarray) -> Dict[str, np.ndarray]:
        """Top-1/top-5/per-class metrics over ``dataset[idxs]``
        (replaces evaluation.py:11-105)."""
        eval_step = self._get_eval_step(dataset.view)
        bs = self.padded_batch_size(self.cfg.loader_te.batch_size)
        variables = state.variables

        def counts():
            for batch in iterate_batches(
                    dataset, idxs, bs,
                    num_threads=self.cfg.loader_te.num_workers,
                    prefetch=self.cfg.loader_te.prefetch):
                yield eval_step(variables,
                                mesh_lib.shard_batch(batch, self.mesh))

        return accumulate_metrics(counts())

    # -- the fit loop ----------------------------------------------------

    def fit(
        self,
        state: TrainState,
        train_set: Dataset,
        labeled_idxs: np.ndarray,
        al_set: Dataset,
        eval_idxs: np.ndarray,
        n_epoch: int,
        es_patience: int,
        rng: np.random.Generator,
        round_idx: int = 0,
        weight_paths: Optional[Dict[str, str]] = None,
        metric_cb: Optional[Callable[[str, float, int], None]] = None,
        batch_hook: Optional[Callable[[int, Dict[str, np.ndarray]], None]]
        = None,
    ) -> FitResult:
        """Train on the labeled subset with per-epoch validation + early
        stopping (parallel_train_fn, strategy.py:304-381).

        ``es_patience == 0`` disables early stopping (parser.py:66-69); in
        that case the final parameters become the "best" (the reference
        would crash in load_best_ckpt — deliberate fix).

        ``batch_hook(epoch, host_batch)`` runs after each classifier step —
        the seam that lets VAAL co-train its VAE/discriminator inside the
        same epoch loop (the reference overrides the whole
        parallel_train_fn, vaal_sampler.py:77-183)."""
        use_es = es_patience != 0 and len(eval_idxs) > 0
        labels = train_set.targets[labeled_idxs]
        class_weights = jnp.asarray(self.class_weights(labels))
        state = self.reinit_optimizer(state)
        bs = self.padded_batch_size(self.cfg.loader_tr.batch_size)

        best_perf, best_epoch, es_count = 0.0, 0, 0
        best_variables = None
        history: List[Dict[str, float]] = []
        key = jax.random.PRNGKey(int(rng.integers(0, 2 ** 31 - 1)))

        epochs_run = 0
        for epoch in range(1, n_epoch + 1):
            epochs_run = epoch
            if hasattr(train_set, "set_epoch"):
                # Advance disk datasets' per-(seed, epoch, index) crop RNG
                # (data/imagenet.py); fold the round in so AL rounds don't
                # replay the same augmentation sequence.
                train_set.set_epoch(round_idx * (n_epoch + 1) + epoch)
            lr = jnp.float32(self.lr_at(epoch - 1))
            losses = []
            for batch in iterate_batches(
                    train_set, labeled_idxs, bs, shuffle=True, rng=rng,
                    num_threads=self.cfg.loader_tr.num_workers,
                    prefetch=self.cfg.loader_tr.prefetch):
                key, sub = jax.random.split(key)
                sharded = mesh_lib.shard_batch(batch, self.mesh)
                state, loss = self._train_step(
                    state, sharded, sub, lr, class_weights,
                    view=train_set.view)
                losses.append(loss)
                if batch_hook is not None:
                    # Receives the already-sharded device batch — no second
                    # host->device transfer on the hot path.
                    batch_hook(epoch, sharded)
            epoch_loss = float(jnp.mean(jnp.stack(losses))) if losses else 0.0
            record = {"epoch": epoch, "lr": float(lr),
                      "train_loss": epoch_loss}

            if use_es:
                perf = self.evaluate(state, al_set, eval_idxs)
                eval_acc = float(perf["accuracy"])
                eval_top5 = float(perf["top_5_accuracy"])
                record.update(val_accuracy=eval_acc, val_top5=eval_top5)
                self.logger.info(
                    f"\tValidation performance on round {round_idx} at "
                    f"epoch {epoch} is {eval_acc * 100:.2f}%")
                # Per-epoch validation curves, like the reference's comet
                # logging (strategy.py:419-422) — the paper's curves need
                # every epoch, not a subsample.
                if metric_cb:
                    metric_cb(f"rd_{round_idx}_validation_accuracy",
                              eval_acc, epoch)
                    metric_cb(f"rd_{round_idx}_validation_top5_accuracy",
                              eval_top5, epoch)
                # >= : later epochs win ties (strategy.py:425-430).
                if eval_acc >= best_perf:
                    best_perf, best_epoch, es_count = eval_acc, epoch, 0
                    best_variables = jax.tree.map(np.asarray,
                                                  state.variables)
                    if weight_paths:
                        ckpt_lib.save_variables(weight_paths["best_ckpt"],
                                                best_variables)
                else:
                    es_count += 1
                # The reference writes the latest ckpt every epoch
                # (strategy.py:440) and never consumes it; a full-variable
                # host transfer per epoch would dominate small-model epochs
                # on TPU, so write it periodically + on exit instead.
                if weight_paths and epoch % self.current_ckpt_every == 0:
                    ckpt_lib.save_variables(weight_paths["current_ckpt"],
                                            jax.tree.map(np.asarray,
                                                         state.variables))
            history.append(record)
            if use_es and es_count > es_patience:
                self.logger.info("Early stopping criterion reached. ")
                break

        if best_variables is None:
            best_epoch = epochs_run
            best_variables = jax.tree.map(np.asarray, state.variables)
            if weight_paths:
                ckpt_lib.save_variables(weight_paths["best_ckpt"],
                                        best_variables)
        if weight_paths:
            ckpt_lib.save_variables(weight_paths["current_ckpt"],
                                    jax.tree.map(np.asarray,
                                                 state.variables))
        self.logger.info(
            f"Sanity Check: Best ckpt occurs on epoch {best_epoch}")
        return FitResult(state=state, best_epoch=best_epoch,
                         best_perf=best_perf, epochs_run=epochs_run,
                         history=history)
