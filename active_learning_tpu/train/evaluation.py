"""Jitted, mesh-sharded evaluation metrics.

Replaces src/utils/evaluation.py: ``accuracy`` (top-1/top-5/per-class over a
loader, :11-66) and ``gather_parallel_eval`` (NCCL all_gather of counts,
:69-98).  On TPU the per-batch counts are computed in one jitted function
over the sharded batch; the cross-device reduction is a by-product of the
sharding (XLA inserts the collective), so there is no separate gather step.
Final division happens on host once all batches are accumulated — identical
math to the reference's corrects/count bookkeeping.
"""

from __future__ import annotations

import functools
from typing import Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..data.augment import apply_view
from ..data.core import ViewSpec
# The calibration bin count is owned by the host-pure diagnostics layer
# (telemetry/diagnostics.py) so the device counts here and the host ECE
# there can never disagree on the ladder.
from ..telemetry.diagnostics import NUM_CAL_BINS


def batch_metric_counts(logits: jnp.ndarray, labels: jnp.ndarray,
                        mask: jnp.ndarray, num_classes: int,
                        top_k: int = 5) -> Dict[str, jnp.ndarray]:
    """Counts for one batch: top-1/top-k corrects, per-class corrects and
    totals, plus the calibration bins (per-confidence-bin count /
    correct / confidence-sum — additive, so they merge across batches,
    chunks, and shards exactly like the accuracy counts; the host side
    derives ECE in telemetry/diagnostics.ece_from_counts).  Padding rows
    (mask 0) contribute nothing.  The calibration counts piggyback on
    the logits this function already holds — the experiment-truth
    layer's zero-extra-pass rule (DESIGN.md §13)."""
    k = min(top_k, num_classes)
    _, topk_pred = jax.lax.top_k(logits, k)
    hit_topk = (topk_pred == labels[:, None]).any(axis=1)
    top1 = topk_pred[:, 0] == labels
    maskf = mask.astype(jnp.float32)
    onehot = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32) * maskf[:, None]
    conf = jnp.max(jax.nn.softmax(logits.astype(jnp.float32), axis=-1),
                   axis=-1)
    cal_bin = jnp.clip((conf * NUM_CAL_BINS).astype(jnp.int32), 0,
                       NUM_CAL_BINS - 1)
    cal_onehot = jax.nn.one_hot(cal_bin, NUM_CAL_BINS,
                                dtype=jnp.float32) * maskf[:, None]
    return {
        "top_1_correct": jnp.sum(top1 * maskf),
        "top_k_correct": jnp.sum(hit_topk * maskf),
        "corrects_byclass": jnp.sum(onehot * (top1 * maskf)[:, None], axis=0),
        "count_byclass": jnp.sum(onehot, axis=0),
        "count": jnp.sum(maskf),
        "cal_count": jnp.sum(cal_onehot, axis=0),
        "cal_correct": jnp.sum(cal_onehot * (top1 * maskf)[:, None],
                               axis=0),
        "cal_conf_sum": jnp.sum(cal_onehot * conf[:, None], axis=0),
    }


# Registered step-builder (scripts/al_lint.py recompile-hazard): the
# eval step is built once per (model, view) and cached by the trainer.
_STEP_BUILDERS = ("make_eval_step",)


def make_eval_step(model, view: ViewSpec, num_classes: int):
    """Jitted: uint8 batch -> metric counts.  The batch arrives sharded over
    the mesh's data axis; XLA reduces the counts across devices."""

    @jax.jit
    def eval_step(variables, batch):
        x = apply_view(batch["image"], view, train=False)
        logits = model.apply(variables, x, train=False)
        return batch_metric_counts(logits, batch["label"], batch["mask"],
                                   num_classes)

    return eval_step


def accumulate_metrics(count_iter: Iterator[Dict[str, jnp.ndarray]]
                       ) -> Dict[str, np.ndarray]:
    """Sum per-batch counts and derive the reference's metric dict keys
    (evaluation.py:58-66): accuracy, top_5_accuracy, accuracy_byclass,
    corrects_byclass, count_byclass, count."""
    # Accumulate WITHOUT fetching: summing device arrays dispatches a tiny
    # async add per batch, and the single np.asarray at the end is the only
    # host round-trip — a per-batch fetch would serialize the eval pipeline
    # on a remote/tunneled runtime.
    totals = None
    for counts in count_iter:
        if totals is None:
            totals = dict(counts)
        else:
            totals = {k: totals[k] + counts[k] for k in totals}
    if totals is not None:
        totals = {k: np.asarray(v) for k, v in totals.items()}
    if totals is None:
        # Empty eval set (eval_split=0): report zero accuracy instead of
        # crashing mid-fit; callers treat 0 as "no signal".
        return {
            "accuracy": np.float32(0.0), "top_5_accuracy": np.float32(0.0),
            "accuracy_byclass": np.zeros(0, np.float32),
            "corrects_byclass": np.zeros(0, np.float32),
            "count_byclass": np.zeros(0, np.float32),
            "count": np.float32(0.0),
            "cal_count": np.zeros(NUM_CAL_BINS, np.float32),
            "cal_correct": np.zeros(NUM_CAL_BINS, np.float32),
            "cal_conf_sum": np.zeros(NUM_CAL_BINS, np.float32),
        }
    count = max(totals["count"], 1.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        byclass = totals["corrects_byclass"] / totals["count_byclass"]
    return {
        "accuracy": totals["top_1_correct"] / count,
        "top_5_accuracy": totals["top_k_correct"] / count,
        "accuracy_byclass": byclass,
        "corrects_byclass": totals["corrects_byclass"],
        "count_byclass": totals["count_byclass"],
        "count": count,
        # Calibration bins ride the same accumulation (ECE derives on
        # host: telemetry/diagnostics.ece_from_counts).
        "cal_count": totals["cal_count"],
        "cal_correct": totals["cal_correct"],
        "cal_conf_sum": totals["cal_conf_sum"],
    }
