"""Checkpoint I/O.

Replaces the reference's ``torch.save(state_dict)`` per-round best/current
checkpoints (src/query_strategies/strategy.py:425-440) and the whole-object
pickle resume (src/utils/resume_training.py) with explicit artifacts:
  * model variables (params + batch_stats) as msgpack (flax.serialization);
  * experiment state (pool masks, round, rng, config echo) as npz + json —
    see experiment/resume.py.

Checkpoint paths follow the reference's layout
(strategy.py:165-173): ``{ckpt_root}/{exp_name}_{exp_hash}/best_rd_{n}`` and
``rd_{n}``.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from .. import faults

# Bumped whenever saved model weights stop being interchangeable across
# code versions even though their SHAPES still match — e.g. the conv
# padding fix (models/resnet.py: strided 3x3 convs moved from XLA-SAME to
# torch-exact (1, 1) padding), where old weights would load cleanly into
# the new graph and silently score through one-pixel-shifted windows.
# Checked by BOTH resume surfaces: experiment-level (experiment/resume.py,
# hard error) and mid-round fit state (below, discard + warn — the round
# safely restarts from scratch).  Version 1 = states saved before the
# field existed, i.e. the pre-padding-fix alignment.
MODEL_FORMAT_VERSION = 2


def save_variables(path: str, variables: Dict[str, Any]) -> None:
    """Atomic write (tmp + rename): a reader never sees a half-written
    checkpoint — mid-round resume (experiment/resume.py) and non-writer
    pod processes both read these files."""
    faults.site("ckpt_write")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    host_vars = jax.tree.map(np.asarray, variables)
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        fh.write(serialization.msgpack_serialize(host_vars))
    os.replace(tmp, path)


def load_variables(path: str, like: Dict[str, Any] = None) -> Dict[str, Any]:
    with open(path, "rb") as fh:
        restored = serialization.msgpack_restore(fh.read())
    if like is not None:
        restored = serialization.from_state_dict(like, restored)
    return restored


def weight_paths(ckpt_root: str, exp_name: str, exp_hash: str,
                 round_idx: int) -> Dict[str, str]:
    """best/current/previous checkpoint paths for a round
    (strategy.py:165-173; ``previous_ckpt`` kept for parity though the
    reference never consumes it).  ``fit_state`` is this framework's
    addition: the mid-round resume state (the reference writes rd_{n}.pth
    every epoch but never reads it back — strategy.py:440,
    resume_training.py:8-52 resume at round granularity only)."""
    ckpt_dir = os.path.join(ckpt_root, f"{exp_name}_{exp_hash}")
    os.makedirs(ckpt_dir, exist_ok=True)
    return {
        "best_ckpt": os.path.join(ckpt_dir, f"best_rd_{round_idx}.msgpack"),
        "previous_ckpt": os.path.join(ckpt_dir, f"rd_{round_idx - 1}.msgpack"),
        "current_ckpt": os.path.join(ckpt_dir, f"rd_{round_idx}.msgpack"),
        "fit_state": os.path.join(ckpt_dir, f"fit_state_rd_{round_idx}"),
        "dir": ckpt_dir,
    }


def latest_best_ckpt(ckpt_dir: str) -> Tuple[Optional[str], int]:
    """(path, round) of the newest round's ``best_rd_{n}.msgpack`` under
    ``ckpt_dir``, or (None, -1) when none exists.

    The scoring service's hot-reload probe (serve/executor.py): a
    running AL experiment appends best checkpoints round by round, and
    the service polls this between batches to serve the freshest model
    without a restart.  Writes are atomic (save_variables), so whatever
    this returns is always a complete file."""
    best: Tuple[Optional[str], int] = (None, -1)
    try:
        names = os.listdir(ckpt_dir)
    except (FileNotFoundError, NotADirectoryError):
        return best
    for name in names:
        m = _BEST_CKPT_RE.match(name)
        if m and int(m.group(1)) > best[1]:
            best = (os.path.join(ckpt_dir, name), int(m.group(1)))
    return best


_BEST_CKPT_RE = re.compile(r"^best_rd_(\d+)\.msgpack$")


# -- best-ckpt publish/subscribe --------------------------------------------
#
# The best checkpoint has CONCURRENT READERS now: the serve executor's
# hot reload (between batches) and the speculative scorer of the
# pipelined round (experiment/pipeline.py) both load ``best_rd_{n}``
# while the trainer is still writing newer ones.  Atomic tmp+rename
# (save_variables) already guarantees no reader sees a torn FILE; what
# it cannot guarantee is freshness attribution — two publishes inside
# one mtime granule look identical to an mtime-stamped poller, and a
# reader that pairs new weights with a stale version guess would score
# pool chunks it later trusts as current.  So every best-ckpt publish
# also writes a TAG sidecar (``best_rd_{n}.msgpack.tag.json``, atomic)
# carrying the monotonic (round, epoch) the weights were best at:
# within one round the best epoch only ever increases, so the tag is a
# strictly monotonic version — never reused, never clock-dependent.
# Write order is weights THEN tag; BestCkptWatcher re-reads the tag
# after loading and treats any disagreement as not-ready (retry next
# poll), so a poll result's (variables, tag) pairing is always either
# exact or attributed to an OLDER tag than the weights — which the
# pipeline's invalidation rule (anything not the final best is
# recomputed) turns into wasted work, never a wrong score.

def publish_best(path: str, variables: Dict[str, Any], *, round_idx: int,
                 epoch: int) -> None:
    """Atomically publish a best checkpoint plus its monotonic
    (round, epoch) tag — the writer side of the best-ckpt bus."""
    save_variables(path, variables)
    # Torn point between the pair's two renames: a crash here leaves
    # weights WITHOUT their tag — exactly the partial publish the
    # watcher's legacy/tag-mismatch rules must absorb (chaos-tested via
    # ckpt_write:torn@N).
    faults.site("ckpt_write", point="torn")
    tag = {"round": int(round_idx), "epoch": int(epoch)}
    tmp = f"{path}.tag.json.tmp"
    with open(tmp, "w") as fh:
        json.dump(tag, fh)
    os.replace(tmp, f"{path}.tag.json")


def read_best_tag(path: str) -> Optional[Tuple[int, int]]:
    """The (round, epoch) tag published alongside ``path``; None when the
    sidecar is absent (a pre-tag writer) or unreadable."""
    try:
        with open(f"{path}.tag.json") as fh:
            tag = json.load(fh)
        return (int(tag["round"]), int(tag["epoch"]))
    except (OSError, ValueError, KeyError, TypeError):
        return None


class BestCkptWatcher:
    """Shared hot-reload probe over an experiment's checkpoint directory
    — ONE spelling of "give me the newest fully-published best ckpt"
    for every concurrent reader (the serve executor between batches,
    the speculative scorer of the pipelined round).

    ``poll()`` returns ``(variables, round, tag)`` when a best ckpt
    NEWER than the last successful poll is completely published, else
    None.  Newness is judged by the monotonic (round, epoch) tag when
    one exists and falls back to (round, mtime) for pre-tag writers; the
    tag is re-read after the weight load and any disagreement reads as
    not-ready (the writer raced between the two renames — the next poll
    sees the settled pair).  A torn or half-written file is impossible
    by construction (every rename is atomic)."""

    def __init__(self, ckpt_dir: str):
        self.ckpt_dir = ckpt_dir
        self._stamp: Optional[Tuple] = None

    @staticmethod
    def _stamp_of(rd: int, tag, mtime: float) -> Tuple:
        # The tag orders publishes exactly; mtime rides along only for
        # tag-less (legacy) writers, where it is the best available.  A
        # tagged publish always supersedes an untagged one at the same
        # round (the tagged writer is the newer code), and the tuple
        # shape keeps every stamp comparable.
        return ((rd, 0, (-1, -1), mtime) if tag is None
                else (rd, 1, tag, 0.0))

    def prime(self) -> None:
        """Mark the CURRENT newest publish as already-seen WITHOUT
        loading it.  A subscriber that only cares about future
        publishes (the speculative scorer arming at round start, when
        the newest file on disk is the previous round's best) would
        otherwise deserialize a full checkpoint on its first poll just
        to discard it by round."""
        path, rd = latest_best_ckpt(self.ckpt_dir)
        if path is None:
            return
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return
        stamp = self._stamp_of(rd, read_best_tag(path), mtime)
        if self._stamp is None or stamp > self._stamp:
            self._stamp = stamp

    def poll(self):
        path, rd = latest_best_ckpt(self.ckpt_dir)
        if path is None:
            return None
        tag = read_best_tag(path)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return None
        stamp = self._stamp_of(rd, tag, mtime)
        if self._stamp is not None and stamp <= self._stamp:
            return None
        try:
            variables = load_variables(path)
        except (OSError, ValueError):
            # The file rotated away mid-read (a newer round replaced
            # it); the next poll sees the settled state.
            return None
        if read_best_tag(path) != tag:
            # Writer raced between the weight rename and the tag rename:
            # the pairing cannot be proven, so report nothing and let the
            # next poll observe the completed publish.
            return None
        self._stamp = stamp
        return variables, rd, tag


# -- mid-round fit state ----------------------------------------------------
#
# Everything needed to continue an interrupted Trainer.fit from the last
# completed epoch, bit-for-bit: model variables, optimizer state, the
# early-stopping bookkeeping, the jax PRNG-key chain, and the numpy
# Generator state that drives batch shuffling.  Two files per round:
# {path}.msgpack (the big trees) + {path}.json (counters + rng state).
# Each file is written atomically, and both carry the same (round, epoch)
# stamp, cross-checked at load: a crash part-way through the pair — before
# the json exists, or between the two os.replace calls when OVERWRITING an
# earlier save — can never pair one epoch's weights with another epoch's
# counters; the torn state reads as nothing-to-resume instead.

def save_fit_state(path: str, *, variables: Dict[str, Any], opt_state: Any,
                   step: Any, epoch: int, round_idx: int, best_perf: float,
                   best_epoch: int, es_count: int, key: Any,
                   rng: np.random.Generator) -> None:
    faults.site("ckpt_write")
    trees = {
        "variables": serialization.to_state_dict(
            jax.tree.map(np.asarray, variables)),
        "opt_state": serialization.to_state_dict(
            jax.tree.map(np.asarray, opt_state)),
        "stamp": np.asarray([int(round_idx), int(epoch)]),
    }
    with open(path + ".msgpack.tmp", "wb") as fh:
        fh.write(serialization.msgpack_serialize(trees))
    os.replace(path + ".msgpack.tmp", path + ".msgpack")
    # Torn point between the pair's renames: trees without counters — the
    # stamp cross-check in load_fit_state reads it as nothing-to-resume.
    faults.site("ckpt_write", point="torn")
    meta = {
        "epoch": int(epoch),
        "round_idx": int(round_idx),
        "model_format": MODEL_FORMAT_VERSION,
        "step": int(np.asarray(step)),
        "best_perf": float(best_perf),
        "best_epoch": int(best_epoch),
        "es_count": int(es_count),
        "key": np.asarray(key).tolist(),
        "rng_state": rng.bit_generator.state,
    }
    with open(path + ".json.tmp", "w") as fh:
        json.dump(meta, fh)
    os.replace(path + ".json.tmp", path + ".json")


def load_fit_state(path: str, round_idx: int) -> Optional[Dict[str, Any]]:
    """Return the saved mid-round state, or None when there is nothing to
    resume (no file, or a state belonging to a different round)."""
    if not (os.path.exists(path + ".msgpack")
            and os.path.exists(path + ".json")):
        return None
    with open(path + ".json") as fh:
        meta = json.load(fh)
    if meta.get("round_idx") != int(round_idx):
        return None
    if int(meta.get("model_format", 1)) != MODEL_FORMAT_VERSION:
        from ..utils.logging import get_logger
        get_logger().warning(
            f"Discarding mid-round fit state with model format "
            f"{meta.get('model_format', 1)} (this code writes "
            f"{MODEL_FORMAT_VERSION}); the round restarts from scratch")
        return None
    with open(path + ".msgpack", "rb") as fh:
        trees = serialization.msgpack_restore(fh.read())
    stamp = np.asarray(trees.pop("stamp", [-1, -1])).tolist()
    if stamp != [meta["round_idx"], meta["epoch"]]:
        # Torn or corrupt pair (a missing stamp included): the weight
        # trees and the counters cannot be proven to be from the same
        # epoch, so there is nothing safe to resume.
        return None
    return {**meta, **trees}


def delete_fit_state(path: str) -> None:
    for suffix in (".msgpack", ".json"):
        try:
            os.remove(path + suffix)
        except FileNotFoundError:
            pass
