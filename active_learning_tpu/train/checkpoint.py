"""Checkpoint I/O.

Replaces the reference's ``torch.save(state_dict)`` per-round best/current
checkpoints (src/query_strategies/strategy.py:425-440) and the whole-object
pickle resume (src/utils/resume_training.py) with explicit artifacts:
  * model variables (params + batch_stats) as msgpack (flax.serialization);
  * experiment state (pool masks, round, rng, config echo) as npz + json —
    see experiment/resume.py.

Checkpoint paths follow the reference's layout
(strategy.py:165-173): ``{ckpt_root}/{exp_name}_{exp_hash}/best_rd_{n}`` and
``rd_{n}``.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import numpy as np
from flax import serialization


def save_variables(path: str, variables: Dict[str, Any]) -> None:
    """Atomic write (tmp + rename): a reader never sees a half-written
    checkpoint — mid-round resume (experiment/resume.py) and non-writer
    pod processes both read these files."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    host_vars = jax.tree.map(np.asarray, variables)
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        fh.write(serialization.msgpack_serialize(host_vars))
    os.replace(tmp, path)


def load_variables(path: str, like: Dict[str, Any] = None) -> Dict[str, Any]:
    with open(path, "rb") as fh:
        restored = serialization.msgpack_restore(fh.read())
    if like is not None:
        restored = serialization.from_state_dict(like, restored)
    return restored


def weight_paths(ckpt_root: str, exp_name: str, exp_hash: str,
                 round_idx: int) -> Dict[str, str]:
    """best/current/previous checkpoint paths for a round
    (strategy.py:165-173; ``previous_ckpt`` kept for parity though the
    reference never consumes it)."""
    ckpt_dir = os.path.join(ckpt_root, f"{exp_name}_{exp_hash}")
    os.makedirs(ckpt_dir, exist_ok=True)
    return {
        "best_ckpt": os.path.join(ckpt_dir, f"best_rd_{round_idx}.msgpack"),
        "previous_ckpt": os.path.join(ckpt_dir, f"rd_{round_idx - 1}.msgpack"),
        "current_ckpt": os.path.join(ckpt_dir, f"rd_{round_idx}.msgpack"),
        "dir": ckpt_dir,
    }
