"""``python -m active_learning_tpu`` — the reference's ``python main_al.py``
(README.md:53).  One extra verb beyond the reference surface:
``python -m active_learning_tpu serve ...`` starts the online scoring
service over an experiment's best checkpoint (serve/cli.py)."""

from .experiment.cli import main

if __name__ == "__main__":
    main()
