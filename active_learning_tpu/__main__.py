"""``python -m active_learning_tpu`` — the reference's ``python main_al.py``
(README.md:53).  Extra verbs beyond the reference surface: ``serve``
(the online scoring service, serve/cli.py), ``stream`` (continual
ingest -> score -> select on one persistent mesh, stream/cli.py),
``status`` (live run summary), ``report`` (label-efficiency
curves), and ``fleet`` (many experiments on preemptible capacity —
the sweep controller, fleet/cli.py)."""

from .experiment.cli import main

if __name__ == "__main__":
    main()
