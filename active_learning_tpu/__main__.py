"""``python -m active_learning_tpu`` — the reference's ``python main_al.py``
(README.md:53)."""

from .experiment.cli import main

if __name__ == "__main__":
    main()
