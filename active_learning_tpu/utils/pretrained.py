"""Pretrained (SSL / transfer) checkpoint ingestion: torch state_dict ->
Flax variables.

Reference: src/utils/load_pretrained_weights.py:5-66 — state-dict surgery
(``module.`` prefix stripping, substring ``skip_key``/``required_key``
filtering, ``replace_key`` renaming) followed by a PARTIAL update of the
network's dict (``init_dict.update(net_dict)``), so layers absent from the
checkpoint (the fresh linear head) keep their random init.  The MoCo-v2
mapping (``encoder_q`` -> ``encoder``, skip ``fc``) comes from
src/arg_pools/ssp_finetuning.py:34-37.

The TPU-side extra work is the layout conversion from torchvision ResNet
naming/shapes to this repo's Flax model (models/resnet.py):

  torch key                         flax path
  ------------------------------------------------------------------
  encoder.conv1.weight              params/encoder/conv_stem/kernel (OIHW->HWIO)
  encoder.bn1.{weight,bias}         params/encoder/bn_stem/{scale,bias}
  encoder.bn1.running_{mean,var}    batch_stats/encoder/bn_stem/{mean,var}
  encoder.layerL.B.convN.weight     params/encoder/stageL_blockB/Conv_{N-1}/kernel
  encoder.layerL.B.bnN.*            params/encoder/stageL_blockB/BatchNorm_{N-1}/*
  encoder.layerL.B.downsample.0/1   .../downsample_conv / downsample_bn
  linear.weight                     params/linear/kernel ([C,D] -> [D,C])

``num_batches_tracked`` has no Flax counterpart and is dropped.  Unmappable
leftover keys are an error — silently ignoring them is how a wrong
checkpoint goes unnoticed.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Iterable, Mapping, Optional, Tuple

import numpy as np

from ..config import PretrainedConfig
from .logging import get_logger

FlaxPath = Tuple[str, ...]  # (collection, module..., leaf)


def load_torch_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Read a torch checkpoint into {key: np.ndarray} (CPU, no grads).
    Handles the common ``{"state_dict": ...}`` wrapper (MoCo et al.),
    matching load_pretrained_weights.py:24-26."""
    import torch
    ckpt = torch.load(path, map_location="cpu", weights_only=False)
    if isinstance(ckpt, dict) and "state_dict" in ckpt:
        ckpt = ckpt["state_dict"]
    return {k: np.asarray(v.detach().numpy() if hasattr(v, "detach") else v)
            for k, v in ckpt.items()}


def surgery(
    state: Mapping[str, np.ndarray],
    required_key: Optional[Iterable[str]] = None,
    skip_key: Optional[Iterable[str]] = None,
    replace_map: Optional[Mapping[str, str]] = None,
) -> Dict[str, np.ndarray]:
    """The reference's key filtering/renaming, verbatim semantics
    (load_pretrained_weights.py:27-61): drop keys containing any
    ``skip_key`` substring; drop keys containing NO ``required_key``
    substring; strip a ``module.`` DataParallel prefix; then apply the
    first matching ``replace_map`` substring rename."""
    replace_map = dict(replace_map or {})
    required = tuple(required_key or ())
    skip = tuple(skip_key or ())

    def keep(k: str) -> bool:
        if any(s in k for s in skip):
            return False
        if required and not any(s in k for s in required):
            return False
        return True

    def rename(k: str) -> str:
        for old, new in replace_map.items():
            if old in k:
                return k.replace(old, new)
        return k

    out: Dict[str, np.ndarray] = {}
    for k, v in state.items():
        if not keep(k):
            continue
        if k.startswith("module."):
            k = k[len("module."):]
        out[rename(k)] = v
    return out


_BN_LEAF = {"weight": ("params", "scale"), "bias": ("params", "bias"),
            "running_mean": ("batch_stats", "mean"),
            "running_var": ("batch_stats", "var")}


def torch_key_to_flax(key: str) -> Optional[Tuple[FlaxPath, Optional[str]]]:
    """Map one torchvision-ResNet-style key to (flax path, transform).

    transform: None | "conv" (OIHW->HWIO) | "dense" (transpose).
    Returns None for keys with no Flax counterpart
    (``num_batches_tracked``).  Raises KeyError for unrecognized keys.
    """
    if key.endswith("num_batches_tracked"):
        return None
    parts = key.split(".")
    if parts[0] == "encoder":
        rest = parts[1:]
        # Stem: conv1 / bn1 at the top level of the torchvision encoder.
        if rest[0] == "conv1" and rest[1] == "weight":
            return (("params", "encoder", "conv_stem", "kernel"), "conv")
        if rest[0] == "bn1":
            coll, leaf = _BN_LEAF[rest[1]]
            return ((coll, "encoder", "bn_stem", leaf), None)
        m = re.fullmatch(r"layer(\d+)", rest[0])
        if m:
            stage = int(m.group(1))
            block = int(rest[1])
            mod = f"stage{stage}_block{block}"
            sub = rest[2]
            leaf = rest[3]
            cm = re.fullmatch(r"conv(\d+)", sub)
            if cm and leaf == "weight":
                return (("params", "encoder", mod,
                         f"Conv_{int(cm.group(1)) - 1}", "kernel"), "conv")
            bm = re.fullmatch(r"bn(\d+)", sub)
            if bm:
                coll, l = _BN_LEAF[leaf]
                return ((coll, "encoder", mod,
                         f"BatchNorm_{int(bm.group(1)) - 1}", l), None)
            if sub == "downsample":
                which = rest[3]
                leaf = rest[4]
                if which == "0" and leaf == "weight":
                    return (("params", "encoder", mod, "downsample_conv",
                             "kernel"), "conv")
                if which == "1":
                    coll, l = _BN_LEAF[leaf]
                    return ((coll, "encoder", mod, "downsample_bn", l), None)
        if rest[0] == "fc":
            # The encoder's original fc: replaced by Identity in the
            # reference (resnet_simclr.py:21); nothing to load into.
            return None
    if parts[0] == "linear":
        if parts[1] == "weight":
            return (("params", "linear", "kernel"), "dense")
        if parts[1] == "bias":
            return (("params", "linear", "bias"), None)
    raise KeyError(f"No Flax mapping for torch key '{key}'")


def _transform(value: np.ndarray, kind: Optional[str]) -> np.ndarray:
    if kind == "conv":
        return np.transpose(value, (2, 3, 1, 0))  # OIHW -> HWIO
    if kind == "dense":
        return np.transpose(value, (1, 0))  # [C, D] -> [D, C]
    return value


def overlay_torch_state(variables: Dict[str, Any],
                        torch_state: Mapping[str, np.ndarray],
                        strict: bool = True) -> Dict[str, Any]:
    """Partial update: write every mappable checkpoint tensor into a copy of
    ``variables`` (the reference's ``init_dict.update(net_dict)``,
    load_pretrained_weights.py:64-65).  Shape mismatches always raise;
    unknown keys raise when ``strict``."""
    from flax.traverse_util import flatten_dict, unflatten_dict
    flat = flatten_dict(variables)
    loaded = 0
    for key, value in torch_state.items():
        try:
            mapped = torch_key_to_flax(key)
        except KeyError:
            if strict:
                raise
            continue
        if mapped is None:
            continue
        path, kind = mapped
        arr = _transform(np.asarray(value), kind)
        if path not in flat:
            raise KeyError(
                f"Checkpoint key '{key}' maps to {'/'.join(path)}, absent "
                f"from the model (wrong depth/variant?)")
        if (path[-2:] == ("conv_stem", "kernel") and arr.shape[:2] == (7, 7)
                and tuple(flat[path].shape)[:2] == (4, 4)):
            # s2d-stem model consuming a standard 7x7-stem checkpoint:
            # fold the kernel exactly (models/resnet.s2d_stem_kernel) —
            # the loaded network computes the identical convolution.
            from ..models.resnet import s2d_stem_kernel
            arr = np.asarray(s2d_stem_kernel(arr))
        if tuple(flat[path].shape) != tuple(arr.shape):
            raise ValueError(
                f"Shape mismatch for '{key}' -> {'/'.join(path)}: "
                f"ckpt {arr.shape} vs model {tuple(flat[path].shape)}")
        flat[path] = arr.astype(np.asarray(flat[path]).dtype)
        loaded += 1
    get_logger().info(f"Overlaid {loaded} pretrained tensors")
    return unflatten_dict(flat)


def apply_pretrained(variables: Dict[str, Any],
                     cfg: PretrainedConfig) -> Dict[str, Any]:
    """Full pipeline: load -> surgery -> overlay.  Called from
    Strategy.init_network_weights after the random re-init
    (strategy.py:185-196)."""
    state = load_torch_state_dict(cfg.path)
    state = surgery(state, required_key=cfg.required_key,
                    skip_key=cfg.skip_key, replace_map=cfg.replace_map)
    return overlay_torch_state(variables, state)


