"""Logger setup: "ActiveLearning" logger to file + console with
millisecond timestamps (reference: src/utils/setup_logging.py)."""

from __future__ import annotations

import datetime as dt
import logging
import os

LOGGER_NAME = "ActiveLearning"


class MillisecondFormatter(logging.Formatter):
    """Render timestamps through ``datetime`` so ``%f`` (sub-second
    precision) works in ``datefmt``; without a ``datefmt``, fall back to
    ISO date-time at millisecond resolution."""

    def formatTime(self, record, datefmt=None):
        created = dt.datetime.fromtimestamp(record.created)
        if datefmt is None:
            return created.isoformat(sep=" ", timespec="milliseconds")
        return created.strftime(datefmt)


def get_logger() -> logging.Logger:
    return logging.getLogger(LOGGER_NAME)


def setup_logging(directory: str, filename: str) -> logging.Logger:
    os.makedirs(directory, exist_ok=True)
    logger = logging.getLogger(LOGGER_NAME)
    logger.setLevel(logging.INFO)
    # Idempotent: clear handlers so repeated setup (tests, resume) doesn't
    # duplicate output lines.
    for h in list(logger.handlers):
        logger.removeHandler(h)
    formatter = MillisecondFormatter(
        fmt="%(asctime)s %(message)s", datefmt="%Y-%m-%d,%H:%M:%S.%f")
    # Append when the log already exists (an experiment RESUME reuses its
    # exp_hash-derived filename — truncating here erased every prior
    # round's log lines); truncate only a genuinely fresh file.  The "w"
    # spelling keeps fresh-run behavior byte-identical.
    path = os.path.join(directory, filename)
    file_handler = logging.FileHandler(
        filename=path, mode="a" if os.path.exists(path) else "w")
    file_handler.setFormatter(formatter)
    logger.addHandler(file_handler)
    console_handler = logging.StreamHandler()
    logger.addHandler(console_handler)
    return logger
