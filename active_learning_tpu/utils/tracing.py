"""Tracing and profiling — a thin shim over telemetry/spans.

The reference's only tracing is ad-hoc ``time()`` deltas printed per phase
(src/main_al.py:160-178) and per-batch loss prints (strategy.py:274-279).
Here the same per-phase timers are HOST SPANS (telemetry/spans.py): one
measurement feeds the ``rd_{name}`` metric, the log line, the Chrome
trace event, and the heartbeat tick, so the trace can never silently
fork from the metrics (scripts/trace_lint.py asserts this routing).
Each phase additionally wraps a device trace annotation so XLA profiler
captures (telemetry/profiler.py — the device-truth layer, which owns
EVERY jax.profiler touch per trace_lint check 10) show query/train/test
spans on the device timeline too.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

from ..telemetry import profiler as _tele_profiler
from ..telemetry import runtime as _tele_runtime
from ..telemetry import spans as _tele_spans
from .logging import get_logger


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name the enclosed host span in device profiler traces; free when no
    trace is active.  Delegates to the device-truth layer's gated
    annotation (telemetry/profiler.trace_annotation) — one module owns
    jax.profiler."""
    with _tele_profiler.trace_annotation(name):
        yield


@contextlib.contextmanager
def phase_timer(name: str, round_idx: int, sink=None,
                logger=None) -> Iterator[None]:
    """Wall-clock a phase, log it, and emit ``rd_{name}`` to the metrics
    sink — the reference's per-phase prints (main_al.py:160-178) with the
    profiler annotation added.  The timing IS the host span's: metric,
    log, trace event, and heartbeat all read one measurement.  Yields
    the span so callers can read the same ``duration_s`` afterwards (the
    driver's overlap_frac accounting sums phase walls from it — still
    one measurement, never a second clock)."""
    logger = logger or get_logger()
    _tele_runtime.get_run().tick(force=True, phase=name, round=round_idx)
    with _tele_spans.get_tracer().span(
            name, args={"round": round_idx}) as sp:
        with annotate(f"{name}/rd{round_idx}"):
            yield sp
    seconds = sp.duration_s
    logger.info(f"Rd {round_idx} {name} is {seconds:.3f}s")
    if sink is not None:
        sink.log_metric(f"rd_{name}", seconds, step=round_idx)


