"""Tracing and profiling — a thin shim over telemetry/spans.

The reference's only tracing is ad-hoc ``time()`` deltas printed per phase
(src/main_al.py:160-178) and per-batch loss prints (strategy.py:274-279).
Here the same per-phase timers are HOST SPANS (telemetry/spans.py): one
measurement feeds the ``rd_{name}`` metric, the log line, the Chrome
trace event, and the heartbeat tick, so the trace can never silently
fork from the metrics (scripts/trace_lint.py asserts this routing).
Each phase additionally wraps a ``jax.profiler.TraceAnnotation`` so
device traces show query/train/test spans, and an opt-in
``profile_dir`` captures a full XLA profiler trace (TensorBoard/XProf)
for the whole run.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

from ..telemetry import runtime as _tele_runtime
from ..telemetry import spans as _tele_spans
from .logging import get_logger


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name the enclosed host span in device profiler traces; free when no
    trace is active."""
    import jax.profiler
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def phase_timer(name: str, round_idx: int, sink=None,
                logger=None) -> Iterator[None]:
    """Wall-clock a phase, log it, and emit ``rd_{name}`` to the metrics
    sink — the reference's per-phase prints (main_al.py:160-178) with the
    profiler annotation added.  The timing IS the host span's: metric,
    log, trace event, and heartbeat all read one measurement.  Yields
    the span so callers can read the same ``duration_s`` afterwards (the
    driver's overlap_frac accounting sums phase walls from it — still
    one measurement, never a second clock)."""
    logger = logger or get_logger()
    _tele_runtime.get_run().tick(force=True, phase=name, round=round_idx)
    with _tele_spans.get_tracer().span(
            name, args={"round": round_idx}) as sp:
        with annotate(f"{name}/rd{round_idx}"):
            yield sp
    seconds = sp.duration_s
    logger.info(f"Rd {round_idx} {name} is {seconds:.3f}s")
    if sink is not None:
        sink.log_metric(f"rd_{name}", seconds, step=round_idx)


@contextlib.contextmanager
def profiler_session(profile_dir: Optional[str]) -> Iterator[None]:
    """Capture an XLA profiler trace under ``profile_dir`` (None = no-op).
    View with TensorBoard's profile plugin / XProf."""
    if not profile_dir:
        yield
        return
    import jax.profiler
    get_logger().info(f"Capturing profiler trace to {profile_dir}")
    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        get_logger().info(f"Profiler trace written to {profile_dir}")
