"""Tracing and profiling.

The reference's only tracing is ad-hoc ``time()`` deltas printed per phase
(src/main_al.py:160-178) and per-batch loss prints (strategy.py:274-279).
Here (SURVEY.md §5): the same per-phase wall-clock timers feed the metrics
sink (experiment/driver.py), each phase is additionally wrapped in a
``jax.profiler.TraceAnnotation`` so device traces show query/train/test
spans, and an opt-in ``profile_dir`` captures a full XLA profiler trace
(viewable in TensorBoard/XProf) for the whole run.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

from .logging import get_logger


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name the enclosed host span in device profiler traces; free when no
    trace is active."""
    import jax.profiler
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def phase_timer(name: str, round_idx: int, sink=None,
                logger=None) -> Iterator[None]:
    """Wall-clock a phase, log it, and emit ``rd_{name}`` to the metrics
    sink — the reference's per-phase prints (main_al.py:160-178) with the
    profiler annotation added."""
    logger = logger or get_logger()
    start = time.time()
    with annotate(f"{name}/rd{round_idx}"):
        yield
    seconds = time.time() - start
    logger.info(f"Rd {round_idx} {name} is {seconds:.3f}s")
    if sink is not None:
        sink.log_metric(f"rd_{name}", seconds, step=round_idx)


@contextlib.contextmanager
def profiler_session(profile_dir: Optional[str]) -> Iterator[None]:
    """Capture an XLA profiler trace under ``profile_dir`` (None = no-op).
    View with TensorBoard's profile plugin / XProf."""
    if not profile_dir:
        yield
        return
    import jax.profiler
    get_logger().info(f"Capturing profiler trace to {profile_dir}")
    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        get_logger().info(f"Profiler trace written to {profile_dir}")
