"""Pluggable experiment-metrics sink.

Replaces the reference's Comet ML integration (src/main_al.py:101-114 and the
``comet_experiment.log_metrics``/``log_asset_data`` calls threaded through
``Strategy``) with a local JSONL sink that records the same metric schema:
``cumulative_budget``, ``rd_test_accuracy``, ``budget_test_accuracy``,
``rd_{n}_validation_accuracy``, per-class accuracy assets, and queried-index
assets (metric names documented at src/main_al.py:24-40).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, Iterable, Optional


class MetricsSink:
    """Abstract sink.  ``step`` mirrors comet's step argument (round, epoch,
    or cumulative budget depending on the metric)."""

    def log_parameters(self, params: Dict[str, Any]) -> None:
        raise NotImplementedError

    def log_metrics(self, metrics: Dict[str, float], step: Optional[float] = None) -> None:
        raise NotImplementedError

    def log_metric(self, name: str, value: float, step: Optional[float] = None) -> None:
        self.log_metrics({name: value}, step=step)

    def log_asset(self, name: str, data: str) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class NullSink(MetricsSink):
    """Disabled metrics (reference: ``--enable_comet`` off =>
    ``disabled=True`` experiment, main_al.py:102)."""

    def log_parameters(self, params):  # noqa: D102
        pass

    def log_metrics(self, metrics, step=None):  # noqa: D102
        pass

    def log_asset(self, name, data):  # noqa: D102
        pass


class JsonlSink(MetricsSink):
    """Append-only JSONL event stream under ``directory``.

    Events: {"kind": "params"|"metric"|"asset", "ts": ..., ...}.  Assets are
    written both inline and as separate files under ``assets/`` so the
    queried-index audit trail survives like the reference's
    ``labeled_idxs_per_round.txt`` (strategy.py:480-483).
    """

    def __init__(self, directory: str, experiment_key: Optional[str] = None):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        os.makedirs(os.path.join(directory, "assets"), exist_ok=True)
        self.experiment_key = experiment_key or uuid.uuid4().hex[:9]
        self._path = os.path.join(directory, "metrics.jsonl")
        self._fh = open(self._path, "a")

    def _emit(self, event: Dict[str, Any]) -> None:
        event["ts"] = time.time()
        self._fh.write(json.dumps(event, default=_json_default) + "\n")
        self._fh.flush()

    def log_parameters(self, params):
        self._emit({"kind": "params", "params": params})

    def log_metrics(self, metrics, step=None):
        self._emit({"kind": "metric", "step": step,
                    "metrics": {k: _to_float(v) for k, v in metrics.items()}})

    def log_asset(self, name, data):
        path = os.path.join(self.directory, "assets", f"{name}.txt")
        with open(path, "w") as fh:
            fh.write(data)
        self._emit({"kind": "asset", "name": name, "path": path})

    def close(self):
        self._fh.close()


def _to_float(v: Any) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return float("nan")


def _json_default(o: Any):
    try:
        import numpy as np
        if isinstance(o, np.ndarray):
            return o.tolist()
        if isinstance(o, np.generic):
            return o.item()
    except ImportError:
        pass
    return str(o)


def make_sink(enable: bool, directory: str,
              experiment_key: Optional[str] = None) -> MetricsSink:
    if not enable:
        return NullSink()
    return JsonlSink(directory, experiment_key=experiment_key)
