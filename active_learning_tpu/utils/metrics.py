"""Pluggable experiment-metrics sinks.

Replaces the reference's Comet ML integration (src/main_al.py:101-114 and the
``comet_experiment.log_metrics``/``log_asset_data`` calls threaded through
``Strategy``) with local sinks that record the same metric schema:
``cumulative_budget``, ``rd_test_accuracy``, ``budget_test_accuracy``,
``rd_{n}_validation_accuracy``, per-class accuracy assets, and queried-index
assets (metric names documented at src/main_al.py:24-40).

Backends (``--metrics_backend`` / ``ExperimentConfig.metrics_backend``):
  * ``jsonl`` (default) — append-only event stream, trivially greppable.
  * ``csv`` — one flat metrics.csv + assets/ directory; zero deps.
  * ``tensorboard`` — event files via torch's SummaryWriter (the import is
    lazy: it drags in TensorFlow and costs ~80 s, so only selecting the
    backend pays it); per-round validation curves land as scalar series.
Multiple backends compose with ``MultiSink`` (comma-separated on the CLI:
``--metrics_backend jsonl,tensorboard``).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any, Dict, Iterable, Optional

# Lock discipline, statically enforced (scripts/al_lint.py
# lock-discipline): the telemetry watchdog emits through the SAME sink
# objects as the main loop, from its own thread — every touch of a
# sink's writer handle (and the TensorBoard per-name auto-step map)
# happens under that sink's _lock.  _next_step is the declared
# under-the-lock helper (log_metrics holds the lock around it).
_GUARDED_BY = {"_fh": "_lock", "_writer": "_lock",
               "_auto_steps": "_lock"}
_LOCKED_HELPERS = ("_next_step",)


class MetricsSink:
    """Abstract sink.  ``step`` mirrors comet's step argument (round, epoch,
    or cumulative budget depending on the metric)."""

    def log_parameters(self, params: Dict[str, Any]) -> None:
        raise NotImplementedError

    def log_metrics(self, metrics: Dict[str, float], step: Optional[float] = None) -> None:
        raise NotImplementedError

    def log_metric(self, name: str, value: float, step: Optional[float] = None) -> None:
        self.log_metrics({name: value}, step=step)

    def log_asset(self, name: str, data: str) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class NullSink(MetricsSink):
    """Disabled metrics (reference: ``--enable_comet`` off =>
    ``disabled=True`` experiment, main_al.py:102)."""

    def log_parameters(self, params):  # noqa: D102
        pass

    def log_metrics(self, metrics, step=None):  # noqa: D102
        pass

    def log_asset(self, name, data):  # noqa: D102
        pass


class JsonlSink(MetricsSink):
    """Append-only JSONL event stream under ``directory``.

    Events: {"kind": "params"|"metric"|"asset", "ts": ..., ...}.  Assets are
    written both inline and as separate files under ``assets/`` so the
    queried-index audit trail survives like the reference's
    ``labeled_idxs_per_round.txt`` (strategy.py:480-483).

    ``rotate_bytes``: size-based rotation for run-indefinitely services
    (ROADMAP item 3 — an unbounded stream on a long-lived streaming-AL
    process eventually fills the disk).  When a write pushes the file
    past the cap, metrics.jsonl is atomically renamed to
    metrics.jsonl.1 (replacing any previous .1) and a fresh file opens
    — all under the sink lock, BETWEEN whole lines, so no event is ever
    split or lost across the boundary (pinned in
    tests/test_diagnostics.py).  0 (default) = never rotate.
    """

    def __init__(self, directory: str, experiment_key: Optional[str] = None,
                 rotate_bytes: int = 0):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        os.makedirs(os.path.join(directory, "assets"), exist_ok=True)
        self.experiment_key = experiment_key or uuid.uuid4().hex[:9]
        self.rotate_bytes = int(rotate_bytes or 0)
        self._path = os.path.join(directory, "metrics.jsonl")
        self._fh = open(self._path, "a")
        # The telemetry watchdog emits ``stall_suspected`` from its own
        # thread; interleaved writes must stay line-atomic.
        self._lock = threading.Lock()

    def _emit(self, event: Dict[str, Any]) -> None:
        event["ts"] = time.time()
        line = json.dumps(event, default=_json_default) + "\n"
        with self._lock:
            self._fh.write(line)
            self._fh.flush()
            if (self.rotate_bytes > 0
                    and self._fh.tell() >= self.rotate_bytes):
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Rotate under the held lock: close, atomically rename to .1
        (os.replace — readers see either the old whole file or the new
        one, never a truncation), reopen fresh.  A failed rename keeps
        appending to the same path (past the cap, but alive) — a
        rotation hiccup must not cost events."""
        self._fh.close()
        try:
            os.replace(self._path, self._path + ".1")
        except OSError:
            pass
        self._fh = open(self._path, "a")

    def log_parameters(self, params):
        self._emit({"kind": "params", "params": params})

    def log_metrics(self, metrics, step=None):
        self._emit({"kind": "metric", "step": step,
                    "metrics": {k: _to_float(v) for k, v in metrics.items()}})

    def log_asset(self, name, data):
        path = os.path.join(self.directory, "assets", f"{name}.txt")
        with open(path, "w") as fh:
            fh.write(data)
        self._emit({"kind": "asset", "name": name, "path": path})

    def close(self):
        # Under the lock: a watchdog-thread emit racing an unlocked
        # close() would write to (or flush) a closed file and kill the
        # watchdog thread with it (found by the lock-discipline checker).
        with self._lock:
            self._fh.close()


def _to_float(v: Any) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return float("nan")


def _json_default(o: Any):
    try:
        import numpy as np
        if isinstance(o, np.ndarray):
            return o.tolist()
        if isinstance(o, np.generic):
            return o.item()
    except ImportError:
        pass
    return str(o)


class CsvSink(MetricsSink):
    """Flat ``metrics.csv`` (name, value, step, ts) + params.json +
    assets/ files — for spreadsheet/pandas consumers; stdlib only."""

    def __init__(self, directory: str, experiment_key: Optional[str] = None):
        import csv

        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        os.makedirs(os.path.join(directory, "assets"), exist_ok=True)
        self.experiment_key = experiment_key or uuid.uuid4().hex[:9]
        path = os.path.join(directory, "metrics.csv")
        new = not os.path.exists(path)
        self._fh = open(path, "a", newline="")
        self._writer = csv.writer(self._fh)
        if new:
            self._writer.writerow(["name", "value", "step", "ts"])
        # The telemetry watchdog emits from its own thread (same
        # discipline as JsonlSink): rows must stay line-atomic.
        self._lock = threading.Lock()

    def log_parameters(self, params):
        with open(os.path.join(self.directory, "params.json"), "w") as fh:
            json.dump(params, fh, indent=1, default=_json_default)

    def log_metrics(self, metrics, step=None):
        ts = time.time()
        with self._lock:
            for name, value in metrics.items():
                self._writer.writerow([name, _to_float(value), step, ts])
            self._fh.flush()

    def log_asset(self, name, data):
        with open(os.path.join(self.directory, "assets", f"{name}.txt"),
                  "w") as fh:
            fh.write(data)

    def close(self):
        # Same close-vs-emit race as JsonlSink.close.
        with self._lock:
            self._fh.close()


class TensorBoardSink(MetricsSink):
    """TensorBoard event files under ``directory/tb`` (the reference's
    Comet charts, viewable with ``tensorboard --logdir``).  Scalars map
    1:1 to the metric schema; params go through add_hparams-style text;
    assets stay plain files (TensorBoard has no asset concept)."""

    def __init__(self, directory: str, experiment_key: Optional[str] = None):
        # Deliberately eager-in-constructor, lazy-at-module: importing
        # SummaryWriter loads TensorFlow (~80 s in this image), a cost
        # only runs that chose this backend should pay.
        from torch.utils.tensorboard import SummaryWriter

        self.directory = directory
        os.makedirs(os.path.join(directory, "assets"), exist_ok=True)
        self.experiment_key = experiment_key or uuid.uuid4().hex[:9]
        self._writer = SummaryWriter(
            os.path.join(directory, "tb", self.experiment_key))
        self._auto_steps: Dict[str, int] = {}
        # SummaryWriter is not documented thread-safe and the per-name
        # auto-step counter certainly is not; the watchdog thread emits
        # through the same sink as the main loop.
        self._lock = threading.Lock()

    def log_parameters(self, params):
        text = "\n".join(f"    {k}: {v}" for k, v in sorted(params.items()))
        # SummaryWriter is not documented thread-safe: add_text must
        # hold the same lock as the watchdog-thread add_scalar emits
        # (found by the lock-discipline checker).
        with self._lock:
            self._writer.add_text("parameters", text)

    def _next_step(self, name: str) -> int:
        # PER-NAME auto-step: a single shared counter incremented once
        # per log_metrics call scrambled every series' x-axis as soon as
        # two call sites omitted ``step`` (each name only saw a sparse,
        # drifting subset of the shared sequence).  Each series now
        # advances its own 1, 2, 3, ...
        if not hasattr(self, "_auto_steps"):  # __new__-built test fakes
            self._auto_steps = {}
        nxt = self._auto_steps.get(name, 0) + 1
        self._auto_steps[name] = nxt
        return nxt

    def log_metrics(self, metrics, step=None):
        if not hasattr(self, "_lock"):  # __new__-built test fakes
            self._lock = threading.Lock()
        with self._lock:
            for name, value in metrics.items():
                self._writer.add_scalar(
                    name, _to_float(value),
                    global_step=(self._next_step(name) if step is None
                                 else step))
            self._writer.flush()

    def log_asset(self, name, data):
        with open(os.path.join(self.directory, "assets", f"{name}.txt"),
                  "w") as fh:
            fh.write(data)

    def close(self):
        # Same close-vs-emit race as JsonlSink.close.
        with self._lock:
            self._writer.close()


class MultiSink(MetricsSink):
    """Fan out every event to several sinks (e.g. jsonl + tensorboard)."""

    def __init__(self, sinks):
        self.sinks = list(sinks)
        self.experiment_key = (self.sinks[0].experiment_key
                               if self.sinks else uuid.uuid4().hex[:9])

    def log_parameters(self, params):
        for s in self.sinks:
            s.log_parameters(params)

    def log_metrics(self, metrics, step=None):
        for s in self.sinks:
            s.log_metrics(metrics, step=step)

    def log_asset(self, name, data):
        for s in self.sinks:
            s.log_asset(name, data)

    def close(self):
        for s in self.sinks:
            s.close()


SINK_BACKENDS = {
    "jsonl": JsonlSink,
    "csv": CsvSink,
    "tensorboard": TensorBoardSink,
}


def make_sink(enable: bool, directory: str,
              experiment_key: Optional[str] = None,
              backend: str = "jsonl",
              rotate_bytes: int = 0) -> MetricsSink:
    """Build the configured sink(s); ``backend`` is a comma-separated list
    of SINK_BACKENDS names (unknown names raise — a typo must not
    silently drop an experiment's metrics).  ``rotate_bytes`` applies to
    the jsonl backend only (the other backends have no append-forever
    file to bound)."""
    if not enable:
        return NullSink()
    names = [b.strip() for b in backend.split(",") if b.strip()]
    if not names:
        # Metrics are ON; an empty spec (templating artifact, "" or ",")
        # silently becoming a NullSink is exactly the dropped-metrics
        # failure the unknown-name error exists to prevent.
        raise ValueError(
            "metrics enabled but metrics_backend is empty; pass one of "
            f"{sorted(SINK_BACKENDS)} or disable metrics explicitly")
    sinks = []
    for name in names:
        try:
            cls = SINK_BACKENDS[name]
        except KeyError:
            raise ValueError(
                f"Unknown metrics backend {name!r}; expected one of "
                f"{sorted(SINK_BACKENDS)}") from None
        kwargs = ({"rotate_bytes": rotate_bytes} if cls is JsonlSink
                  else {})
        sinks.append(cls(directory, experiment_key=experiment_key,
                         **kwargs))
    if len(sinks) == 1:
        return sinks[0]
    return MultiSink(sinks)
