"""Fault injection + unified recovery (DESIGN.md §10).

One package owns the failure model: the deterministic injection registry
(``site``/``configure`` — registry.py), the one RetryPolicy with
explicit transient-vs-fatal classification (retry.py), the atomic round
journal (journal.py), the degradation ladder (ladder.py — imported
directly by the driver, not re-exported: it touches the parallel stack),
and driver preemption (preempt.py).

jax-free at import time on purpose: telemetry/status.py reads the
journal through this package with no backend touch.
"""

from .journal import JOURNAL_FILE, RoundJournal, read_journal  # noqa: F401
from .preempt import PreemptionRequested  # noqa: F401
from .registry import (ACTIONS, SITES, InjectedFault, InjectedOOM,  # noqa: F401
                       ThreadDeath, active_spec, configure,
                       fault_counters, parse_spec, site)
from .retry import (FATAL, OOM, TRANSIENT, RetryPolicy,  # noqa: F401
                    classify_exception, retry_counters)
