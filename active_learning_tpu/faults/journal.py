"""The atomic round journal: one JSON file recording where the run IS.

``round_journal.json`` lives in --log_dir next to the heartbeat and is
rewritten atomically (tmp + rename — the publish_best idiom) with a
monotonic ``seq`` tag, so an external reader (the ``status`` verb's
--strict exit-code contract, a post-mortem after preemption) always
sees a complete, ordered record: current round/phase/attempt, the
labeled-set size + CRC, whether the pipelined round is armed, the
active degradation rungs, and the terminal status (finished / preempted
/ stalled / crashed).

Unlike the heartbeat (liveness: WHEN did it last move) the journal is
state (WHERE is it, and in what mode): a healthy heartbeat with a
non-empty ``degrade`` list is exactly the "alive but degraded" state an
orchestrator wants a distinct exit code for.

Resume continuity: a new RoundJournal over an existing file continues
its ``seq`` — the monotonic tag never restarts within an experiment
directory, so two records can always be ordered even across process
restarts within one filesystem.

Stdlib-only on purpose: telemetry/status.py reads it with NO jax import.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

JOURNAL_FILE = "round_journal.json"


def read_journal(path: str) -> Optional[Dict[str, Any]]:
    """The journal payload, or None when absent/unparseable (a torn file
    is impossible by construction; missing means the run predates the
    journal or never started)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


class RoundJournal:
    """Merge-and-rewrite journal writer (field semantics like the
    heartbeat: a write merges its fields over the retained ones, so a
    ``status="preempted"`` snapshot keeps the round/phase context of the
    last regular write).  ``enabled=False`` (non-coordinator processes)
    makes every write a no-op.  Never raises: a full disk must not take
    the run down — the log already records real progress."""

    def __init__(self, path: str, enabled: bool = True):
        self.path = path
        self.enabled = enabled
        self._lock = threading.Lock()
        self._fields: Dict[str, Any] = {}
        prior = read_journal(path) if enabled else None
        self._seq = int(prior.get("seq", 0)) if prior else 0

    def write(self, **fields: Any) -> Optional[Dict[str, Any]]:
        """Merge ``fields`` (None values delete), bump seq, rewrite
        atomically.  Returns the written payload (None when disabled or
        the write failed)."""
        if not self.enabled:
            return None
        with self._lock:
            for k, v in fields.items():
                if v is None:
                    self._fields.pop(k, None)
                else:
                    self._fields[k] = v
            self._seq += 1
            payload = {**self._fields, "seq": self._seq, "ts": time.time()}
        try:
            directory = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(directory, exist_ok=True)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(payload, fh)
            os.replace(tmp, self.path)
        except OSError:
            return None
        return payload
