"""Driver-side preemption: SIGTERM/SIGINT -> checkpoint-and-exit.

Preemptible TPU pods make eviction the COMMON case, and until now only
``serve/`` handled signals — a SIGTERM to the driver died wherever it
stood, losing up to a whole round of mesh time.  The contract here:

  * the signal handler only RECORDS the request (async-signal-safe; a
    raise inside XLA's dispatch would corrupt the very state we want to
    save) and logs once;
  * the trainer checks at each epoch boundary — publishing any pending
    best snapshot and saving the mid-round fit state first, so the
    resumed fit continues bit-for-bit — and the driver checks at each
    phase boundary; whichever sees the flag first raises
    ``PreemptionRequested``;
  * the driver's handler for it writes the round journal
    (status="preempted"), drains the pipeline's scorer/prefetch threads
    (the normal shutdown path — no orphans), finishes telemetry, and
    re-raises; the CLI maps it to exit 0.  ``--resume_training`` then
    reproduces the uninterrupted run's experiment_state bit-identically
    (pinned by the SIGTERM subprocess test in tests/test_faults.py).

Handlers install only on the main thread (signal.signal requires it;
in-process test harnesses calling run_experiment from workers simply
keep their own handlers) and the previous handlers are restored on
uninstall, so a driver run never leaks its disposition into a host
process (pytest, a notebook).
"""

from __future__ import annotations

import signal
import threading
from typing import Any, Dict, Optional


class PreemptionRequested(Exception):
    """Raised at a safe point after SIGTERM/SIGINT; carries the signal
    number.  The run's durable state is already consistent when this is
    raised — resuming reproduces the uninterrupted run."""

    def __init__(self, signum: int):
        name = {signal.SIGTERM: "SIGTERM",
                signal.SIGINT: "SIGINT"}.get(signum, str(signum))
        super().__init__(f"preemption requested ({name}); state "
                         "checkpointed for --resume_training")
        self.signum = signum


_STATE: Dict[str, Any] = {"signum": None, "logger": None}


def _handler(signum, frame) -> None:  # pragma: no cover - exercised via kill
    first = _STATE["signum"] is None
    _STATE["signum"] = signum
    logger = _STATE.get("logger")
    if first and logger is not None:
        try:
            logger.warning(
                "preemption signal received: checkpointing at the next "
                "epoch/phase boundary, then exiting for --resume_training")
        except Exception:  # noqa: BLE001 - inside a signal handler
            pass


def install(logger=None) -> Optional[Dict[int, Any]]:
    """Install the SIGTERM/SIGINT recorders; returns the previous
    handlers for ``uninstall``, or None when not on the main thread
    (the host process keeps its own handling)."""
    if threading.current_thread() is not threading.main_thread():
        return None
    _STATE["logger"] = logger
    previous = {}
    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            previous[signum] = signal.signal(signum, _handler)
        except (ValueError, OSError):  # pragma: no cover - exotic hosts
            pass
    return previous


def uninstall(previous: Optional[Dict[int, Any]]) -> None:
    if not previous:
        return
    for signum, handler in previous.items():
        try:
            signal.signal(signum, handler)
        except (ValueError, OSError):  # pragma: no cover
            pass


def reset() -> None:
    """Clear a recorded request (run start: a flag left by a previous
    in-process run must not kill the new one)."""
    _STATE["signum"] = None


def requested() -> Optional[int]:
    """The recorded signal number, or None."""
    return _STATE["signum"]


def check() -> None:
    """Raise PreemptionRequested iff a signal was recorded — the one
    spelling every safe point uses."""
    signum = _STATE["signum"]
    if signum is not None:
        raise PreemptionRequested(signum)
