"""The ONE retry policy (DESIGN.md §10): exponential backoff + jitter,
attempt/wall budgets, and explicit transient-vs-fatal classification —
replacing the ad-hoc guards that grew up around device transfer, shard
upload, checkpoint IO, and the best-ckpt-watcher polls.

Two rules, both enforced statically by scripts/trace_lint.py check 8:

  * every ``RetryPolicy(...)`` construction passes ``classify=``
    explicitly — there is no default classifier to hide behind, so "what
    does this site consider transient" is always written at the site
    (no bare ``except Exception: retry`` anywhere);
  * classification returns one of TRANSIENT (back off and retry), OOM
    (never retried at the same shape — re-raised for the degradation
    ladder's batch-halving rung), FATAL (re-raised immediately).

Every retry is counted process-wide (``retry_counters``) and surfaced
through the run's telemetry: the driver emits ``fault_retries_total`` /
``degrade_events`` into the MetricsSink at round boundaries, the
Prometheus scrape file carries the same gauges, and the site label of
the most recent retry rides the heartbeat as ``fault_last_site`` (a
string, so it travels the heartbeat rather than a numeric gauge).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, Optional

from .registry import InjectedFault, InjectedOOM, ThreadDeath

TRANSIENT = "transient"
OOM = "oom"
FATAL = "fatal"

_COUNTERS_LOCK = threading.Lock()
_RETRIES_TOTAL = 0
_RETRIES_BY_SITE: Dict[str, int] = {}
_LAST_SITE: Optional[str] = None


def classify_exception(exc: BaseException) -> str:
    """The default classification shared by the infrastructure call
    sites (call sites still name it explicitly — trace_lint check 8):

      OOM        allocator exhaustion (XLA RESOURCE_EXHAUSTED, the
                 injected stand-in) — retrying at the same shape fails
                 the same way; the degradation ladder halves the batch
                 instead;
      TRANSIENT  injected faults, injected thread death (a dead worker
                 thread is rebuilt by re-running the pass), and OSError
                 (full disk, yanked NFS, racing renames — the classic
                 retryable IO surface);
      FATAL      everything else: a programming error retried three
                 times is a programming error that wasted two retries.
    """
    if isinstance(exc, InjectedOOM):
        return OOM
    if "RESOURCE_EXHAUSTED" in str(exc):
        return OOM
    if isinstance(exc, (InjectedFault, ThreadDeath)):
        return TRANSIENT
    if isinstance(exc, OSError):
        return TRANSIENT
    return FATAL


def _record_retry(site: str) -> None:
    global _RETRIES_TOTAL, _LAST_SITE
    with _COUNTERS_LOCK:
        _RETRIES_TOTAL += 1
        _RETRIES_BY_SITE[site] = _RETRIES_BY_SITE.get(site, 0) + 1
        _LAST_SITE = site
    # Surface through the installed run's telemetry (inert default
    # records nothing): the site label rides the heartbeat for
    # `status`.  The fault_retries_total GAUGE is owned by the driver's
    # round-boundary emission, which subtracts its run-start baseline —
    # setting the raw process total here would fight it.
    try:
        from ..telemetry import runtime as tele_runtime
        tele_runtime.get_run().tick(fault_last_site=site)
    except Exception:  # noqa: BLE001 - accounting must never take a run down
        pass


def retry_counters() -> Dict[str, Any]:
    """Process-cumulative retry accounting: {"total", "by_site",
    "last_site"} — the driver emits total per round, bench rides it on
    the al_round phases."""
    with _COUNTERS_LOCK:
        return {"total": _RETRIES_TOTAL,
                "by_site": dict(_RETRIES_BY_SITE),
                "last_site": _LAST_SITE}


class RetryPolicy:
    """Bounded, classified retry around one operation.

    ``site`` is a free-form metrics label (it names the retried
    OPERATION for fault_retries_total attribution; the injection-site
    registry in registry.SITES is a separate, closed namespace).
    ``classify`` maps an exception to TRANSIENT/OOM/FATAL and is
    REQUIRED — trace_lint check 8 rejects constructions without it.
    """

    def __init__(self, site: str, classify: Callable[[BaseException], str],
                 max_attempts: int = 3, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0, wall_budget_s: float = 30.0):
        if classify is None:
            raise ValueError(
                f"RetryPolicy({site!r}): classify is required — every "
                "call site states its transient-vs-fatal rule")
        self.site = site
        self.classify = classify
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.wall_budget_s = float(wall_budget_s)
        self._jitter = random.Random(f"retry:{site}")

    def call(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        """Run ``fn(*args, **kwargs)``; retry classified-TRANSIENT
        failures with exponential backoff + jitter until the attempt or
        wall budget runs out, then re-raise the last failure.  OOM and
        FATAL re-raise immediately (see classify_exception)."""
        t0 = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except BaseException as exc:  # noqa: BLE001 - classified below
                kind = self.classify(exc)
                if kind != TRANSIENT:
                    raise
                if attempt >= self.max_attempts:
                    raise
                if time.monotonic() - t0 >= self.wall_budget_s:
                    raise
                delay = min(self.max_delay_s,
                            self.base_delay_s * (2 ** (attempt - 1)))
                delay *= 0.5 + self._jitter.random()  # [0.5x, 1.5x)
                _record_retry(self.site)
                try:
                    from ..utils.logging import get_logger
                    get_logger().warning(
                        f"retry[{self.site}] attempt {attempt}/"
                        f"{self.max_attempts} failed with "
                        f"{type(exc).__name__}: {exc}; retrying in "
                        f"{delay * 1000:.0f} ms")
                except Exception:  # noqa: BLE001 - logging is best-effort
                    pass
                time.sleep(delay)
