"""The deterministic fault-injection registry (DESIGN.md §10).

Every recovery path this framework claims — retried H2D uploads, torn
checkpoint pairs that read as nothing-to-resume, a speculative scorer
thread that dies without losing the round, a preempted driver that
resumes bit-identically — is only real if a test can MAKE the failure
happen on demand.  This module is that switch: named fault points
(``site("h2d_upload")``) compiled into the production code paths, free
when disarmed (one module-global ``is None`` check — pinned like the
telemetry-off <50µs/step bound) and, when armed via ``--fault_spec`` /
``$AL_FAULT_SPEC``, deterministically raising, tearing a multi-file
write, killing the calling thread, or delaying.

Spec grammar (comma-separated)::

    site:action[@arg]

    h2d_upload:raise@3        raise InjectedFault on the 3rd hit (1-based,
                              fires exactly once)
    ckpt_write:torn@1         raise at the site's TORN point (between the
                              two renames of an atomic multi-file write)
                              on the 1st torn-point hit
    spec_scorer:die@0.5       kill the calling thread (ThreadDeath, a
                              BaseException that sails past
                              ``except Exception`` guards) with seeded
                              probability 0.5 per hit
    dispatch:delay@0.05       sleep 50 ms at every hit
    feed_worker:oom@2         raise InjectedOOM (classified like XLA's
                              RESOURCE_EXHAUSTED) on the 2nd hit

Integer args are Nth-hit triggers (deterministic, fire once); float args
in (0, 1) are per-hit probabilities drawn from a per-(seed, site)
``random.Random`` — replayable across runs; for ``delay`` the arg is
seconds.  No arg = every hit.

Site names are a CLOSED registry (``SITES``): scripts/trace_lint.py
check 8 statically verifies every ``faults.site()`` call site names a
registered site (string literal, registered exactly once) and that every
registered site is wired somewhere — a typo'd site name can never
silently never-fire.

Every site call has two points: ``enter`` (the default — raise/oom/die/
delay fire here, BEFORE the guarded work) and ``torn`` (only the
``torn`` action fires there — placed between the renames of an atomic
write pair so the crash leaves exactly the partial state the readers
must treat as nothing-to-resume).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Optional, Tuple

# The closed site registry — each name registered EXACTLY once (enforced
# statically by trace_lint check 8 alongside the wiring coverage).
#   h2d_upload    parallel/resident.pool_arrays — the once-per-experiment
#                 resident-pool device upload
#   shard_upload  parallel/mesh.shard_rows — the per-shard H2D of a
#                 row-sharded upload
#   ckpt_write    train/checkpoint.save_variables / save_fit_state /
#                 publish_best + experiment/resume.save_experiment (torn
#                 points between each atomic pair's renames)
#   spec_scorer   experiment/pipeline._score_chunk — the speculative
#                 scorer thread's chunk execution
#   feed_worker   data/cache.device_prefetch — the async H2D feeder
#                 thread behind scoring/serving
#   dispatch      parallel/mesh.DispatchGate.__enter__ — every
#                 collective-bearing jitted dispatch
#   grad_probe    experiment/driver.run_grad_allreduce_probe — the
#                 multichip learning probe gating --grad_allreduce
#                 int8 (an injected failure = a broken probe; the run
#                 must degrade to the f32 sync loudly, never crash)
#   wal_write     stream/wal.IngestWAL.append — the ingest WAL's durable
#                 append (torn point between the half-written line and
#                 its completion: a kill there must replay as a dropped
#                 never-acked record, not corruption)
#   stream_drain  stream/service.StreamService._drain — applying queued
#                 ingest records to the pool between rounds (a failure
#                 here must crash the service BEFORE any round consumes
#                 a half-applied pool; the WAL replay on restart loses
#                 no accepted row)
#   page_read     data/diskpool._DiskPoolCore._load_block — one
#                 bucket-aligned block read off the disk tier (torn
#                 point between the block's two half-reads: a fault
#                 there must never leave a partial block in the host
#                 cache; the gather's RetryPolicy re-reads the block)
#   fleet_journal fleet/journal.write_atomic_json — the fleet
#                 controller's scheduling-state rewrite (torn point
#                 between the tmp write and the rename: a controller
#                 killed there must restart from the PREVIOUS complete
#                 journal, never a spliced one)
SITES = ("h2d_upload", "ckpt_write", "spec_scorer", "feed_worker",
         "shard_upload", "dispatch", "grad_probe", "wal_write",
         "stream_drain", "page_read", "fleet_journal")

ACTIONS = ("raise", "oom", "die", "delay", "torn")


class InjectedFault(RuntimeError):
    """A deliberately injected, transiently-classified failure."""

    def __init__(self, site_name: str, detail: str = ""):
        super().__init__(f"injected fault at site {site_name!r}"
                         + (f" ({detail})" if detail else ""))
        self.site = site_name


class InjectedOOM(InjectedFault):
    """Injected allocator exhaustion — the message carries the XLA
    RESOURCE_EXHAUSTED marker so string-matching classifiers (the bench
    crash ladder's, retry.classify_exception) treat it exactly like the
    real thing."""

    def __init__(self, site_name: str):
        super().__init__(site_name, "RESOURCE_EXHAUSTED (injected)")


class ThreadDeath(BaseException):
    """Injected thread death.  Deliberately a BaseException: it must
    sail past every ``except Exception`` guard on the thread's stack and
    actually KILL the thread, so the survivors' cleanup paths (the
    pipeline worker's finally, device_prefetch's feeder forwarding) are
    what the chaos tests exercise — not a politely caught error."""

    def __init__(self, site_name: str):
        super().__init__(f"injected thread death at site {site_name!r}")
        self.site = site_name


class _SiteState:
    """One armed site: its action, trigger arg, seeded rng, and hit
    counters (per point)."""

    def __init__(self, name: str, action: str, arg, seed: int):
        self.name = name
        self.action = action
        self.arg = arg
        self.hits: Dict[str, int] = {"enter": 0, "torn": 0}
        self.fires = 0
        self._rng = random.Random(f"{seed}:{name}:{action}")

    def hit(self, point: str) -> Optional[float]:
        """Count the hit and fire the action: raising actions raise;
        ``delay`` RETURNS the sleep seconds instead (the caller sleeps
        OUTSIDE the registry lock — sites fire from several threads, and
        a sleep under the shared lock would serialize exactly the
        cross-thread races delays exist to widen)."""
        fire_point = "torn" if self.action == "torn" else "enter"
        if point != fire_point:
            return None
        self.hits[point] += 1
        arg = self.arg
        if self.action == "delay":
            self.fires += 1
            return float(arg) if arg is not None else 0.01
        if arg is None:
            fire = True
        elif isinstance(arg, int):
            fire = self.hits[point] == arg  # Nth hit, exactly once
        else:
            fire = self._rng.random() < float(arg)
        if not fire:
            return None
        self.fires += 1
        if self.action == "oom":
            raise InjectedOOM(self.name)
        if self.action == "die":
            raise ThreadDeath(self.name)
        raise InjectedFault(self.name, self.action)


# Disarmed = None: site() is one global read + identity compare.  The
# lock guards only ARMED-path hit counting (sites fire from several
# threads: the scorer, the prefetch feeder, the trainer).
_ARMED: Optional[Dict[str, _SiteState]] = None
_LOCK = threading.Lock()

# Lock discipline, statically enforced (scripts/al_lint.py
# lock-discipline): per-site hit/fire counters are mutated from every
# thread a site fires on — counted only under _LOCK.  ``hit`` is the
# declared under-the-lock helper (site() holds _LOCK around it).
_GUARDED_BY = {"hits": "_LOCK", "fires": "_LOCK"}
_LOCKED_HELPERS = ("hit",)


def parse_spec(spec: str) -> Dict[str, Tuple[str, Any]]:
    """``"h2d_upload:raise@3,ckpt_write:torn@1"`` ->
    ``{"h2d_upload": ("raise", 3), "ckpt_write": ("torn", 1)}``.
    Unknown sites/actions and malformed args fail fast — a typo'd spec
    arming nothing would make every chaos run silently vacuous."""
    out: Dict[str, Tuple[str, Any]] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        try:
            name, rest = part.split(":", 1)
        except ValueError:
            raise ValueError(f"fault spec entry {part!r}: expected "
                             "site:action[@arg]") from None
        if name not in SITES:
            raise ValueError(f"fault spec names unknown site {name!r} "
                             f"(registered: {', '.join(SITES)})")
        action, _, arg_s = rest.partition("@")
        if action not in ACTIONS:
            raise ValueError(f"fault spec action {action!r} for {name!r} "
                             f"is not one of {', '.join(ACTIONS)}")
        arg: Any = None
        if arg_s:
            try:
                arg = int(arg_s)
                if action != "delay" and arg < 1:
                    raise ValueError
            except ValueError:
                try:
                    arg = float(arg_s)
                except ValueError:
                    raise ValueError(
                        f"fault spec arg {arg_s!r} for {part!r} is "
                        "neither an int hit-count nor a float") from None
                if action != "delay" and not (0.0 < arg < 1.0):
                    raise ValueError(
                        f"fault spec probability {arg} for {part!r} must "
                        "be in (0, 1)")
        if name in out:
            raise ValueError(f"fault spec arms site {name!r} twice")
        out[name] = (action, arg)
    return out


def configure(spec: Optional[str], seed: int = 0) -> None:
    """Arm the registry from a spec string (None/"" disarms).  The spec
    resolution order at the driver is --fault_spec, then $AL_FAULT_SPEC
    — but the driver only calls this when one of them is set, so a test
    that armed programmatically before calling run_experiment keeps its
    arming."""
    global _ARMED
    if not spec:
        _ARMED = None
        return
    parsed = parse_spec(spec)
    _ARMED = {name: _SiteState(name, action, arg, seed)
              for name, (action, arg) in parsed.items()}


def active_spec() -> Optional[Dict[str, Tuple[str, Any]]]:
    armed = _ARMED
    if armed is None:
        return None
    return {name: (st.action, st.arg) for name, st in armed.items()}


def site(name: str, point: str = "enter") -> None:
    """A named fault point.  Disarmed (the production default) this is a
    single module-global check — zero-cost on hot paths (pinned in
    tests/test_faults.py).  Armed, the site's action fires per its
    trigger rule; see the module docstring for the grammar."""
    armed = _ARMED
    if armed is None:
        return
    st = armed.get(name)
    if st is None:
        return
    with _LOCK:
        delay = st.hit(point)
    if delay is not None:
        time.sleep(delay)


def fault_counters() -> Dict[str, Dict[str, int]]:
    """Per-site hit/fire counters of the CURRENT arming ({} when
    disarmed) — chaos tests assert the fault actually fired, so a
    recovered run can never be mistaken for a never-faulted one."""
    armed = _ARMED
    if armed is None:
        return {}
    with _LOCK:
        return {name: {"hits": sum(st.hits.values()), "fires": st.fires}
                for name, st in armed.items()}
