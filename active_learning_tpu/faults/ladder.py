"""The degradation ladder: escalate instead of crashing (DESIGN.md §10).

A failure that survives the site-level RetryPolicy reaches the driver's
round-attempt loop, which restores the round-start snapshot (pool, rng,
init key, model variables — so the retried round is BIT-identical to
the fault-free one) and asks this ladder for a less ambitious mode.
Rung order, each reversible at the next round boundary (``relax``):

  1. pipeline_off     speculative pipelined round -> sequential round
                      (the pipeline's correctness contract makes this
                      bit-identical; it only costs wall-clock)
  2. pool_replicated  row-sharded residency -> replicated (pinned pools
                      demoted; the next upload lands replicated —
                      layouts are bit-identical by the PR 6 contract)
  3. feed_host        resident budget -> 0: every consumer (scoring,
                      eval, the train feed) falls back to its
                      host-streamed path with zero recompiles (the
                      documented demotion path) — feeds are
                      bit-identical by the PR 5 contract
  4. batch_half       OOM only: halve the train batch (the bench-only
                      crash ladder promoted into the driver).  The ONE
                      rung that is not bit-identical — batch size
                      changes BN statistics — which is why OOM is
                      outside the chaos matrix's bit-identity claim.

Rung selection: OOM-classified failures try batch_half first, then fall
through to the HBM-FREEING rungs (feed_host, pipeline_off — never
pool_replicated, which costs more per chip) when the batch is already
at the device floor; failures whose provenance names a subsystem (an
InjectedFault's site, the exception's traceback module) prefer that
subsystem's rung; anything else takes the next un-applied rung in
order.  Every escalation logs,
emits ``degrade_events`` through the MetricsSink at the round boundary,
updates the round journal's ``degrade`` list, and rides the telemetry
gauges — `status --strict` exits 4 while any rung is active.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional

from . import retry as retry_lib
from .registry import InjectedFault, ThreadDeath

RUNGS = ("pipeline_off", "pool_replicated", "feed_host", "batch_half")

# Site/subsystem provenance -> preferred first rung.
_SITE_RUNG = {
    "spec_scorer": "pipeline_off",
    "dispatch": "pipeline_off",
    "shard_upload": "pool_replicated",
    "h2d_upload": "pool_replicated",
    "feed_worker": "feed_host",
}

# Traceback-module provenance for REAL failures (no injected .site):
# the deepest frame inside one of these subsystems names the rung —
# a genuine shard-upload OSError on a multi-hour ImageNet round must
# not waste its first retry attempt on the irrelevant pipeline_off.
_MODULE_RUNG = (
    ("active_learning_tpu/experiment/pipeline", "pipeline_off"),
    ("active_learning_tpu/parallel/resident", "pool_replicated"),
    ("active_learning_tpu/parallel/mesh", "pool_replicated"),
    ("active_learning_tpu/data/cache", "feed_host"),
    ("active_learning_tpu/data/pipeline", "feed_host"),
)


def _provenance_rung(exc: BaseException) -> Optional[str]:
    """The rung the failure's origin names: an injected fault carries
    its site; anything else is attributed by the DEEPEST traceback
    frame inside a mapped subsystem module."""
    if isinstance(exc, (InjectedFault, ThreadDeath)):
        rung = _SITE_RUNG.get(getattr(exc, "site", ""))
        if rung is not None:
            return rung
    frames = []
    tb = exc.__traceback__
    while tb is not None:
        frames.append(tb.tb_frame.f_code.co_filename)
        tb = tb.tb_next
    for fname in reversed(frames):  # innermost first
        norm = fname.replace(os.sep, "/")
        for frag, rung in _MODULE_RUNG:
            if frag in norm:
                return rung
    return None


class DegradeRequested(Exception):
    """Raised at a driver safe point when the stall watchdog (armed with
    --watchdog_action degrade) asked for escalation — consumed by the
    round-attempt loop exactly like a classified failure."""


class DegradationLadder:
    """Owns the active rungs for one experiment run.  The driver calls
    ``relax`` at each round start (degradation is per-round — the next
    round retries at full capability), ``escalate`` when a round attempt
    fails, and ``check_stall`` at safe points."""

    def __init__(self, strategy, logger=None, sink=None, journal=None):
        self.strategy = strategy
        self.logger = logger
        self.sink = sink
        self.journal = journal
        self.active: List[str] = []
        self.events = 0  # cumulative escalations this run
        self._saved: Dict[str, Any] = {}
        self._stall_requested = False

    def max_attempts(self) -> int:
        """Round attempts = one clean try + one per remaining rung."""
        return len(RUNGS) + 1

    # -- stall hand-off (watchdog thread -> driver safe point) -----------

    def request_stall(self) -> None:
        self._stall_requested = True

    def check_stall(self) -> None:
        if self._stall_requested:
            self._stall_requested = False
            raise DegradeRequested("stall watchdog requested degradation")

    # -- escalation ------------------------------------------------------

    def _candidate_rungs(self, exc: BaseException) -> List[str]:
        """Un-applied rungs in preference order for ``exc``.  OOM:
        batch_half first, then the rungs that FREE HBM (demoting the
        resident pool, stopping the scorer's extra buffers) — never
        pool_replicated, whose per-chip residency costs MORE than row.
        Everything else: the failure's provenance rung, then the
        generic order; batch_half stays OOM-only."""
        kind = retry_lib.classify_exception(exc)
        if kind == retry_lib.OOM:
            order = ("batch_half", "feed_host", "pipeline_off")
        else:
            preferred = _provenance_rung(exc)
            order = ([preferred] if preferred
                     and preferred != "batch_half" else [])
            order += [r for r in RUNGS
                      if r != "batch_half" and r not in order]
        return [r for r in order if r not in self.active]

    def escalate(self, exc: BaseException, round_idx: int) -> Optional[str]:
        """Apply the next rung for ``exc``; returns its name, or None
        when the ladder is exhausted (the caller re-raises).  A
        candidate that cannot apply (batch already at the device floor)
        falls through to the next instead of dead-ending the ladder."""
        rung = None
        for candidate in self._candidate_rungs(exc):
            if self._apply(candidate):
                rung = candidate
                break
        if rung is None:
            return None
        self.active.append(rung)
        self.events += 1
        if self.logger is not None:
            self.logger.warning(
                f"degradation ladder: round {round_idx} failed with "
                f"{type(exc).__name__} ({exc}); engaging rung "
                f"{rung!r} (active: {self.active}) and retrying the "
                "round from its start")
        if self.sink is not None:
            self.sink.log_metric("degrade_events", self.events,
                                 step=round_idx)
        if self.journal is not None:
            self.journal.write(degrade=list(self.active), round=round_idx,
                               status="running")
        try:
            from ..telemetry import runtime as tele_runtime
            rt = tele_runtime.get_run()
            rt.set_gauges(degrade_active=len(self.active))
            rt.tick(force=True, degrade=",".join(self.active))
        except Exception:  # noqa: BLE001 - accounting must never crash
            pass
        return rung

    def _apply(self, rung: str) -> bool:
        strategy = self.strategy
        trainer = strategy.trainer
        if rung == "pipeline_off":
            pipe = strategy.pipeline
            self._saved["pipeline"] = pipe
            if pipe is not None:
                pipe.disarm()
            strategy.pipeline = None
            return True
        if rung == "pool_replicated":
            from ..parallel import resident as resident_lib
            self._saved["pool_sharding"] = (trainer.pool_sharding,
                                            trainer._shard_ways)
            # Demote every pinned entry so the next upload lands in the
            # new layout (an entry's layout is fixed at first upload).
            resident_lib.enforce_budget(trainer.resident_pool, 0)
            trainer.pool_sharding = "replicated"
            trainer._shard_ways = 1
            return True
        if rung == "feed_host":
            self._saved["resident_budget"] = trainer.resident_budget
            # pin: the retried attempt's round-start AUTO budget refresh
            # must not re-admit the resident path mid-degraded-round.
            trainer.set_resident_budget(0, pin=True)
            return True
        if rung == "batch_half":
            halved = self._halve_batch(trainer)
            return halved is not None
        return False

    def _halve_batch(self, trainer) -> Optional[int]:
        loader = trainer.cfg.loader_tr
        floor = trainer.n_devices
        new_bs = max(floor, loader.batch_size // 2)
        if new_bs == loader.batch_size:
            return None
        self._saved.setdefault("loader_tr", loader)
        trainer.cfg = dataclasses.replace(
            trainer.cfg, loader_tr=dataclasses.replace(loader,
                                                       batch_size=new_bs))
        if self.logger is not None:
            self.logger.warning(
                f"degradation ladder: train batch halved to {new_bs} "
                "(OOM); reverts at the next round boundary")
        return new_bs

    # -- reversal at the round boundary ----------------------------------

    def relax(self, round_idx: Optional[int] = None) -> List[str]:
        """Revert every active rung (called at round start — each round
        retries at full capability; a systematic failure re-engages the
        ladder, a transient one stays recovered).  Returns the reverted
        rung names."""
        if not self.active:
            self._stall_requested = False
            return []
        strategy = self.strategy
        trainer = strategy.trainer
        reverted = list(self.active)
        if "pipeline_off" in self.active:
            strategy.pipeline = self._saved.get("pipeline")
        if "pool_replicated" in self.active:
            from ..parallel import resident as resident_lib
            sharding, ways = self._saved["pool_sharding"]
            # Demote the replicated-degraded entries so the restored
            # layout's next upload is actually row-sharded again.
            resident_lib.enforce_budget(trainer.resident_pool, 0)
            trainer.pool_sharding = sharding
            trainer._shard_ways = ways
        if "feed_host" in self.active:
            trainer.set_resident_budget(self._saved["resident_budget"])
        if "batch_half" in self.active:
            trainer.cfg = dataclasses.replace(
                trainer.cfg, loader_tr=self._saved["loader_tr"])
        self.active = []
        self._saved = {}
        self._stall_requested = False
        if self.logger is not None:
            self.logger.info(
                f"degradation ladder: reverted {reverted} at the round "
                "boundary (full capability restored)")
        if self.journal is not None:
            self.journal.write(degrade=[], round=round_idx)
        try:
            from ..telemetry import runtime as tele_runtime
            tele_runtime.get_run().set_gauges(degrade_active=0)
        except Exception:  # noqa: BLE001
            pass
        return reverted
