"""The disk tier of the pool storage hierarchy (DESIGN.md §16):
disk extents -> bounded host block cache -> HBM residency.

Every pool before this tier had to materialize in host RAM before
sharding (``ArrayDataset.images``), capping the system at RAM-per-host
rows.  ``DiskPool`` is the demand-paged backend behind
``--pool_backend disk``: rows live in one sparse extent file on disk
(written once, block by block, through the same bucketed-extent
machinery as ``GrowableRowStore``), and gathers page **bucket-aligned
row blocks** into a byte-bounded LRU host cache.  The hot tier above —
the labeled rows the trainer scans every epoch — is pinned in HBM by
the resident machinery (``parallel/resident.pin_hot``), counted by
``pinned_bytes`` and demotable by ``enforce_budget`` like any pinned
pool entry.

Bit-identity: a ``DiskPool`` serves exactly the bytes of the array it
spilled, so every consumer that reads through the ``Dataset`` contract
(``gather`` + ``targets``) — the host scoring stream, the host train
feeds, eval — produces results BIT-identical to the in-memory backend
(pinned e2e in tests/test_disk_pool.py for Margin and Coreset).  The
``images`` property deliberately raises AttributeError: every residency
and feed gate in the codebase reads ``getattr(ds, "images", None)``, so
a paged pool cleanly routes ALL whole-array consumers to the streaming
paths (the same contract as a partially-populated DecodedPoolCache).
Paging overlaps device compute for free: gathers run on the
``device_prefetch`` / ``iterate_batches`` feeder threads, so a block
read for batch n+1 hides behind batch n's dispatch.

Honesty rules (statically enforced by al_lint check 17
``disk-pool-paging`` over the ``_PAGED_READERS`` registry below): no
paging-path function may materialize the whole store on one host — no
``np.asarray(mm)``, no full ``mm[:]`` slice, no ``mm.copy()``.  Reads
are bounded block slices; the spy counters (``max_read_rows``,
``peak_cache_bytes``) let tests prove it dynamically too.

Multi-host meshes: pass ``local_rows`` (``mesh.process_pool_rows``) and
each process spills + reads ONLY its own contiguous row range — the
same per-process slicing ``shard_rows`` uploads through — so the full
pool never lands on any one host even transiently.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from .. import faults
from ..utils.logging import get_logger
from .core import Dataset, ViewSpec

# The closed registry of paging-path functions (al_lint check 17
# ``disk-pool-paging``): these are the ONLY functions that touch the
# disk extent, and none of them may materialize the whole store.
_PAGED_READERS = ("gather", "_load_block", "spill_rows")

# One retry policy for block reads off the disk tier (DESIGN.md §10):
# OSError / injected faults are transient (NFS hiccup, racing page-out),
# anything else is a programming error and re-raises immediately.
_PAGE_RETRY = faults.RetryPolicy(site="page_read",
                                 classify=faults.classify_exception,
                                 max_attempts=3)

# Bounded reservoir of per-block stall samples for the round percentiles
# — big enough for every block of a round at ImageNet scale, small
# enough to never matter.
_STALL_SAMPLES_MAX = 8192


def page_rows_for(requested: int, extent_floor: int = 64) -> int:
    """Snap a requested block size onto the shared extent ladder
    (``pool.bucket_size``) so paged blocks are bucket-aligned — the
    same enumerable ladder the resident uploads and the growable store
    extents live on."""
    from ..pool import bucket_size
    return bucket_size(max(int(requested), 1), floor=int(extent_floor))


def spill_rows(mm: np.ndarray, source, lo: int, hi: int,
               block_rows: int) -> None:
    """Write rows [lo, hi) of ``source`` (anything with ``gather``, or a
    plain array) into the extent memmap ``mm``, one bounded block at a
    time — the spill never holds more than ``block_rows`` rows beyond
    the source itself, and never slices the whole store."""
    images = source if isinstance(source, np.ndarray) else None
    for b0 in range(int(lo), int(hi), int(block_rows)):
        b1 = min(b0 + int(block_rows), int(hi))
        if images is not None:
            mm[b0:b1] = images[b0:b1]
        else:
            mm[b0:b1] = source.gather(np.arange(b0, b1, dtype=np.int64))
    mm.flush()


class _DiskPoolCore:
    """The shared storage + cache object behind every ``DiskPool`` view
    (the train/al pair shares ONE extent file, one block cache, one
    stats ledger — exactly like ``ArrayDataset.with_view`` shares one
    array).

    Thread contract: gathers run concurrently from the pipeline's
    worker threads and the device_prefetch feeder; all cache + stats
    bookkeeping is under ``_lock``.
    """

    # Lock discipline (al_lint lock-discipline): the block cache, its
    # LRU order, and every stat counter are mutated from all feeder
    # threads — only under _lock.
    _GUARDED_BY = {"_blocks": "_lock", "_lru": "_lock",
                   "_cache_bytes": "_lock", "_stats": "_lock",
                   "_stalls": "_lock"}

    def __init__(self, path: str, n_rows: int, image_shape,
                 dtype=np.uint8, page_rows: int = 2048,
                 host_cache_bytes: int = 1 << 30,
                 local_rows: Optional[slice] = None):
        self.path = path
        self.n_rows = int(n_rows)
        self.image_shape = tuple(int(d) for d in image_shape)
        self.dtype = np.dtype(dtype)
        self.page_rows = page_rows_for(page_rows)
        self.host_cache_bytes = int(host_cache_bytes)
        self.row_bytes = int(np.prod(self.image_shape, dtype=np.int64)
                             or 1) * self.dtype.itemsize
        # The per-process row range (multi-host meshes): reads outside
        # it raise — this process's disk extent only ever held its own
        # rows.  None = single-process, everything local.
        self.local_rows = local_rows
        self._mm: Optional[np.ndarray] = None
        self._lock = threading.Lock()
        self._blocks: Dict[int, np.ndarray] = {}
        self._lru = []  # block ids, least-recently-used first
        self._cache_bytes = 0
        self._stats = {"hits": 0, "misses": 0, "rows_paged_in": 0,
                       "page_in_time_s": 0.0, "max_read_rows": 0,
                       "peak_cache_bytes": 0}
        self._stalls = []  # per-block read ms, drained per round

    # -- construction ------------------------------------------------------

    def create(self, source) -> None:
        """Sparse-create the extent file (tmp+rename, the store idiom)
        and spill this process's row range of ``source`` into it,
        block by block.  After this the source array can be dropped —
        the disk extent is the pool."""
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        with open(self.path + ".tmp", "wb") as fh:
            fh.truncate(self.n_rows * self.row_bytes)
        os.replace(self.path + ".tmp", self.path)
        mm = np.memmap(self.path, dtype=self.dtype, mode="r+",
                       shape=(self.n_rows, *self.image_shape))
        lo, hi = self._local_span()
        spill_rows(mm, source, lo, hi, self.page_rows)
        del mm
        # Read-only from here on: the paging tier never writes the pool.
        self._mm = np.memmap(self.path, dtype=self.dtype, mode="r",
                             shape=(self.n_rows, *self.image_shape))
        get_logger().info(
            f"Disk pool at {self.path}: {hi - lo}/{self.n_rows} rows "
            f"spilled ({(hi - lo) * self.row_bytes / 1e9:.2f} GB on "
            f"disk), page block {self.page_rows} rows, host cache "
            f"budget {self.host_cache_bytes / 1e6:.0f} MB")

    def _local_span(self) -> Tuple[int, int]:
        if self.local_rows is None:
            return 0, self.n_rows
        return (int(self.local_rows.start or 0),
                int(self.n_rows if self.local_rows.stop is None
                    else self.local_rows.stop))

    # -- the paging path ---------------------------------------------------

    def gather(self, idxs: np.ndarray) -> np.ndarray:
        """Rows for ``idxs``, paged through the block cache.  Exactly
        the bytes the spilled array held at those indices."""
        idxs = np.asarray(idxs, dtype=np.int64)
        out = np.empty((len(idxs), *self.image_shape), dtype=self.dtype)
        if len(idxs) == 0:
            return out
        lo, hi = self._local_span()
        if int(idxs.min()) < lo or int(idxs.max()) >= hi:
            raise IndexError(
                f"disk pool gather outside this process's rows "
                f"[{lo}, {hi}): [{int(idxs.min())}, {int(idxs.max())}] "
                "— multi-host paged reads must stay process-local")
        block_ids = idxs // self.page_rows
        for b in np.unique(block_ids):
            blk = self._block(int(b))
            sel = block_ids == b
            out[sel] = blk[idxs[sel] - int(b) * self.page_rows]
        return out

    def _block(self, b: int) -> np.ndarray:
        """One cached block, paging it in (under the read RetryPolicy)
        on miss and evicting LRU blocks past the host-cache budget."""
        with self._lock:
            blk = self._blocks.get(b)
            if blk is not None:
                self._stats["hits"] += 1
                if self._lru and self._lru[-1] != b:
                    self._lru.remove(b)
                    self._lru.append(b)
                return blk
            self._stats["misses"] += 1
        t0 = time.perf_counter()
        blk = _PAGE_RETRY.call(self._load_block, b)
        dt_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            if b not in self._blocks:
                self._blocks[b] = blk
                self._lru.append(b)
                self._cache_bytes += blk.nbytes
                while (self._cache_bytes > self.host_cache_bytes
                        and len(self._lru) > 1):
                    cold = self._lru.pop(0)
                    self._cache_bytes -= self._blocks.pop(cold).nbytes
            self._stats["rows_paged_in"] += blk.shape[0]
            self._stats["page_in_time_s"] += dt_ms / 1e3
            self._stats["max_read_rows"] = max(
                self._stats["max_read_rows"], blk.shape[0])
            self._stats["peak_cache_bytes"] = max(
                self._stats["peak_cache_bytes"], self._cache_bytes)
            if len(self._stalls) < _STALL_SAMPLES_MAX:
                self._stalls.append(dt_ms)
        return blk

    def _load_block(self, b: int) -> np.ndarray:
        """Read block ``b`` off the disk extent into fresh host memory.
        Two bounded half-reads with the torn fault point between: a
        fault mid-block surfaces BEFORE anything enters the cache — a
        torn read can never serve rows (the page_read chaos contract)."""
        faults.site("page_read")
        lo = b * self.page_rows
        hi = min(lo + self.page_rows, self._local_span()[1])
        blk = np.empty((hi - lo, *self.image_shape), dtype=self.dtype)
        mid = (lo + hi) // 2
        blk[: mid - lo] = self._mm[lo:mid]
        faults.site("page_read", point="torn")
        blk[mid - lo:] = self._mm[mid:hi]
        return blk

    # -- telemetry ---------------------------------------------------------

    def take_round_stats(self) -> Dict[str, Optional[float]]:
        """Per-round paging gauges (satellite of §16): absolute disk
        rows, the round's cache hit fraction and page-in bandwidth, and
        stall percentiles — counters and samples reset on read so each
        round reports its own window."""
        with self._lock:
            s = dict(self._stats)
            stalls = self._stalls
            self._stalls = []
            for k in ("hits", "misses", "rows_paged_in"):
                self._stats[k] = 0
            self._stats["page_in_time_s"] = 0.0
        total = s["hits"] + s["misses"]
        lo, hi = self._local_span()
        out: Dict[str, Optional[float]] = {
            "pool_disk_rows": float(hi - lo),
            "pool_cache_hit_frac": (s["hits"] / total) if total else None,
            "page_in_rows_per_sec": (
                s["rows_paged_in"] / s["page_in_time_s"]
                if s["page_in_time_s"] > 0 else None),
            "page_in_stall_ms_p50": (
                float(np.percentile(stalls, 50)) if stalls else None),
            "page_in_stall_ms_p99": (
                float(np.percentile(stalls, 99)) if stalls else None),
        }
        return out

    def spy_counters(self) -> Dict[str, int]:
        """Cumulative honesty counters for the no-full-materialization
        spy test: the largest single read and the cache's peak bytes —
        both must stay far below the pool."""
        with self._lock:
            return {"max_read_rows": self._stats["max_read_rows"],
                    "peak_cache_bytes": self._stats["peak_cache_bytes"]}


class DiskPool(Dataset):
    """A ``Dataset`` view over one ``_DiskPoolCore`` — the disk-backed
    twin of ``ArrayDataset``; ``with_view`` shares the core exactly like
    ``ArrayDataset.with_view`` shares the array.  Targets stay in RAM
    (int64 [N] — a few MB at 100M rows) so label bookkeeping, class
    counts, and the pool-state machinery never touch disk."""

    # Feed/residency gates read this to admit paged pools to the
    # epoch-scan path (trainer.resolve_train_feed).
    paged_backend = True

    def __init__(self, core: _DiskPoolCore, targets: np.ndarray,
                 num_classes: int, view: ViewSpec):
        self._core = core
        self.targets = np.asarray(targets, dtype=np.int64)
        self.num_classes = int(num_classes)
        self.view = view
        self.image_shape = core.image_shape

    def __len__(self) -> int:
        return self._core.n_rows

    @property
    def images(self):
        """Deliberately absent: the whole-pool array never exists on
        one host.  Raising AttributeError (not returning the memmap!)
        routes every ``getattr(ds, "images", None)`` residency/feed
        gate to the streaming paths — the DecodedPoolCache contract."""
        raise AttributeError(
            "a DiskPool has no whole-pool images array; read through "
            "gather() (the paged path)")

    def gather(self, idxs: np.ndarray) -> np.ndarray:
        return self._core.gather(idxs)

    def with_view(self, view: ViewSpec) -> "DiskPool":
        return DiskPool(self._core, self.targets, self.num_classes, view)

    # Telemetry pass-throughs (the driver reads them off the al_set).
    def take_round_stats(self) -> Dict[str, Optional[float]]:
        return self._core.take_round_stats()

    def spy_counters(self) -> Dict[str, int]:
        return self._core.spy_counters()


def host_ram_bytes() -> int:
    """Physical host RAM (0 when the platform cannot say — callers then
    never auto-select the disk tier)."""
    try:
        return (os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE"))
    except (ValueError, OSError, AttributeError):
        return 0


def resolve_pool_backend(backend: str, pool_bytes: int,
                         watermark_frac: float = 0.5) -> str:
    """The ONE rule for which pool backend a run gets: explicit
    ``memory``/``disk`` win; ``auto`` takes the disk tier only when the
    pool would cross the host-RAM watermark (a pool comfortably in RAM
    pays nothing for the paging layer it doesn't need)."""
    if backend not in ("auto", "memory", "disk"):
        raise ValueError(f"pool_backend={backend!r} is not one of "
                         "auto/memory/disk")
    if backend != "auto":
        return backend
    ram = host_ram_bytes()
    if ram > 0 and pool_bytes > ram * float(watermark_frac):
        return "disk"
    return "memory"


def wrap_pool(train_set, al_set, directory: str, page_rows: int = 2048,
              host_cache_bytes: int = 1 << 30,
              local_rows: Optional[slice] = None
              ) -> Tuple[DiskPool, DiskPool]:
    """Spill the (shared-storage) train/al dataset pair onto the disk
    tier and return two ``DiskPool`` views over ONE core — after this
    the in-memory images array has no live reference in the experiment
    stack and the pool pages from disk for the rest of the run."""
    images = getattr(train_set, "images", None)
    if not isinstance(images, np.ndarray):
        raise ValueError("pool_backend=disk needs an in-memory or "
                         "memmap source pool to spill")
    core = _DiskPoolCore(
        os.path.join(directory, "pool_rows.u8"), len(train_set),
        train_set.image_shape, dtype=images.dtype, page_rows=page_rows,
        host_cache_bytes=host_cache_bytes, local_rows=local_rows)
    core.create(images)
    train_dp = DiskPool(core, train_set.targets, train_set.num_classes,
                        train_set.view)
    al_dp = DiskPool(core, al_set.targets, al_set.num_classes,
                     al_set.view)
    return train_dp, al_dp
