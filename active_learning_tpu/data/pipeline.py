"""Host-side batching pipeline.

Replaces the reference's torch DataLoader + DistributedSampler stack
(strategy.py:308-328): here the "sampler" is explicit index math, batches
are fixed-shape (the last batch is padded and masked — XLA wants static
shapes), and a background prefetcher overlaps host gather/decode with device
compute (the reference's num_workers/prefetch_factor,
arg_pools/default.py:29-38).

Every batch carries the example indices, preserving the reference's
``(x, y, index)`` dataset contract (custom_cifar10.py:23-25) that lets
acquisition scores map back to pool indices.
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterator, Optional

import numpy as np

from .core import Dataset


def batch_index_lists(idxs: np.ndarray, batch_size: int,
                      shuffle: bool = False,
                      rng: Optional[np.random.Generator] = None,
                      drop_last: bool = False):
    """Split ``idxs`` into per-batch index arrays."""
    idxs = np.asarray(idxs)
    if shuffle:
        if rng is None:
            raise ValueError("shuffle=True requires an explicit rng")
        idxs = rng.permutation(idxs)
    n = len(idxs)
    if drop_last:
        n = (n // batch_size) * batch_size
    return [idxs[i:i + batch_size] for i in range(0, n, batch_size)]


def padded_batch_layout(batch_idxs: np.ndarray, batch_size: int):
    """The deterministic global row layout of one fixed-shape batch:
    (padded index array, validity mask).  Padding rows repeat the batch's
    first example (mask 0.0), so every process computes the identical
    layout from the same index math — no cross-host coordination."""
    idxs = np.asarray(batch_idxs)
    actual = len(idxs)
    mask = np.ones(batch_size, dtype=np.float32)
    if actual < batch_size:
        idxs = np.concatenate(
            [idxs, np.repeat(idxs[:1], batch_size - actual)], axis=0)
        mask[actual:] = 0.0
    return idxs, mask


def space_to_depth(images: np.ndarray, block: int = 2) -> np.ndarray:
    """Host-side space-to-depth: uint8 [B, H, W, C] -> [B, H/b, W/b,
    b*b*C], channel index (di*b + dj)*C + c — the SAME layout contract as
    the device-side models/resnet.space_to_depth and the s2d stem's folded
    kernel (s2d_stem_kernel).  Byte count is unchanged (the h2d transfer
    costs the same); doing it here keeps the layout shuffle off the
    accelerator step for streamed disk datasets."""
    b, h, w, c = images.shape
    x = images.reshape(b, h // block, block, w // block, block, c)
    return np.ascontiguousarray(
        x.transpose(0, 1, 3, 2, 4, 5)).reshape(
            b, h // block, w // block, block * block * c)


def gather_batch(dataset: Dataset, batch_idxs: np.ndarray,
                 batch_size: int,
                 local: Optional[slice] = None,
                 s2d: bool = False) -> Dict[str, np.ndarray]:
    """Gather one fixed-shape batch: uint8 images + labels + pool indices +
    validity mask (0.0 on padding rows).

    ``local`` restricts the EXPENSIVE work (image gather/decode) to the
    given row range of the global batch — the per-host slice of a
    multi-host mesh (parallel/mesh.py process_local_rows, the reference's
    DistributedSampler rank slicing strategy.py:312-314).  The default
    gathers every row."""
    idxs, mask = padded_batch_layout(batch_idxs, batch_size)
    if local is not None:
        idxs, mask = idxs[local], mask[local]
    # Real rows are a prefix (the global mask is monotone, so any slice of
    # it is too); pad rows all repeat one index — decode it once.  For
    # disk datasets the decode is deterministic per (seed, epoch, index),
    # so the repeat is identical to re-gathering.
    n_real = int(mask.sum())
    images = dataset.gather(idxs[:n_real])
    if n_real < len(idxs):
        pad_img = dataset.gather(idxs[n_real:n_real + 1])
        images = np.concatenate(
            [images, np.repeat(pad_img, len(idxs) - n_real, axis=0)], axis=0)
    labels = dataset.targets[idxs]
    if s2d:
        images = space_to_depth(images)
    return {"image": images, "label": labels.astype(np.int32),
            "index": np.asarray(idxs, dtype=np.int32), "mask": mask}


def iterate_batches(
    dataset: Dataset,
    idxs: np.ndarray,
    batch_size: int,
    shuffle: bool = False,
    rng: Optional[np.random.Generator] = None,
    drop_last: bool = False,
    prefetch: int = 2,
    num_threads: int = 0,
    local: Optional[slice] = None,
    s2d: bool = False,
) -> Iterator[Dict[str, np.ndarray]]:
    """Yield fixed-shape host batches; with ``num_threads > 0``, N worker
    threads gather/decode batches concurrently and results are reassembled
    IN ORDER (the reference's num_workers DataLoader processes,
    arg_pools/default.py:29-38).  Concurrency matters for disk-backed
    datasets where ``gather`` decodes JPEGs; in-flight work is bounded by
    ``num_threads + prefetch`` batches."""
    batches = batch_index_lists(idxs, batch_size, shuffle=shuffle, rng=rng,
                                drop_last=drop_last)
    if num_threads <= 0:
        for b in batches:
            yield gather_batch(dataset, b, batch_size, local=local, s2d=s2d)
        return

    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    executor = ThreadPoolExecutor(max_workers=num_threads,
                                  thread_name_prefix="al-gather")
    try:
        pending: deque = deque()
        it = iter(batches)
        max_inflight = num_threads + max(1, prefetch)
        for b in itertools.islice(it, max_inflight):
            pending.append(executor.submit(gather_batch, dataset, b,
                                           batch_size, local=local, s2d=s2d))
        while pending:
            batch = pending.popleft().result()  # ordered; errors propagate
            nxt = next(it, None)
            if nxt is not None:
                pending.append(executor.submit(gather_batch, dataset, nxt,
                                               batch_size, local=local,
                                               s2d=s2d))
            yield batch
    finally:
        executor.shutdown(wait=False, cancel_futures=True)


def train_feed_batches(
    dataset: Dataset,
    idxs: np.ndarray,
    batch_size: int,
    rng: Optional[np.random.Generator] = None,
    shuffle: bool = True,
    num_workers: int = 0,
    prefetch: int = 2,
    local: Optional[slice] = None,
    s2d: bool = False,
    put=None,
    depth: int = 2,
):
    """The prefetched-host train feed: ``num_workers`` gather/decode
    threads (driving the native/decode.cpp thread-pool decoder for disk
    trees and the memmap cache for decoded pools) assemble fixed-shape
    batches IN ORDER, and — when ``put`` is given — the double-buffered
    ``device_prefetch`` stage dispatches batch n+1's host->device
    transfer while batch n computes, ``depth`` batches deep.

    This is the host leg of the trainer's feed hierarchy
    (resident-gather > prefetched-host > serial-host): batch membership
    and order are EXACTLY ``iterate_batches(shuffle=True)``'s, so the
    stream is bit-identical to the serial loop at the same rng state —
    workers and prefetch change wall-clock only, never a pixel.  It is
    also the reference's DataLoader ``num_workers``/``prefetch_factor``
    counterpart (arg_pools/default.py:29-38) for the train loader.
    """
    batches = iterate_batches(dataset, idxs, batch_size, shuffle=shuffle,
                              rng=rng, num_threads=num_workers,
                              prefetch=prefetch, local=local, s2d=s2d)
    if put is None:
        return batches
    from .cache import device_prefetch
    return device_prefetch(batches, put, depth=max(1, depth))


def num_batches(n: int, batch_size: int, drop_last: bool = False) -> int:
    return n // batch_size if drop_last else -(-n // batch_size)
