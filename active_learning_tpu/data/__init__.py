"""Data layer: dataset registry + triple factory.

``get_data`` mirrors src/data_utils/top_level_data_utils.py:7-19 (name ->
(train_set, test_set, al_set)), dispatching through the DATASETS registry
instead of an if/elif chain.
"""

from ..registry import DATASETS
from .core import (ArrayDataset, CIFAR10_NORM, Dataset, IMAGENET_NORM,
                   Normalization, ViewSpec)

# Register datasets.
from . import cifar10 as _cifar10  # noqa: F401
from . import imbalance as _imbalance  # noqa: F401
from . import synthetic as _synthetic  # noqa: F401
from . import imagenet as _imagenet  # noqa: F401


def get_data(data_name: str, data_path=None, debug_mode: bool = False,
             imbalance_args=None, **kwargs):
    factory = DATASETS.get(data_name)
    return factory(data_path, debug_mode=debug_mode,
                   imbalance_args=imbalance_args, **kwargs)
