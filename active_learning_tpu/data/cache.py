"""Decode-once caches for deterministic dataset views.

Two tiers, both exact because the al/val/test views are deterministic —
``gather(i)`` is time-invariant (data/imagenet.py val transform,
independent of ``set_epoch``):

  * ``CachedEvalRows`` — RAM, per-round: the per-epoch validation loop
    re-reads the SAME eval rows every epoch (reference: a fresh
    DataLoader pass over the val subset per epoch, strategy.py:383-398);
    decode each once per round instead.
  * ``DecodedPoolCache`` — disk memmap, per-EXPERIMENT: acquisition
    scoring re-reads the WHOLE unlabeled pool every round, and on
    ImageNet-scale trees the JPEG decode is ~30x slower than the
    device's scoring rate (bench: 1,048 img/s/core decode vs 9,742
    img/s/chip scoring, h2d ceiling 3,133 img/s).  Each row is decoded
    exactly once for the life of the cache file and every later round
    (and validation, and the test set) streams uint8 rows at disk/page-
    cache speed.  The reference re-decodes per epoch via DataLoader
    workers (src/query_strategies/strategy.py:325-328).

Memory/disk bounding: CachedEvalRows admits rows until ``max_bytes`` of
RAM; DecodedPoolCache refuses to build at all (factory returns the
dataset unwrapped) when the FULL pool would exceed its byte budget —
the scoring pass touches every row, so a partial disk cache would still
thrash.  Admitted RAM rows are COPIES, never views into a gathered batch
— a view would pin the whole batch while the byte accounting counted one
row.  Thread-safe: the pipelines gather batches from ``num_workers``
threads concurrently (data/pipeline.py); RAM-cache bookkeeping is under
a lock, and the memmap tier writes disjoint rows (row data first, THEN
the valid flag, so a crash mid-write re-decodes instead of serving a
torn row).  On a multi-host mesh each process caches its own rows in its
own file (no cross-process file locking needed).
"""

from __future__ import annotations

import glob
import hashlib
import json
import mmap
import os
import threading
from typing import Dict, Optional

import numpy as np

from .core import Dataset
from ..utils.logging import get_logger


def _msync_range(arr: np.ndarray, lo_byte: int, hi_byte: int) -> bool:
    """msync only the pages covering bytes [lo_byte, hi_byte) of a
    memmap-backed array; returns False when the mmap backing cannot be
    found (the caller falls back to a full flush).  numpy's
    ``memmap.flush`` has no range form, so a per-batch whole-mapping
    flush would sweep the entire multi-GB mapping from every writer."""
    mm = arr
    while mm is not None and not isinstance(mm, mmap.mmap):
        mm = getattr(mm, "base", None)
    if mm is None:
        return False
    gran = mmap.ALLOCATIONGRANULARITY
    start = lo_byte // gran * gran
    end = min(len(mm), -(-hi_byte // gran) * gran)
    if end > start:
        mm.flush(start, end - start)
    return True


class CachedEvalRows:
    """Wrap a dataset whose active view is deterministic; same gather
    contract, rows served from memory after first decode.

    Only sound for augmentation-free views — wrapping a train view would
    freeze the first epoch's crops forever, so callers gate on the view.
    """

    def __init__(self, dataset: Dataset, max_bytes: int = 4 << 30):
        self.dataset = dataset
        self.view = dataset.view
        self.targets = dataset.targets
        self.num_classes = dataset.num_classes
        # Proxied so Trainer.eval_batch_size sees the row size through the
        # wrapper — the scoring and validation passes share one batch-floor
        # policy, and a wrapper hiding image_shape would silently drop the
        # eval pass to the conservative unknown-shape floor.
        self.image_shape = dataset.image_shape
        self._rows: Dict[int, np.ndarray] = {}
        self._bytes = 0
        self._max_bytes = int(max_bytes)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.dataset)

    def gather(self, idxs: np.ndarray) -> np.ndarray:
        idxs = np.asarray(idxs)
        if len(idxs) == 0:
            # Preserve the wrapped dataset's empty-gather shape contract
            # (a multi-host last batch can leave a process zero real rows).
            return self.dataset.gather(idxs)
        with self._lock:
            missing = sorted({int(i) for i in idxs} - self._rows.keys())
        fetched: Dict[int, np.ndarray] = {}
        if missing:
            rows = self.dataset.gather(np.asarray(missing, dtype=np.int64))
            with self._lock:
                for i, row in zip(missing, rows):
                    fetched[i] = row
                    if (i not in self._rows
                            and self._bytes + row.nbytes <= self._max_bytes):
                        self._rows[i] = row.copy()
                        self._bytes += row.nbytes
        out = []
        with self._lock:
            for j in idxs:
                i = int(j)
                row = self._rows.get(i)
                out.append(row if row is not None else fetched[i])
        return np.stack(out)


class DecodedPoolCache:
    """Disk-memmap decode-once cache over a deterministic-view disk
    dataset: uint8 [N, H, W, C] rows written on first gather, valid flags
    set AFTER the row bytes (torn writes re-decode, never serve).  The
    backing file is sparse — disk usage grows with rows actually decoded.

    Persistent across processes and experiments: the file name carries a
    fingerprint of (paths, image/resize size, row shape), so a changed
    tree or transform gets a fresh cache instead of stale rows.  Build
    via ``maybe_wrap_decoded`` (returns the dataset unwrapped when
    ineligible).  Attribute access falls through to the wrapped dataset
    (``paths``, ``targets``, ``image_shape``, ...), so downstream gates
    like the trainer's eval-cache check keep working.
    """

    # Basenames of caches live in THIS process (the al pool and the test
    # set legitimately share a directory): eviction must never take them.
    _IN_USE: set = set()

    def __init__(self, dataset, cache_dir: str,
                 signature: Optional[str] = None):
        self.dataset = dataset
        n = len(dataset)
        shape = (n, *dataset.image_shape)
        os.makedirs(cache_dir, exist_ok=True)
        # The signature stats every image file; callers that already
        # computed it (maybe_wrap_decoded's eviction pass) hand it in so
        # an ImageNet-scale tree pays the ~1.3M-stat sweep once, not
        # twice.
        sig = signature or self._signature(dataset)
        # Per-process files on pods: each process gathers only its own
        # rows; sharing one file over NFS would need row-range locking.
        proc = 0
        try:
            import jax
            proc = jax.process_index()
        except Exception:
            pass
        base = os.path.join(cache_dir, f"decoded_{sig}_p{proc}")
        self._data_path = base + ".u8"
        self._valid_path = base + ".valid"
        meta_path = base + ".json"
        fresh = not (os.path.exists(self._data_path)
                     and os.path.exists(self._valid_path)
                     and os.path.exists(meta_path))
        if fresh:
            # Sparse-create both files, meta last (its presence marks the
            # pair usable).
            for path, nbytes in ((self._data_path, int(np.prod(shape))),
                                 (self._valid_path, n)):
                with open(path + ".tmp", "wb") as fh:
                    fh.truncate(nbytes)
                os.replace(path + ".tmp", path)
            with open(meta_path + ".tmp", "w") as fh:
                json.dump({"shape": shape, "signature": sig}, fh)
            os.replace(meta_path + ".tmp", meta_path)
        DecodedPoolCache._IN_USE.add(base)
        self._rows = np.memmap(self._data_path, dtype=np.uint8, mode="r+",
                               shape=shape)
        self._valid = np.memmap(self._valid_path, dtype=np.uint8, mode="r+",
                                shape=(n,))
        have = int(np.count_nonzero(self._valid))
        get_logger().info(
            f"Decoded-pool cache at {base}.u8: {have}/{n} rows present "
            f"({'resumed' if not fresh else 'new'}, "
            f"{np.prod(shape) / 1e9:.1f} GB full size, sparse)")

    @staticmethod
    def _signature(dataset) -> str:
        h = hashlib.sha1()
        h.update(str(getattr(dataset, "image_size", "")).encode())
        h.update(str(getattr(dataset, "resize_size", "")).encode())
        h.update(str(len(dataset)).encode())
        for p in dataset.paths[: len(dataset)]:
            h.update(p.encode())
            # Size+mtime per file: images re-encoded IN PLACE at the same
            # paths must produce a fresh cache, not stale pixels.  One
            # stat per file costs seconds even at ImageNet scale, paid
            # once per cache construction.
            try:
                st = os.stat(p)
                h.update(f"|{st.st_size}|{st.st_mtime_ns}".encode())
            except OSError:
                h.update(b"|missing")
        return h.hexdigest()[:16]

    def __len__(self) -> int:
        return len(self.dataset)

    @property
    def images(self):
        """The decoded pool as one uint8 array — exposed ONLY once every
        row is decoded.  A fully-populated cache thereby becomes eligible
        for the device-resident paths (parallel/resident.py:eligible):
        when ``resident_scoring_bytes`` covers the pool, rounds 1+ score
        via on-device gathers instead of host->device streaming.  While
        partial, AttributeError (falling through to the wrapped dataset,
        which has no ``images``): a half-empty memmap must never be
        uploaded as real data."""
        if int(np.count_nonzero(self._valid)) != len(self.dataset):
            raise AttributeError("decoded pool not fully populated")
        return self._rows

    def __getattr__(self, name):
        # Only called for attributes NOT set on self: view/targets/paths/
        # image_shape/num_classes/train_transform all resolve through the
        # wrapped dataset, staying live if it mutates.
        if name == "dataset":  # unpickling guard: no silent recursion
            raise AttributeError(name)
        return getattr(self.dataset, name)

    def gather(self, idxs: np.ndarray) -> np.ndarray:
        idxs = np.asarray(idxs, dtype=np.int64)
        if len(idxs) == 0:
            return self.dataset.gather(idxs)
        valid = self._valid[idxs] != 0
        if not valid.all():
            missing = np.unique(idxs[~valid])
            rows = self.dataset.gather(missing)
            self._rows[missing] = rows
            # Row bytes DURABLY first (msync — paid only on the
            # populating pass, where JPEG decode dominates), THEN the
            # flags: without the flush the kernel may persist a flag page
            # before its row page, and a system crash would leave valid=1
            # over zero bytes — served as a real image for the rest of
            # the cache's life.  With it, a crash at any point costs a
            # re-decode, never a torn row.
            self._flush_row_range(int(missing[0]), int(missing[-1]) + 1)
            self._valid[missing] = 1
        return np.asarray(self._rows[idxs])

    def _flush_row_range(self, lo: int, hi: int) -> None:
        """msync only the pages covering rows [lo, hi): the populating
        pass writes contiguous batches, and a whole-mapping flush per
        batch would sweep the entire multi-GB mapping from every
        pipeline thread (see ``_msync_range``)."""
        row_bytes = int(self._rows.strides[0])
        if not _msync_range(self._rows, lo * row_bytes, hi * row_bytes):
            self._rows.flush()  # unexpected backing; full msync

    def flush(self) -> None:
        self._rows.flush()
        self._valid.flush()


class GrowableRowStore:
    """A row array in one disk file whose capacity grows by
    ``pool.bucket_size``-aligned extents — the backing tier of the
    streaming subsystem's growable candidate pool
    (active_learning_tpu/stream/store.py).

    Why extent-aligned: everything downstream that compiles against the
    array's LEADING dimension (the resident-pool upload and its jitted
    gather runners, parallel/resident.py) sees only capacities from the
    same enumerable shape ladder the trainer and k-center already bucket
    on — so a pool that grows row by row recompiles at most once per
    bucket boundary, never once per append (pinned in
    tests/test_compile_reuse.py).

    Durability model: this file is DERIVED state by default.  The
    streaming subsystem's source of truth is the fsync'd ingest WAL
    (stream/wal.py); the store is rebuilt from base data + WAL replay at
    every service start, so the store itself needs no write atomicity —
    creation is still tmp+rename (a half-created file never masquerades
    as a store) and growth is a plain ftruncate, which keeps every
    EXISTING mapping valid (mappings cover the old length; only new
    pages appear).  ``rows`` is re-mapped only when capacity grows, so
    ``id(store.rows)`` is stable within a capacity epoch — exactly the
    identity the resident cache keys on.

    ``reuse=True`` opts a caller into keeping an existing file instead:
    the WAL-compaction path (stream/store.py) promotes the store to a
    sealed disk extent whose prefix IS durable truth (rows a pruned WAL
    segment can no longer rebuild).  The file is kept only when its
    size is a whole number of rows covering the requested capacity —
    any such size was produced by this class's own bucketed ftruncates,
    so the capacity stays on the bucket ladder; anything else falls back
    to the fresh-create path (``reused`` tells the caller which
    happened, i.e. whether the prefix contents can be trusted).
    """

    def __init__(self, path: str, row_shape, dtype=np.uint8,
                 capacity: int = 0, extent_floor: int = 256,
                 reuse: bool = False):
        from ..pool import bucket_size

        self._bucket = lambda n: bucket_size(max(int(n), 1),
                                             floor=int(extent_floor))
        self.path = path
        self.row_shape = tuple(int(d) for d in row_shape)
        self.dtype = np.dtype(dtype)
        self._row_bytes = int(np.prod(self.row_shape, dtype=np.int64)
                              or 1) * self.dtype.itemsize
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.capacity = self._bucket(capacity)
        # Written-interval tracking: flush() syncs only the dirty byte
        # range (satellite of the §16 disk tier — the whole-file
        # memmap.flush here used to sweep the full multi-GB mapping on
        # every seal).
        self._dirty: Optional[tuple] = None
        self.reused = False
        if reuse and os.path.exists(path):
            size = os.path.getsize(path)
            if (size >= self.capacity * self._row_bytes
                    and size % self._row_bytes == 0):
                self.capacity = size // self._row_bytes
                self.reused = True
        if not self.reused:
            # Fresh every construction: the store is derived (see
            # docstring), and reusing a stale file would let a crashed
            # run's rows shadow the WAL replay about to rebuild them.
            with open(path + ".tmp", "wb") as fh:
                fh.truncate(self.capacity * self._row_bytes)
            os.replace(path + ".tmp", path)
        self.rows = self._map()

    def _map(self) -> np.ndarray:
        return np.memmap(self.path, dtype=self.dtype, mode="r+",
                         shape=(self.capacity, *self.row_shape))

    def ensure_capacity(self, n_rows: int) -> bool:
        """Grow (sparse ftruncate) to the bucket enclosing ``n_rows``;
        returns True when capacity actually changed (the caller's cue to
        refresh snapshots / re-pin resident uploads)."""
        want = self._bucket(n_rows)
        if want <= self.capacity:
            return False
        os.truncate(self.path, want * self._row_bytes)
        self.capacity = want
        self.rows = self._map()
        return True

    def note_written(self, lo: int, hi: int) -> None:
        """Record rows [lo, hi) as written since the last flush; the
        next ``flush`` syncs only the union of noted intervals."""
        if hi <= lo:
            return
        if self._dirty is None:
            self._dirty = (int(lo), int(hi))
        else:
            self._dirty = (min(self._dirty[0], int(lo)),
                           max(self._dirty[1], int(hi)))

    def flush(self) -> None:
        """Sync the written row range to disk — a no-op when nothing
        was written since the last flush, and never a whole-file sweep
        for a small append (the data/cache.py flush-granularity fix)."""
        if self._dirty is None:
            return
        lo, hi = self._dirty
        hi = min(hi, self.capacity)
        if hi > lo and not _msync_range(self.rows, lo * self._row_bytes,
                                        hi * self._row_bytes):
            self.rows.flush()  # unexpected backing; full msync
        self._dirty = None


def device_prefetch(batches, put, depth: int = 2):
    """Async double-buffered host->device feed: a background thread pulls
    host batches from ``batches`` and calls ``put`` (e.g.
    mesh.shard_batch — jax device transfers are async-dispatch, so the
    h2d of batch n+1 is in flight while batch n computes), yielding
    device batches IN ORDER from a queue bounded at ``depth``.

    This is the residency fallback for pools too big for HBM
    (strategies/scoring.collect_pool): without it the host path serializes
    gather -> transfer -> dispatch per batch, so query time is the SUM of
    host and device time; with it the pass is bounded by max(host feed,
    PCIe, device).  ``depth`` bounds in-flight device batches so the
    prefetcher can never race a whole pool into HBM.  Errors from the
    feeder thread re-raise at the consuming ``next()``; an abandoned
    generator unblocks and joins the thread on close().
    """
    import queue
    import threading

    from .. import faults

    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    DONE, ERROR = object(), object()

    def feed():
        try:
            for batch in batches:
                # Fault point for the feeder thread (raise AND thread
                # death land here): either way the BaseException forward
                # below delivers it to the consuming ``next()``, which
                # fails the PASS, never hangs it — callers retry the
                # whole pass (Strategy.collect_scores) or ride the
                # driver's round-retry ladder (the train feed).
                faults.site("feed_worker")
                item = put(batch)
                while not stop.is_set():
                    try:
                        q.put((None, item), timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            q.put((DONE, None))
        except BaseException as e:  # noqa: BLE001 - re-raised at consumer
            q.put((ERROR, e))

    t = threading.Thread(target=feed, name="al-device-prefetch",
                         daemon=True)
    t.start()
    try:
        while True:
            tag, item = q.get()
            if tag is DONE:
                return
            if tag is ERROR:
                raise item
            yield item
    finally:
        stop.set()
        while True:  # drain so the feeder's put() can't deadlock join
            try:
                q.get_nowait()
            except Exception:
                break
        t.join(timeout=5.0)


def maybe_wrap_decoded(dataset, cache_dir: Optional[str],
                       max_bytes: int) -> "Dataset":
    """Wrap ``dataset`` in a DecodedPoolCache when it is a disk-backed
    deterministic view whose FULL decoded pool fits ``max_bytes`` (the
    scoring pass touches every row, so a partial cache would thrash);
    otherwise return it unchanged.  Never raises: cache construction
    failures (unwritable dir, full disk) log and fall through."""
    if not cache_dir or max_bytes <= 0:
        return dataset
    if not hasattr(dataset, "paths") or getattr(dataset, "train_transform",
                                                False):
        return dataset
    full = len(dataset) * int(np.prod(dataset.image_shape))
    if full > max_bytes:
        get_logger().info(
            f"Decoded-pool cache disabled: full pool is {full / 1e9:.1f} GB "
            f"> budget {max_bytes / 1e9:.1f} GB")
        return dataset
    try:
        sig = DecodedPoolCache._signature(dataset)
        _evict_stale_caches(cache_dir, full, max_bytes, keep_sig=sig)
        return DecodedPoolCache(dataset, cache_dir, signature=sig)
    except OSError as e:
        get_logger().warning(f"Decoded-pool cache unavailable ({e!r}); "
                             "continuing undecached")
        return dataset


def _evict_stale_caches(cache_dir: str, need_bytes: int, max_bytes: int,
                        keep_sig: str) -> None:
    """Old cache triples (from re-encoded trees, other datasets, dead
    experiments) would otherwise accumulate in the shared persistent dir
    forever; before building a new cache, delete the least-recently-used
    ones until existing + need fits the byte budget.  Allocated (sparse)
    sizes are what count; in-process caches and the current signature's
    files are never taken."""
    groups: Dict[str, list] = {}
    for path in glob.glob(os.path.join(cache_dir, "decoded_*")):
        base = path.rsplit(".", 1)[0]
        groups.setdefault(base, []).append(path)
    entries = []
    total = 0
    for base, paths in groups.items():
        if keep_sig in os.path.basename(base) \
                or base in DecodedPoolCache._IN_USE:
            continue
        try:
            stats = [os.stat(p) for p in paths]
        except OSError:
            continue
        alloc = sum(s.st_blocks * 512 for s in stats)
        entries.append((max(s.st_mtime for s in stats), alloc, paths))
        total += alloc
    entries.sort()  # oldest first
    for mtime, alloc, paths in entries:
        if total + need_bytes <= max_bytes:
            break
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass
        total -= alloc
        get_logger().info(
            f"Evicted stale decoded cache {paths[0].rsplit('.', 1)[0]} "
            f"({alloc / 1e9:.1f} GB)")
