"""Decode-once cache for deterministic dataset views.

The per-epoch validation loop re-reads the SAME eval rows every epoch
(reference: a fresh DataLoader pass over the val subset per epoch,
strategy.py:383-398).  For in-memory datasets that is a cheap array
gather, but for disk-backed ImageNet it is thousands of JPEG
decode+resize operations repeated up to n_epoch times per round.  The
al/test views are deterministic — ``gather(i)`` is time-invariant
(data/imagenet.py val transform, independent of ``set_epoch``) — so the
decoded uint8 rows can be cached after the first epoch.

Memory-bounded: rows are cached until ``max_bytes`` is reached; rows past
the budget fall through to the wrapped dataset every time, so a too-large
eval split degrades to the uncached behavior instead of exhausting host
RAM.  Admitted rows are COPIES, never views into a gathered batch — a
view would pin the whole batch while the byte accounting counted one row.
Thread-safe: the eval pipeline gathers batches from ``num_workers``
threads concurrently (data/pipeline.py), so all cache bookkeeping is
under a lock (decode itself runs outside it; a duplicate concurrent
decode of the same deterministic row is benign).  On a multi-host mesh
each process only ever gathers (and therefore caches) its own rows.
"""

from __future__ import annotations

import threading
from typing import Dict

import numpy as np

from .core import Dataset


class CachedEvalRows:
    """Wrap a dataset whose active view is deterministic; same gather
    contract, rows served from memory after first decode.

    Only sound for augmentation-free views — wrapping a train view would
    freeze the first epoch's crops forever, so callers gate on the view.
    """

    def __init__(self, dataset: Dataset, max_bytes: int = 4 << 30):
        self.dataset = dataset
        self.view = dataset.view
        self.targets = dataset.targets
        self.num_classes = dataset.num_classes
        self._rows: Dict[int, np.ndarray] = {}
        self._bytes = 0
        self._max_bytes = int(max_bytes)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.dataset)

    def gather(self, idxs: np.ndarray) -> np.ndarray:
        idxs = np.asarray(idxs)
        if len(idxs) == 0:
            # Preserve the wrapped dataset's empty-gather shape contract
            # (a multi-host last batch can leave a process zero real rows).
            return self.dataset.gather(idxs)
        with self._lock:
            missing = sorted({int(i) for i in idxs} - self._rows.keys())
        fetched: Dict[int, np.ndarray] = {}
        if missing:
            rows = self.dataset.gather(np.asarray(missing, dtype=np.int64))
            with self._lock:
                for i, row in zip(missing, rows):
                    fetched[i] = row
                    if (i not in self._rows
                            and self._bytes + row.nbytes <= self._max_bytes):
                        self._rows[i] = row.copy()
                        self._bytes += row.nbytes
        out = []
        with self._lock:
            for j in idxs:
                i = int(j)
                row = self._rows.get(i)
                out.append(row if row is not None else fetched[i])
        return np.stack(out)
