"""ImageNet-scale disk-backed datasets.

* ``ImageFolderDataset`` — the reference's torchvision ImageFolder pattern
  (src/data_utils/custom_imagenet.py:9-42): class-per-subdirectory layout,
  JPEG decode at access time.
* ``FileListDataset`` — the ImageNet-LT long-tailed variant
  (src/data_utils/custom_imbalanced_imagenet.py:17-46): a text file of
  ``relative/path label`` lines.

Host transforms (decode-time, data-dependent so they can't live in jit):
RandomResizedCrop(224) for the train view, Resize(256)+CenterCrop(224) for
the al/test views (custom_imagenet.py:45-54).  The horizontal flip and
normalization run on device (data/augment.py).  Decoding is parallelized by
the pipeline's prefetch threads and the native batch-gather component.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..registry import DATASETS
from .core import Dataset, IMAGENET_NORM, ViewSpec

_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")


def _require_pil():
    try:
        from PIL import Image  # noqa: F401
        return Image
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "PIL is required for disk-backed image datasets") from e


def random_resized_crop_params(h: int, w: int, rng: np.random.Generator,
                               scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)
                               ) -> Tuple[int, int, int, int]:
    """torchvision RandomResizedCrop.get_params semantics: sample area and
    log-uniform aspect ratio, 10 attempts then center-crop fallback."""
    area = h * w
    log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(scale[0], scale[1])
        aspect = np.exp(rng.uniform(log_ratio[0], log_ratio[1]))
        cw = int(round(np.sqrt(target_area * aspect)))
        ch = int(round(np.sqrt(target_area / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            top = int(rng.integers(0, h - ch + 1))
            left = int(rng.integers(0, w - cw + 1))
            return top, left, ch, cw
    # Fallback: center crop at the closest valid ratio.
    in_ratio = w / h
    if in_ratio < ratio[0]:
        cw, ch = w, int(round(w / ratio[0]))
    elif in_ratio > ratio[1]:
        ch, cw = h, int(round(h * ratio[1]))
    else:
        cw, ch = w, h
    top = (h - ch) // 2
    left = (w - cw) // 2
    return top, left, ch, cw


class _DiskImageDataset(Dataset):
    """Shared decode/transform logic for disk-backed datasets.

    Two decode paths, same transform semantics:
      * native (default): batch JPEG decode + crop + bilinear resize in the
        C++ component (native/decode.cpp) with its own thread pool — crop
        rectangles are still computed here in Python from the per-(seed,
        epoch, index) RNG, so randomness is identical across paths;
      * PIL fallback: per-image decode, used when the native library is
        unavailable or a file isn't a baseline JPEG.
    """

    def __init__(self, paths: List[str], targets: Sequence[int],
                 num_classes: int, view: ViewSpec, train_transform: bool,
                 image_size: int = 224, resize_size: int = 256,
                 limit: Optional[int] = None, seed: int = 0,
                 use_native: bool = True, decode_threads: int = 4):
        self.paths = paths
        self.targets = np.asarray(targets, dtype=np.int64)
        self.num_classes = num_classes
        self.view = view
        self.train_transform = train_transform
        self.image_size = image_size
        self.resize_size = resize_size
        self._limit = limit
        self._seed = seed
        self._epoch = 0
        self._use_native = use_native and os.environ.get(
            "AL_TPU_NO_NATIVE") != "1"
        self.decode_threads = decode_threads
        # (height, width) per index, filled on first native touch — image
        # files are immutable, so headers are parsed at most once.
        self._dims_cache: dict = {}
        self.image_shape = (image_size, image_size, 3)

    def __len__(self) -> int:
        if self._limit is not None:
            return min(self._limit, len(self.paths))
        return len(self.paths)

    def set_epoch(self, epoch: int) -> None:
        """Advance the crop-RNG stream: crops are a pure function of
        (seed, epoch, index) — reproducible regardless of gather order or
        decode-thread interleaving (torch draws crop params from a shared
        global stream, so its crops depend on worker scheduling)."""
        self._epoch = int(epoch)

    def _decode_one(self, path: str, index: int) -> np.ndarray:
        PILImage = _require_pil()
        with open(path, "rb") as fh:
            img = PILImage.open(fh).convert("RGB")
        s = self.image_size
        if self.train_transform:
            rng = np.random.default_rng(
                (self._seed, self._epoch, int(index)))
            top, left, ch, cw = random_resized_crop_params(
                img.height, img.width, rng)
            img = img.resize((s, s), PILImage.BILINEAR,
                             box=(left, top, left + cw, top + ch))
        else:
            # Resize(256) (short side) + CenterCrop(224).
            r = self.resize_size
            if img.width <= img.height:
                new_w, new_h = r, max(1, int(round(img.height * r / img.width)))
            else:
                new_h, new_w = r, max(1, int(round(img.width * r / img.height)))
            img = img.resize((new_w, new_h), PILImage.BILINEAR)
            left = (new_w - s) // 2
            top = (new_h - s) // 2
            img = img.crop((left, top, left + s, top + s))
        return np.asarray(img, dtype=np.uint8)

    def _crop_rect(self, h: int, w: int, index: int
                   ) -> Tuple[int, int, int, int]:
        """(top, left, ch, cw) for one image under the current view."""
        if self.train_transform:
            rng = np.random.default_rng(
                (self._seed, self._epoch, int(index)))
            return random_resized_crop_params(h, w, rng)
        # Resize(short=256) + CenterCrop(224) == centered crop of
        # 224 * short/256 in the original image, bilinear-resized.
        short = min(h, w)
        box = int(round(self.image_size * short / self.resize_size))
        return (h - box) // 2, (w - box) // 2, box, box

    def _native_dims(self, idxs: np.ndarray) -> Optional[np.ndarray]:
        """Per-index (h, w) via the header cache; -1 rows mean libjpeg
        can't handle that file (PIL decodes it instead)."""
        from . import native
        missing = [int(i) for i in idxs if int(i) not in self._dims_cache]
        if missing:
            dims = native.jpeg_dims([self.paths[i] for i in missing],
                                    self.decode_threads)
            if dims is None:
                return None
            for i, hw in zip(missing, dims):
                self._dims_cache[i] = (int(hw[0]), int(hw[1]))
        return np.asarray([self._dims_cache[int(i)] for i in idxs],
                          dtype=np.int32)

    def _gather_native(self, idxs: np.ndarray) -> Optional[np.ndarray]:
        """Batch decode via native/decode.cpp.  Files the native path can't
        handle (non-JPEG extension, CMYK encodings, parse failures) fall
        back to PIL INDIVIDUALLY — one odd file never disables the fast
        path for the rest of the dataset."""
        from . import native
        if native.load() is None:
            self._use_native = False  # no library: skip the probe forever
            return None
        paths = [self.paths[int(i)] for i in idxs]
        is_jpeg = np.asarray(
            [p.lower().endswith((".jpg", ".jpeg")) for p in paths])
        dims = self._native_dims(idxs) if is_jpeg.any() else None
        if dims is None:
            return None
        ok = is_jpeg & (dims[:, 0] > 0)
        out = np.empty((len(idxs), *self.image_shape), dtype=np.uint8)
        if ok.any():
            sel = np.flatnonzero(ok)
            rects = np.asarray(
                [self._crop_rect(*self._dims_cache[int(idxs[i])],
                                 int(idxs[i])) for i in sel],
                dtype=np.int32)
            decoded, failed = native.decode_crop_resize(
                [paths[i] for i in sel], rects, self.image_size,
                self.decode_threads)
            out[sel] = decoded
            ok[sel[failed]] = False
        for i in np.flatnonzero(~ok):
            out[i] = self._decode_one(paths[i], int(idxs[i]))
        return out

    def gather(self, idxs: np.ndarray) -> np.ndarray:
        idxs = np.asarray(idxs)
        if self._use_native:
            out = self._gather_native(idxs)
            if out is not None:
                return out
        out = np.empty((len(idxs), *self.image_shape), dtype=np.uint8)
        for i, idx in enumerate(idxs):
            out[i] = self._decode_one(self.paths[int(idx)], int(idx))
        return out


class ImageFolderDataset(_DiskImageDataset):
    """Class-per-subdirectory layout (torchvision ImageFolder semantics:
    classes are the sorted subdirectory names)."""

    def __init__(self, root: str, view: ViewSpec, train_transform: bool,
                 num_classes: int = 1000, limit: Optional[int] = None,
                 seed: int = 0):
        classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise FileNotFoundError(f"No class directories under '{root}'")
        class_to_idx = {c: i for i, c in enumerate(classes)}
        paths, targets = [], []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(_IMG_EXTS):
                    paths.append(os.path.join(cdir, fname))
                    targets.append(class_to_idx[c])
        super().__init__(paths, targets, max(num_classes, len(classes)),
                         view, train_transform, limit=limit, seed=seed)
        self.classes = classes


class FileListDataset(_DiskImageDataset):
    """``path label`` per line (custom_imbalanced_imagenet.py:22-26)."""

    def __init__(self, root: str, list_file: str, view: ViewSpec,
                 train_transform: bool, num_classes: int = 1000,
                 limit: Optional[int] = None, seed: int = 0):
        paths, targets = [], []
        with open(list_file) as fh:
            for line in fh:
                parts = line.split()
                if len(parts) >= 2:
                    paths.append(os.path.join(root, parts[0]))
                    targets.append(int(parts[1]))
        super().__init__(paths, targets, num_classes, view, train_transform,
                         limit=limit, seed=seed)


def get_data_imagenet(data_path: str, debug_mode: bool = False, **_unused):
    """train/ and val/ subdirs (custom_imagenet.py:32-36)."""
    limit = 50 if debug_mode else None
    train_view = ViewSpec(IMAGENET_NORM, augment=True, pad=0)  # flip only
    val_view = ViewSpec(IMAGENET_NORM, augment=False)
    traindir = os.path.join(data_path, "train")
    valdir = os.path.join(data_path, "val")
    train_set = ImageFolderDataset(traindir, train_view, True, limit=limit)
    al_set = ImageFolderDataset(traindir, val_view, False, limit=limit)
    test_set = ImageFolderDataset(valdir, val_view, False, limit=limit)
    return train_set, test_set, al_set


def get_data_imbalanced_imagenet(data_path: str, debug_mode: bool = False,
                                 list_dir: Optional[str] = None, **_unused):
    """ImageNet-LT: file-list train/al over the train images + ImageFolder
    val (custom_imbalanced_imagenet.py:62-77)."""
    limit = 50 if debug_mode else None
    train_view = ViewSpec(IMAGENET_NORM, augment=True, pad=0)
    val_view = ViewSpec(IMAGENET_NORM, augment=False)
    list_dir = list_dir or os.path.join(data_path, "ImageNet_LT")
    train_list = os.path.join(list_dir, "ImageNet_LT_train.txt")
    train_set = FileListDataset(data_path, train_list, train_view, True,
                                limit=limit)
    al_set = FileListDataset(data_path, train_list, val_view, False,
                             limit=limit)
    test_set = ImageFolderDataset(os.path.join(data_path, "val"), val_view,
                                  False, limit=limit)
    return train_set, test_set, al_set


DATASETS.register("imagenet", get_data_imagenet)
DATASETS.register("imbalanced_imagenet", get_data_imbalanced_imagenet)
