"""Synthetic class imbalance: exponential / step subsampling.

Re-implements src/data_utils/custom_imbalanced_cifar10.py:29-61
(``get_img_num_per_cls`` + ``gen_imbalanced_data``) as index selection over
any in-memory dataset, with the imbalance seed controlling the per-class
subsample (reference seeds the global np.random at :24).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..registry import DATASETS
from .core import ArrayDataset, CIFAR10_NORM, ViewSpec


def img_num_per_cls(n_total: int, num_classes: int, imbalance_type: str,
                    imbalance_factor: float) -> List[int]:
    """Per-class sample counts (custom_imbalanced_cifar10.py:29-43)."""
    img_max = n_total / num_classes
    if imbalance_type == "exp":
        return [int(img_max * imbalance_factor ** (c / (num_classes - 1.0)))
                for c in range(num_classes)]
    if imbalance_type == "step":
        return ([int(img_max)] * (num_classes // 2)
                + [int(img_max * imbalance_factor)] * (num_classes // 2))
    raise ValueError("Choose a valid imbalance_type: one of exp or step.")


def imbalanced_indices(targets: np.ndarray, counts: Sequence[int],
                       seed: int) -> np.ndarray:
    """Seeded per-class subsample, classes concatenated in label order
    (custom_imbalanced_cifar10.py:45-61)."""
    rng = np.random.default_rng(seed)
    targets = np.asarray(targets)
    out = []
    for cls, count in enumerate(counts):
        idx = np.flatnonzero(targets == cls)
        rng.shuffle(idx)
        out.append(idx[:count])
    return np.concatenate(out)


def make_imbalanced(dataset: ArrayDataset, imbalance_type: Optional[str],
                    imbalance_factor: float, seed: int) -> ArrayDataset:
    if imbalance_type is None:
        return dataset
    counts = img_num_per_cls(len(dataset.images), dataset.num_classes,
                             imbalance_type, imbalance_factor)
    keep = imbalanced_indices(dataset.targets, counts, seed)
    return ArrayDataset(dataset.images[keep], dataset.targets[keep],
                        dataset.num_classes, dataset.view)


def get_data_imbalanced_cifar10(data_path: str, debug_mode: bool = False,
                                imbalance_args=None, download: bool = False,
                                **_unused):
    """Imbalanced train/al over CIFAR-10 with a balanced test set
    (custom_imbalanced_cifar10.py:86-100)."""
    from .cifar10 import load_cifar10_arrays

    (tr_images, tr_targets), (te_images, te_targets) = load_cifar10_arrays(
        data_path, download=download)
    limit = 50 if debug_mode else None
    train_view = ViewSpec(CIFAR10_NORM, augment=True, pad=4)
    val_view = ViewSpec(CIFAR10_NORM, augment=False)

    full_train = ArrayDataset(tr_images, tr_targets, 10, train_view)
    imb = imbalance_args
    imbalanced = make_imbalanced(full_train, imb.imbalance_type,
                                 imb.imbalance_factor, imb.imbalance_seed)
    train_set = ArrayDataset(imbalanced.images, imbalanced.targets, 10,
                             train_view, limit=limit)
    al_set = train_set.with_view(val_view)
    test_set = ArrayDataset(te_images, te_targets, 10, val_view, limit=limit)
    return train_set, test_set, al_set


def get_data_imbalanced_synthetic(data_path=None, debug_mode: bool = False,
                                  imbalance_args=None, n_train: int = 512,
                                  num_classes: int = 10, image_size: int = 32,
                                  seed: int = 1234, **_unused):
    """Imbalanced variant of the synthetic dataset, so the imbalance code
    path is testable without CIFAR on disk."""
    from .synthetic import get_data_synthetic

    train_set, test_set, _ = get_data_synthetic(
        n_train=n_train, num_classes=num_classes, image_size=image_size,
        seed=seed, debug_mode=False)
    imb = imbalance_args
    limit = 50 if debug_mode else None
    train_set = make_imbalanced(train_set, imb.imbalance_type,
                                imb.imbalance_factor, imb.imbalance_seed)
    train_set = ArrayDataset(train_set.images, train_set.targets,
                             num_classes, train_set.view, limit=limit)
    al_set = train_set.with_view(test_set.view)
    return train_set, test_set, al_set


DATASETS.register("imbalanced_cifar10", get_data_imbalanced_cifar10)
DATASETS.register("imbalanced_synthetic", get_data_imbalanced_synthetic)
