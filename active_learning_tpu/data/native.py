"""ctypes bindings for the native data-path component (native/decode.cpp).

The C++ side does the bandwidth-heavy work — libjpeg decode, crop, bilinear
resize, straight into one preallocated uint8 batch buffer with an internal
thread pool.  Crop-rectangle RANDOMNESS stays in Python
(data/imagenet.py) so augmentation remains a pure function of
(seed, epoch, index).

The library is built lazily with g++ on first use and cached under
native/build/; if the toolchain or libjpeg is missing, callers fall back to
the PIL path (``load() returns None``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

from ..utils.logging import get_logger

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libaldata.so")

_lock = threading.Lock()
_lib = None
_load_failed = False


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "decode.cpp")
    if not os.path.exists(src):
        return False
    os.makedirs(os.path.dirname(_SO_PATH), exist_ok=True)
    # Compile to a process-unique temp name, then rename: the publish is
    # atomic, so concurrent first-users can never dlopen a half-written .so.
    tmp = f"{_SO_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-fPIC", "-std=c++17", "-shared", src,
           "-o", tmp, "-ljpeg", "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO_PATH)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        get_logger().warning(
            f"native decode build failed ({e!r}); using the PIL path")
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it if needed; None if unavailable."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_SO_PATH) and not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            get_logger().warning(f"native decode load failed ({e!r})")
            _load_failed = True
            return None
        lib.al_jpeg_dims.restype = ctypes.c_int
        lib.al_jpeg_dims.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
        lib.al_decode_crop_resize.restype = ctypes.c_int
        lib.al_decode_crop_resize.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
        _lib = lib
        return _lib


def _path_array(paths: Sequence[str]):
    arr = (ctypes.c_char_p * len(paths))()
    arr[:] = [p.encode() for p in paths]
    return arr


def jpeg_dims(paths: Sequence[str], n_threads: int = 4
              ) -> Optional[np.ndarray]:
    """[N, 2] (height, width) from JPEG headers; rows are (-1, -1) for
    files libjpeg can't parse (caller decides the fallback).  None if the
    native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    out = np.empty((len(paths), 2), dtype=np.int32)
    lib.al_jpeg_dims(
        _path_array(paths), len(paths),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n_threads)
    return out


def decode_crop_resize(paths: Sequence[str], rects: np.ndarray,
                       out_size: int, n_threads: int = 4):
    """Decode + crop (rects[i] = top, left, ch, cw) + bilinear resize into
    a uint8 [N, out_size, out_size, 3] batch.  Returns (batch, failed_mask)
    — failed rows (e.g. CMYK JPEGs) are zeroed for the caller to re-decode
    individually — or None if the native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    rects = np.ascontiguousarray(rects, dtype=np.int32)
    assert rects.shape == (len(paths), 4)
    out = np.empty((len(paths), out_size, out_size, 3), dtype=np.uint8)
    failed = np.zeros(len(paths), dtype=np.uint8)
    lib.al_decode_crop_resize(
        _path_array(paths), len(paths),
        rects.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), out_size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        failed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n_threads)
    return out, failed.astype(bool)
