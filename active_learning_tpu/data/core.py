"""Dataset abstraction for the TPU-native pipeline.

The reference builds three torch datasets over the SAME underlying training
data (src/data_utils/custom_cifar10.py:28-40): ``train_set`` (augmented),
``al_set`` (validation transforms only), ``test_set`` — every ``__getitem__``
returns ``(x, y, index)`` so scores map back to pool indices
(custom_cifar10.py:23-25).

The TPU-first design is different: datasets hand the host pipeline raw
**uint8** batches (4x less host->device DMA than float32), and all math —
normalization and augmentation — runs on-device *inside* the jitted step
where XLA fuses it into the first conv (see data/augment.py).  A "view"
(train vs al) is therefore just a flag choosing the on-device transform, not
a separate dataset copy.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Normalization:
    mean: Tuple[float, ...]
    std: Tuple[float, ...]


# Reference normalization constants (custom_cifar10.py:50-54,
# custom_imagenet.py:49).
CIFAR10_NORM = Normalization((0.4914, 0.4822, 0.4465),
                             (0.2023, 0.1994, 0.2010))
IMAGENET_NORM = Normalization((0.485, 0.456, 0.406), (0.229, 0.224, 0.225))


@dataclasses.dataclass(frozen=True)
class ViewSpec:
    """On-device transform selection for a dataset view.

    augment: random crop (with ``pad`` zero-padding) + horizontal flip — the
      reference's train transform (custom_cifar10.py:47-49).  The al/test
      views use augment=False (custom_cifar10.py:36-40).
    """

    normalization: Normalization
    augment: bool = False
    pad: int = 4


class Dataset:
    """Base: in-memory or disk-backed; always indexable by pool index."""

    num_classes: int
    targets: np.ndarray  # int64 [N]
    view: ViewSpec
    image_shape: Tuple[int, int, int]  # H, W, C of a gathered batch row

    def __len__(self) -> int:
        raise NotImplementedError

    def gather(self, idxs: np.ndarray) -> np.ndarray:
        """Return uint8 images [len(idxs), H, W, C] for the given indices."""
        raise NotImplementedError

    def class_counts(self) -> np.ndarray:
        return np.bincount(self.targets[: len(self)],
                           minlength=self.num_classes)


class ArrayDataset(Dataset):
    """In-memory uint8 dataset (CIFAR-scale data; fits in host RAM).

    ``limit`` implements the reference's debug_mode truncation to 50
    samples (custom_cifar10.py:14-17) without copying.
    """

    def __init__(self, images: np.ndarray, targets: Sequence[int],
                 num_classes: int, view: ViewSpec,
                 limit: Optional[int] = None):
        assert images.dtype == np.uint8 and images.ndim == 4, (
            "images must be uint8 [N,H,W,C]")
        self.images = images
        self.targets = np.asarray(targets, dtype=np.int64)
        assert len(self.images) == len(self.targets)
        self.num_classes = num_classes
        self.view = view
        self._limit = limit
        self.image_shape = tuple(images.shape[1:])

    def __len__(self) -> int:
        if self._limit is not None:
            return min(self._limit, len(self.images))
        return len(self.images)

    def gather(self, idxs: np.ndarray) -> np.ndarray:
        return self.images[np.asarray(idxs)]

    def with_view(self, view: ViewSpec) -> "ArrayDataset":
        """A second view over the same arrays (zero-copy) — how the
        train_set/al_set pair shares storage."""
        return ArrayDataset(self.images, self.targets, self.num_classes,
                            view, limit=self._limit)
