"""Synthetic learnable dataset.

The environment has zero network egress, so CIFAR/ImageNet can only be used
when already on disk.  This dataset generates class-structured images
(per-class template + noise) so end-to-end AL runs, tests, and benchmarks
exercise real learning dynamics without any downloads.  It plays the role of
the reference's ``--debug_mode`` tiny datasets (src/utils/parser.py:70-71)
but with controllable size/shape/class count.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..registry import DATASETS
from .core import ArrayDataset, Normalization, ViewSpec

SYNTH_NORM = Normalization((0.5, 0.5, 0.5), (0.25, 0.25, 0.25))


def _class_templates(num_classes: int, hw: int, rng: np.random.Generator
                     ) -> np.ndarray:
    # Class templates are SPATIALLY COARSE (a 4x4 color grid upsampled to
    # hw), not per-pixel noise: real images keep their identity under the
    # train view's random crop/flip, and so must these — a per-pixel
    # template decorrelates under a few pixels of shift, which silently
    # capped every augmented fit on this dataset at near-chance accuracy.
    coarse = rng.uniform(40, 215, size=(num_classes, 4, 4, 3))
    reps = -(-hw // 4)
    return np.repeat(np.repeat(coarse, reps, axis=1),
                     reps, axis=2)[:, :hw, :hw, :]


def _make_images(n: int, templates: np.ndarray, rng: np.random.Generator,
                 noise_sigma: float = 25.0
                 ) -> Tuple[np.ndarray, np.ndarray]:
    num_classes, hw = templates.shape[0], templates.shape[1]
    targets = rng.integers(0, num_classes, size=n)
    noise = rng.normal(0, noise_sigma, size=(n, hw, hw, 3))
    images = np.clip(templates[targets] + noise, 0, 255).astype(np.uint8)
    return images, targets.astype(np.int64)


def get_data_synthetic(
    data_path: Optional[str] = None,
    n_train: int = 512,
    n_test: int = 128,
    num_classes: int = 10,
    image_size: int = 32,
    seed: int = 1234,
    debug_mode: bool = False,
    **_unused,
):
    """Build the (train_set, test_set, al_set) triple over shared storage,
    mirroring the reference's dataset-triple contract
    (src/data_utils/custom_cifar10.py:28-40)."""
    rng = np.random.default_rng(seed)
    # ONE template set shared by train and test: each split drawing its
    # own class definitions made the test set a different task — models
    # that learned the train classes scored at or BELOW chance on test,
    # silently, for every synthetic accuracy number.
    templates = _class_templates(num_classes, image_size, rng)
    tr_images, tr_targets = _make_images(n_train, templates, rng)
    te_images, te_targets = _make_images(n_test, templates, rng)
    limit = 50 if debug_mode else None

    train_view = ViewSpec(SYNTH_NORM, augment=True, pad=4)
    val_view = ViewSpec(SYNTH_NORM, augment=False)

    train_set = ArrayDataset(tr_images, tr_targets, num_classes, train_view,
                             limit=limit)
    al_set = train_set.with_view(val_view)
    test_set = ArrayDataset(te_images, te_targets, num_classes, val_view,
                            limit=limit)
    return train_set, test_set, al_set


DATASETS.register("synthetic", get_data_synthetic)
