"""Format-exact CIFAR-10 facsimile archives for offline validation.

The sandbox has zero network egress, so the REAL data path — fetch ->
md5 -> extract -> python-batch pickles -> ``get_data_cifar10`` — cannot
be exercised against the canonical ``cifar-10-python.tar.gz``.  This
module writes an archive that is byte-layout-faithful to it (same member
names, same pickle schema: ``data`` as uint8 [N, 3072] row-major RGB
planes, ``labels`` as a list, plus ``batches.meta``), with the images
drawn from the learnable synthetic template dataset.  Everything the
loader and the fetch path do to the real file, they do to this one; only
the pixel content differs.

Used by tests/test_data.py and by scripts/cifar10_evidence.py (the
shortened-protocol evidence run, VERDICT r4 Missing #1/#4).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tarfile
from typing import Tuple

import numpy as np

from .synthetic import _class_templates, _make_images

LABEL_NAMES = ["airplane", "automobile", "bird", "cat", "deer",
               "dog", "frog", "horse", "ship", "truck"]


def write_cifar10_facsimile(path: str, n_train: int = 50000,
                            n_test: int = 10000, seed: int = 77,
                            noise_sigma: float = 25.0,
                            contrast: float = 1.0
                            ) -> Tuple[str, str]:
    """Write ``cifar-10-python.tar.gz`` at ``path``; returns (path, md5).

    ``n_train`` is split over five ``data_batch_*`` files exactly like
    the canonical archive (10,000 rows each at full size).

    ``noise_sigma``/``contrast`` set task difficulty (contrast scales the
    class templates toward mid-grey).  At the synthetic defaults a linear
    model saturates from the first batch of labels; evidence runs use
    MODEL-CALIBRATED settings, because the informative band depends
    sharply on the learner (live-v5e map, 2026-07-31, shortened protocol
    with cosine+warmup at lr 0.04-0.05):

      * linear probe: 0.06/σ60 → ~40% at 1k labels rising to ~65% at 6k
        (matches the sklearn logistic-regression ceiling).
      * from-scratch ResNet-18: 0.06/σ60 → pinned at CHANCE (the CNN
        fits noise before finding the template subspace a linear model
        reads off directly); 0.08/σ65 → bistable (some rounds 52%, some
        chance — and Margin's preference for the noisiest examples makes
        ITS rounds likelier to collapse); **0.10/σ60 → the informative
        band** (67% at 1k labels rising to ~90% at 5k, stable across
        seeds WITH 3 warmup epochs — without warmup even 0.12/σ55
        collapses on re-init); 0.12/σ55 → 85-94%; ≥0.25/σ50 → ~100% by
        round 0 (Bayes-trivial).

    The Bayes classifier for template+iid-Gaussian is linear, so the
    probe tracks the Bayes ceiling while a CNN transitions sharply from
    noise-fitting to near-Bayes — calibrate per model, not per dataset
    (scripts/cifar10_evidence.py applies these defaults)."""
    rng = np.random.default_rng(seed)
    templates = _class_templates(10, 32, rng)
    templates = 127.5 + contrast * (templates - 127.5)

    def batch_dict(n):
        images, targets = _make_images(n, templates, rng,
                                       noise_sigma=noise_sigma)
        # HWC uint8 -> the archive's [N, 3072] R-plane/G-plane/B-plane
        # rows (the inverse of the loader's reshape/transpose).
        data = images.transpose(0, 3, 1, 2).reshape(n, -1)
        return {"data": np.ascontiguousarray(data),
                "labels": [int(t) for t in targets]}

    per = -(-n_train // 5)
    tmpdir = os.path.dirname(os.path.abspath(path))
    os.makedirs(tmpdir, exist_ok=True)
    members = []
    left = n_train
    for i in range(1, 6):
        n = min(per, left)
        left -= n
        members.append((f"data_batch_{i}", batch_dict(n)))
    members.append(("test_batch", batch_dict(n_test)))
    members.append(("batches.meta",
                    {"label_names": LABEL_NAMES,
                     "num_cases_per_batch": per, "num_vis": 3072}))

    with tarfile.open(path, "w:gz") as tar:
        for name, obj in members:
            blob = pickle.dumps(obj, protocol=2)
            info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
            info.size = len(blob)
            import io
            tar.addfile(info, io.BytesIO(blob))
    with open(path, "rb") as fh:
        md5 = hashlib.md5(fh.read()).hexdigest()
    return path, md5
