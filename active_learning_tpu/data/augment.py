"""On-device input transforms, designed to live *inside* the jitted train
step so XLA fuses them into the first convolution (HBM-bandwidth-friendly:
the host ships uint8; everything else happens on-chip).

Replaces the reference's host-side torchvision transforms
(src/data_utils/custom_cifar10.py:43-54): RandomCrop(32, padding=4) +
RandomHorizontalFlip for training, plain normalize for al/test views.
Randomness comes from the JAX PRNG key threaded through the train step.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .core import Normalization, ViewSpec


def normalize(images_u8: jnp.ndarray, norm: Normalization) -> jnp.ndarray:
    """uint8 [B,H,W,C] -> float32 normalized (ToTensor + Normalize).

    Space-to-depth batches (data/pipeline.space_to_depth: channel index
    (di*2 + dj)*C + c) are per-PIXEL the same affine transform, so the
    mean/std vectors just tile 4x along the blocked channel axis."""
    mean = jnp.asarray(norm.mean, dtype=jnp.float32) * 255.0
    std = jnp.asarray(norm.std, dtype=jnp.float32) * 255.0
    blocks = images_u8.shape[-1] // mean.shape[0]
    if blocks > 1:
        mean = jnp.tile(mean, blocks)
        std = jnp.tile(std, blocks)
    return (images_u8.astype(jnp.float32) - mean) / std


def s2d_flip(images: jnp.ndarray, flip: jnp.ndarray) -> jnp.ndarray:
    """Per-sample horizontal flip of a space-to-depth batch [B, H/2, W/2,
    4C]: mirroring the original W axis reverses the blocked column axis
    AND swaps the dj∈{0,1} in-block offsets — channel (di, dj, c) maps to
    (di, 1-dj, c).  Exactly equal to s2d(flip(x)); pinned by
    tests/test_s2d_stem.py."""
    c4 = images.shape[-1]
    c = c4 // 4
    perm = jnp.arange(c4).reshape(2, 2, c)[:, ::-1, :].reshape(-1)
    flipped = images[:, :, ::-1, :][..., perm]
    return jnp.where(flip[:, None, None, None], flipped, images)


def random_crop_flip(images: jnp.ndarray, key: jax.Array,
                     pad: int = 4) -> jnp.ndarray:
    """Per-sample random crop (zero padding, torch RandomCrop semantics) +
    per-sample horizontal flip, fully vectorized.

    Shapes are static: pad -> vmapped dynamic_slice back to the original
    H x W, so the whole thing stays one fused XLA computation.
    """
    b, h, w, c = images.shape
    key_crop, key_flip = jax.random.split(key)
    if pad > 0:
        padded = jnp.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
        offsets = jax.random.randint(key_crop, (b, 2), 0, 2 * pad + 1)

        def crop_one(img, off):
            return jax.lax.dynamic_slice(img, (off[0], off[1], 0), (h, w, c))

        cropped = jax.vmap(crop_one)(padded, offsets)
    else:
        # pad=0: flip-only augmentation (ImageNet's random-resized crop
        # happens host-side at decode time; only the flip is on-device).
        cropped = images
    flip = jax.random.bernoulli(key_flip, 0.5, (b,))
    flipped = jnp.where(flip[:, None, None, None], cropped[:, :, ::-1, :],
                        cropped)
    return flipped


def apply_view(images_u8: jnp.ndarray, view: ViewSpec,
               key: jax.Array = None, train: bool = True) -> jnp.ndarray:
    """Apply a dataset view's transform on device.

    augment=True + train=True: random crop/flip on raw uint8 (so the crop
    padding is black pixels, matching torch's RandomCrop-before-Normalize
    order), then normalize.  Otherwise: normalize only (the reference's val
    transform).
    """
    x = images_u8
    s2d = len(view.normalization.mean) * 4 == x.shape[-1]
    if view.augment and train:
        assert key is not None, "augmentation requires a PRNG key"
        if s2d:
            # Space-to-depth batches only exist on the 224px path, whose
            # train view is flip-only (pad=0: the random crop happened at
            # decode time, data/imagenet.py).
            assert view.pad == 0, "s2d batches support flip-only views"
            _, key_flip = jax.random.split(key)
            x = s2d_flip(x, jax.random.bernoulli(key_flip, 0.5,
                                                 (x.shape[0],)))
        else:
            x = random_crop_flip(x, key, pad=view.pad)
    return normalize(x, view.normalization)
