"""CIFAR-10 from disk, with a self-provisioning fetch path.

The reference self-provisions via torchvision ``download=True``
(custom_cifar10.py:30-33); this module reads the standard
``cifar-10-batches-py`` python-pickle layout from disk and, when the
batches are absent, can fetch + verify + extract the canonical
``cifar-10-python.tar.gz`` itself (``fetch_cifar10``) — one command on
any networked machine.  Environments with zero egress (this sandbox)
get a fast, explicit error instead of a hang.

Produces the reference's dataset triple: augmented train view, plain al
view over the same storage, and the test split
(src/data_utils/custom_cifar10.py:28-40).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tarfile
import tempfile
from typing import Optional, Tuple

import numpy as np

from ..registry import DATASETS
from .core import ArrayDataset, CIFAR10_NORM, ViewSpec

_TRAIN_FILES = [f"data_batch_{i}" for i in range(1, 6)]
_TEST_FILES = ["test_batch"]

# The canonical distribution (same source torchvision uses,
# torchvision/datasets/cifar.py): md5 of cifar-10-python.tar.gz.
CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
CIFAR10_TGZ_MD5 = "c58f30108f718f92721af3b95e74349a"


_DEFAULT = object()  # late-bind to the module constants (patchable)


def fetch_cifar10(data_path: str, url: Optional[str] = None,
                  expected_md5=_DEFAULT, timeout: float = 60.0) -> str:
    """Download + md5-verify + extract the CIFAR-10 python batches under
    ``data_path``; returns the ``cifar-10-batches-py`` directory.

    The one-command bootstrap the reference gets from torchvision
    ``download=True``.  ``file://`` URLs work (tests use them), member
    paths are validated before extraction, and a bad digest raises
    before anything is unpacked."""
    import urllib.request

    url = CIFAR10_URL if url is None else url
    if expected_md5 is _DEFAULT:
        expected_md5 = CIFAR10_TGZ_MD5
    dest_root = os.path.join(data_path, "cifar-10-batches-py")
    if os.path.isfile(os.path.join(dest_root, "data_batch_1")):
        return dest_root
    os.makedirs(data_path, exist_ok=True)
    digest = hashlib.md5()
    with tempfile.NamedTemporaryFile(dir=data_path, suffix=".tar.gz",
                                     delete=False) as tmp:
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                while True:
                    chunk = resp.read(1 << 20)
                    if not chunk:
                        break
                    digest.update(chunk)
                    tmp.write(chunk)
            tmp.flush()
            if expected_md5 and digest.hexdigest() != expected_md5:
                raise RuntimeError(
                    f"CIFAR-10 download from {url} has md5 "
                    f"{digest.hexdigest()}, expected {expected_md5} — "
                    "corrupt or tampered archive; nothing extracted")
            with tarfile.open(tmp.name, "r:gz") as tar:
                for member in tar.getmembers():
                    # The canonical archive holds exactly one top-level
                    # dir of flat files; anything else (absolute paths,
                    # .., links) is hostile and refused.
                    parts = member.name.split("/")
                    if (member.name.startswith(("/", "..")) or ".." in parts
                            or not (member.isfile() or member.isdir())):
                        raise RuntimeError(
                            f"refusing suspicious archive member "
                            f"'{member.name}'")
                tar.extractall(data_path, filter="data")
        finally:
            os.unlink(tmp.name)
    if not os.path.isfile(os.path.join(dest_root, "data_batch_1")):
        raise FileNotFoundError(
            f"archive from {url} extracted but no "
            f"cifar-10-batches-py/data_batch_1 under {data_path}")
    return dest_root


def _load_batches(root: str, files) -> Tuple[np.ndarray, np.ndarray]:
    images, targets = [], []
    for fname in files:
        path = os.path.join(root, fname)
        with open(path, "rb") as fh:
            entry = pickle.load(fh, encoding="latin1")
        data = entry["data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        images.append(np.ascontiguousarray(data))
        targets.extend(entry.get("labels", entry.get("fine_labels")))
    return np.concatenate(images).astype(np.uint8), np.asarray(
        targets, dtype=np.int64)


def find_cifar10_root(data_path: str, download: bool = False) -> str:
    candidates = [data_path, os.path.join(data_path, "cifar-10-batches-py")]
    for cand in candidates:
        if cand and os.path.isfile(os.path.join(cand, "data_batch_1")):
            return cand
    if download:
        try:
            return fetch_cifar10(data_path)
        except OSError as e:  # DNS/socket failure: no egress
            raise FileNotFoundError(
                f"CIFAR-10 batches not found under '{data_path}' and the "
                f"download from {CIFAR10_URL} failed ({e!r}). On a "
                "networked machine this fetch is automatic; offline, "
                "place the cifar-10-batches-py directory there yourself.")
    raise FileNotFoundError(
        f"CIFAR-10 python batches not found under '{data_path}'. Expected "
        "'data_batch_1'..'data_batch_5' + 'test_batch' (the "
        "cifar-10-batches-py layout). Pass download=True (CLI: "
        "--download_data) to fetch the canonical archive, or use the "
        "'synthetic' dataset.")


def load_cifar10_arrays(data_path: str, download: bool = False):
    root = find_cifar10_root(data_path, download=download)
    train = _load_batches(root, _TRAIN_FILES)
    test = _load_batches(root, _TEST_FILES)
    return train, test


def get_data_cifar10(data_path: str, debug_mode: bool = False,
                     download: bool = False, **_unused):
    (tr_images, tr_targets), (te_images, te_targets) = load_cifar10_arrays(
        data_path, download=download)
    limit = 50 if debug_mode else None
    train_view = ViewSpec(CIFAR10_NORM, augment=True, pad=4)
    val_view = ViewSpec(CIFAR10_NORM, augment=False)

    train_set = ArrayDataset(tr_images, tr_targets, 10, train_view,
                             limit=limit)
    al_set = train_set.with_view(val_view)
    test_set = ArrayDataset(te_images, te_targets, 10, val_view, limit=limit)
    return train_set, test_set, al_set


DATASETS.register("cifar10", get_data_cifar10)
