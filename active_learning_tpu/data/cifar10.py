"""CIFAR-10 from disk (no network: the torchvision download path of the
reference, custom_cifar10.py:30-33, is replaced by reading an existing
``cifar-10-batches-py`` directory — the standard python-pickle layout).

Produces the reference's dataset triple: augmented train view, plain al
view over the same storage, and the test split
(src/data_utils/custom_cifar10.py:28-40).
"""

from __future__ import annotations

import os
import pickle
from typing import Optional, Tuple

import numpy as np

from ..registry import DATASETS
from .core import ArrayDataset, CIFAR10_NORM, ViewSpec

_TRAIN_FILES = [f"data_batch_{i}" for i in range(1, 6)]
_TEST_FILES = ["test_batch"]


def _load_batches(root: str, files) -> Tuple[np.ndarray, np.ndarray]:
    images, targets = [], []
    for fname in files:
        path = os.path.join(root, fname)
        with open(path, "rb") as fh:
            entry = pickle.load(fh, encoding="latin1")
        data = entry["data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        images.append(np.ascontiguousarray(data))
        targets.extend(entry.get("labels", entry.get("fine_labels")))
    return np.concatenate(images).astype(np.uint8), np.asarray(
        targets, dtype=np.int64)


def find_cifar10_root(data_path: str) -> str:
    candidates = [data_path, os.path.join(data_path, "cifar-10-batches-py")]
    for cand in candidates:
        if cand and os.path.isfile(os.path.join(cand, "data_batch_1")):
            return cand
    raise FileNotFoundError(
        f"CIFAR-10 python batches not found under '{data_path}'. Expected "
        "'data_batch_1'..'data_batch_5' + 'test_batch' (the "
        "cifar-10-batches-py layout). This environment has no network "
        "egress, so the data must already be on disk; use the 'synthetic' "
        "dataset otherwise.")


def load_cifar10_arrays(data_path: str):
    root = find_cifar10_root(data_path)
    train = _load_batches(root, _TRAIN_FILES)
    test = _load_batches(root, _TEST_FILES)
    return train, test


def get_data_cifar10(data_path: str, debug_mode: bool = False, **_unused):
    (tr_images, tr_targets), (te_images, te_targets) = load_cifar10_arrays(
        data_path)
    limit = 50 if debug_mode else None
    train_view = ViewSpec(CIFAR10_NORM, augment=True, pad=4)
    val_view = ViewSpec(CIFAR10_NORM, augment=False)

    train_set = ArrayDataset(tr_images, tr_targets, 10, train_view,
                             limit=limit)
    al_set = train_set.with_view(val_view)
    test_set = ArrayDataset(te_images, te_targets, 10, val_view, limit=limit)
    return train_set, test_set, al_set


DATASETS.register("cifar10", get_data_cifar10)
