"""lock-discipline: a static race detector for the thread zoo.

PRs 7-9 each needed review rounds to catch the same bug class: a field
that one thread mutates under a lock being read bare by another (the
resident cache's iteration paths, the pipeline's plan/bus state, the
telemetry sinks written by the watchdog thread).  This checker turns
the discipline into a module-local declaration the engine can PROVE:

    _GUARDED_BY = {"_plan": "_cv", "stats": "_cv"}      # field -> lock
    _LOCKED_HELPERS = ("hit",)                          # called under it

Every lexical read or write of a guarded field — ``self.<field>`` /
``obj.<field>`` attribute access, ``d["<field>"]`` subscripts, and
``.get("<field>")``/``.setdefault("<field>")``/``.pop("<field>")`` dict
calls (the spelling the resident cache uses) — must sit inside a
``with self.<lock>:`` / ``with <LOCK>:`` block, or inside a function
declared as a locked helper (named in ``_LOCKED_HELPERS`` or suffixed
``_locked`` — the existing ``_next_job_locked`` convention).
``__init__``/``__new__`` are exempt: an object under construction is
not yet shared.

The check is LEXICAL: a nested function defined inside a ``with`` block
counts as guarded even if something later calls it bare (don't do
that), and aliases hoisted out of a locked region are not tracked.
That is the same trade every annotation-based race checker
(GUARDED_BY in Clang's thread-safety analysis, the original Java
``@GuardedBy``) makes — cheap, zero-false-negative on the direct-access
pattern this codebase uses, and the registry documents intent even
where the proof is partial.

Suppression: ``# al-lint: lock-ok <reason>`` on the flagged line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..engine import Checker, Context
from ..findings import Finding

_DICT_KEY_CALLS = {"get", "setdefault", "pop"}
_EXEMPT_FNS = {"__init__", "__new__"}


def _module_registry(tree: ast.Module, rel: str, problems: List[Finding]
                     ) -> Tuple[Optional[Dict[str, str]], set]:
    """Parse ``_GUARDED_BY`` (dict of str -> str literals) and
    ``_LOCKED_HELPERS`` (tuple of str literals) from the module body.
    Returns (guarded map or None, helper names)."""
    guarded = None
    helpers: set = set()
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = {t.id for t in node.targets if isinstance(t, ast.Name)}
        if "_GUARDED_BY" in names:
            if not isinstance(node.value, ast.Dict):
                problems.append(Finding(
                    check="lock-discipline", path=rel, line=node.lineno,
                    message="_GUARDED_BY must be a literal dict of "
                            "{'field': 'lock'} string pairs — the "
                            "registry must be statically checkable"))
                continue
            guarded = {}
            for k, v in zip(node.value.keys, node.value.values):
                if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    guarded[k.value] = v.value
                else:
                    problems.append(Finding(
                        check="lock-discipline", path=rel,
                        line=getattr(k, "lineno", node.lineno),
                        message="_GUARDED_BY holds a non-literal entry — "
                                "fields and locks are declared as string "
                                "literals"))
        elif "_LOCKED_HELPERS" in names \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, str):
                    helpers.add(elt.value)
    return guarded, helpers


def _lock_names_of_with(node) -> set:
    """The terminal names of a With statement's context managers:
    ``with self._cv:`` -> {_cv}, ``with _CACHE_LOCK:`` -> {_CACHE_LOCK}."""
    names = set()
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Attribute):
            names.add(expr.attr)
        elif isinstance(expr, ast.Name):
            names.add(expr.id)
    return names


def _lock_defined(tree: ast.Module, lock: str) -> bool:
    """The declared lock must exist somewhere: a module-level assignment
    (``_CACHE_LOCK = threading.RLock()``) or an instance attribute
    assignment (``self._cv = threading.Condition()``)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Name) and t.id == lock:
                    return True
                if isinstance(t, ast.Attribute) and t.attr == lock:
                    return True
    return False


class LockDisciplineChecker(Checker):
    id = "lock-discipline"
    title = ("every access to a _GUARDED_BY field happens under its "
             "declared lock")
    suppress_token = "lock-ok"

    def check(self, ctx: Context) -> List[Finding]:
        problems: List[Finding] = []
        for path in ctx.files:
            tree, err = ctx.tree(path)
            if err is not None:
                continue  # parse failures are the legacy checks' finding
            rel = ctx.rel(path)
            guarded, helpers = _module_registry(tree, rel, problems)
            if not guarded:
                continue
            locks = set(guarded.values())
            for lock in sorted(locks):
                if not _lock_defined(tree, lock):
                    problems.append(Finding(
                        check=self.id, path=rel, line=0,
                        message=f"_GUARDED_BY names lock {lock!r} but "
                                "nothing in the module defines it — the "
                                "registry drifted from the code",
                        hint="declare the lock (module-level or in "
                             "__init__) or fix the registry entry"))
            self._scan(tree, rel, guarded, helpers, problems)
        return problems

    # -- the lexical walk -------------------------------------------------

    def _scan(self, tree, rel, guarded, helpers, problems):
        checker = self

        def fn_exempt(name: str) -> bool:
            return (name in _EXEMPT_FNS or name in helpers
                    or name.endswith("_locked"))

        def visit(node, held: frozenset, exempt: bool):
            """held: lock names lexically held here; exempt: inside a
            constructor or declared locked helper."""
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                exempt = exempt or fn_exempt(node.name)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                held = held | frozenset(_lock_names_of_with(node))
            elif not exempt:
                checker._check_access(node, rel, guarded, held, problems)
            for child in ast.iter_child_nodes(node):
                visit(child, held, exempt)

        visit(tree, frozenset(), False)

    def _check_access(self, node, rel, guarded, held, problems):
        field = None
        if isinstance(node, ast.Attribute) and node.attr in guarded:
            field = node.attr
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str) \
                and node.slice.value in guarded:
            field = node.slice.value
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _DICT_KEY_CALLS and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str) \
                and node.args[0].value in guarded:
            field = node.args[0].value
        if field is None:
            return
        lock = guarded[field]
        if lock in held:
            return
        problems.append(Finding(
            check=self.id, path=rel, line=node.lineno,
            message=(f"{field!r} is guarded by {lock!r} "
                     f"(_GUARDED_BY) but accessed outside any "
                     f"'with {lock}:' block — a cross-thread race"),
            hint=f"wrap the access in 'with ...{lock}:', move it into a "
                 "*_locked/_LOCKED_HELPERS helper, or annotate "
                 "'# al-lint: lock-ok <reason>'"))
