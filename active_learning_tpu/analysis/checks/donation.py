"""donation-safety: no use-after-donate of jitted buffers.

``donate_argnums`` hands an argument's device buffer to XLA for in-place
reuse — after the call the Python reference points at a DELETED array,
and touching it again raises (or, with a stale view, silently reads
garbage).  PR 9's ``reinit_optimizer`` dodged exactly this by hand; this
checker proves it for the whole tree:

  * every ``jax.jit(..., donate_argnums=...)`` def is discovered (the
    ``@functools.partial(jax.jit, donate_argnums=(...))`` decorator
    spelling and direct ``jax.jit(fn, ...)`` calls), and its
    ``donate_argnums`` must be a LITERAL int/tuple — a computed donation
    set cannot be checked;
  * modules whose donating steps are stored on attributes (the trainer's
    ``self._train_step`` family) declare them:

        _DONATES = {"_train_step": (0,), "_epoch_scan": (0,)}

    and every declared name must actually be assigned somewhere in the
    module (registry drift is a finding);
  * at every call site of a donating callable — by local name inside the
    def's own enclosing scope, or by attribute name from ``_DONATES`` —
    the argument expression at each donated position (a plain name,
    dotted path, or literal-keyed subscript) must not be READ again in
    the enclosing function after the call: a statement that rebinds the
    path (``state, ... = step(state, ...)``) clears it; a later
    rebinding kills the taint; a call inside a loop without a same-
    statement rebind taints the whole loop body (the next iteration
    reads the donated buffer).

  Calls inside jit-decorated functions are SKIPPED: donation of a traced
  value inside another trace is a no-op, not a hazard.  Arguments that
  are fresh expressions (``f(jnp.asarray(x))``) are unobservable after
  the call and therefore safe.  Positions hidden behind ``*args``
  splats are not resolvable statically and are skipped.

Suppression: ``# al-lint: donated-ok <reason>`` on the call (or use)
line; the reason string is REQUIRED and rides into the --json report.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..engine import Checker, Context
from ..findings import Finding


def _is_jit_expr(node) -> bool:
    """True when the expression mentions ``jit`` (jax.jit / an aliased
    jit name) — used both for decorator detection and traced-context
    exemption."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == "jit":
            return True
        if isinstance(n, ast.Name) and n.id == "jit":
            return True
    return False


def _donate_positions(call: ast.Call):
    """The literal donate_argnums of a jit(...) call expression:
    (positions tuple, None) or (None, error string) when non-literal."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,), None
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in v.elts):
            return tuple(e.value for e in v.elts), None
        return None, ("donate_argnums is not a literal int/tuple — the "
                      "donation set must be statically checkable")
    return None, None


def _path_of(node) -> Optional[Tuple[str, ...]]:
    """A checkable access path: Name -> ("x",), Attribute chains ->
    ("self", "vaal_state"), literal-keyed Subscripts -> ("oh", "['p']").
    None for anything else (fresh temporaries are safe by construction).
    """
    if isinstance(node, ast.Name):
        return (node.id,)
    if isinstance(node, ast.Attribute):
        base = _path_of(node.value)
        return None if base is None else base + (node.attr,)
    if isinstance(node, ast.Subscript) \
            and isinstance(node.slice, ast.Constant) \
            and isinstance(node.slice.value, (str, int)):
        base = _path_of(node.value)
        return None if base is None else base + (f"[{node.slice.value!r}]",)
    return None


def _assigned_paths(stmt) -> List[Tuple[str, ...]]:
    """Paths a statement REBINDS (Assign/AnnAssign/AugAssign/For
    targets; tuple/list targets flattened — but not walked deeper:
    ``state.opt_state = x`` rebinds the attribute path, not ``state``
    itself)."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    flat = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            flat.extend(t.elts)
        else:
            flat.append(t)
    out = []
    for t in flat:
        p = _path_of(t)
        if p is not None:
            out.append(p)
    return out


def _is_load(node) -> bool:
    ctx = getattr(node, "ctx", None)
    return ctx is None or isinstance(ctx, ast.Load)


def _reads_path(stmt, path) -> bool:
    """True when ``stmt`` LOADS ``path`` or any extension of it (reading
    ``state.params`` after donating ``state`` is still a read of the
    dead buffer's tree).  Store/Del contexts don't count — an
    assignment TARGET is a rebind, not a read."""
    for n in ast.walk(stmt):
        if not _is_load(n):
            continue
        p = _path_of(n)
        if p is not None and len(p) >= len(path) \
                and p[:len(path)] == path:
            return True
    return False


def _contains(root, node) -> bool:
    for n in ast.walk(root):
        if n is node:
            return True
    return False


class _Scope:
    """One discovered donating callable: name, donated positions, and
    the AST scope its bare name is visible in (module or enclosing
    function)."""

    def __init__(self, name: str, positions: Tuple[int, ...], scope_node):
        self.name = name
        self.positions = positions
        self.scope_node = scope_node


class DonationSafetyChecker(Checker):
    id = "donation-safety"
    title = ("arguments at donate_argnums positions are never read "
             "after the donating call")
    suppress_token = "donated-ok"

    def check(self, ctx: Context) -> List[Finding]:
        problems: List[Finding] = []
        # Pass 1: collect every module's _DONATES registry.  The union
        # is applied package-wide — the trainer's donating steps are
        # called through attributes from bench.py and the strategies,
        # and an attribute call site doesn't care which module declared
        # the step.
        union: Dict[str, Tuple[int, ...]] = {}
        for path in ctx.files:
            tree, err = ctx.tree(path)
            if err is not None:
                continue
            union.update(self._registry(tree, ctx.rel(path), problems))
        for path in ctx.files:
            tree, err = ctx.tree(path)
            if err is not None:
                continue
            self._check_module(tree, ctx.rel(path), union, problems)
        return problems

    # -- discovery --------------------------------------------------------

    def _registry(self, tree, rel, problems) -> Dict[str, Tuple[int, ...]]:
        """One module's _DONATES declaration (attribute-stored donating
        steps), validated: literal entries only, every declared name
        assigned somewhere in the declaring module."""
        registry: Dict[str, Tuple[int, ...]] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "_DONATES"
                    for t in node.targets):
                if not isinstance(node.value, ast.Dict):
                    problems.append(Finding(
                        check=self.id, path=rel, line=node.lineno,
                        message="_DONATES must be a literal dict of "
                                "{'name': (positions...)} — the registry "
                                "must be statically checkable"))
                    continue
                for k, v in zip(node.value.keys, node.value.values):
                    ok = (isinstance(k, ast.Constant)
                          and isinstance(k.value, str)
                          and isinstance(v, (ast.Tuple, ast.List))
                          and all(isinstance(e, ast.Constant)
                                  and isinstance(e.value, int)
                                  for e in v.elts))
                    if ok:
                        registry[k.value] = tuple(e.value for e in v.elts)
                    else:
                        problems.append(Finding(
                            check=self.id, path=rel,
                            line=getattr(k, "lineno", node.lineno),
                            message="_DONATES holds a non-literal entry"))

        # Registry drift: every declared name must be assigned somewhere.
        if registry:
            assigned = set()
            for n in ast.walk(tree):
                if isinstance(n, (ast.Assign, ast.AnnAssign)):
                    targets = (n.targets if isinstance(n, ast.Assign)
                               else [n.target])
                    for t in targets:
                        if isinstance(t, ast.Attribute):
                            assigned.add(t.attr)
                        elif isinstance(t, ast.Name):
                            assigned.add(t.id)
            for name in sorted(set(registry) - assigned):
                problems.append(Finding(
                    check=self.id, path=rel, line=0,
                    message=f"_DONATES names {name!r} but nothing in the "
                            "module assigns it — the registry drifted",
                    hint="fix or remove the registry entry"))
        return registry

    def _check_module(self, tree, rel, registry, problems):
        donating: List[_Scope] = []       # local jit defs

        # Local jit-with-donate defs, with their visibility scope.
        parents: Dict[int, ast.AST] = {}
        for n in ast.walk(tree):
            for c in ast.iter_child_nodes(n):
                parents[id(c)] = n

        def enclosing_fn(node):
            cur = parents.get(id(node))
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cur = parents.get(id(cur))
            return cur

        for n in ast.walk(tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in n.decorator_list:
                    if not (isinstance(dec, ast.Call)
                            and _is_jit_expr(dec)):
                        continue
                    pos, perr = _donate_positions(dec)
                    if perr:
                        problems.append(Finding(
                            check=self.id, path=rel, line=dec.lineno,
                            message=f"{n.name}: {perr}"))
                    elif pos:
                        scope = enclosing_fn(n) or tree
                        donating.append(_Scope(n.name, pos, scope))
            elif isinstance(n, ast.Call) and _is_jit_expr(n) \
                    and not isinstance(parents.get(id(n)),
                                       (ast.Call, ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                # Direct jax.jit(fn, donate_argnums=...) — bind under the
                # assigned name when there is one.
                pos, perr = _donate_positions(n)
                if perr:
                    problems.append(Finding(
                        check=self.id, path=rel, line=n.lineno,
                        message=perr))
                elif pos:
                    parent = parents.get(id(n))
                    if isinstance(parent, ast.Assign):
                        for t in parent.targets:
                            if isinstance(t, ast.Name):
                                scope = enclosing_fn(n) or tree
                                donating.append(
                                    _Scope(t.id, pos, scope))

        if not donating and not registry:
            return

        self._check_calls(tree, rel, donating, registry, parents,
                          problems)

    # -- call-site analysis ----------------------------------------------

    def _check_calls(self, tree, rel, donating, registry, parents,
                     problems):
        by_name: Dict[str, List[_Scope]] = {}
        for d in donating:
            by_name.setdefault(d.name, []).append(d)

        def in_traced_context(node) -> bool:
            cur = parents.get(id(node))
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in cur.decorator_list:
                        if _is_jit_expr(dec):
                            return True
                cur = parents.get(id(cur))
            return False

        def enclosing_function(node):
            cur = parents.get(id(node))
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cur = parents.get(id(cur))
            return cur if cur is not None else tree

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            positions = None
            callee = ""
            if isinstance(node.func, ast.Name) \
                    and node.func.id in by_name:
                for cand in by_name[node.func.id]:
                    if _contains(cand.scope_node, node):
                        positions = cand.positions
                        callee = cand.name
                        break
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in registry:
                positions = registry[node.func.attr]
                callee = node.func.attr
            if positions is None:
                continue
            if in_traced_context(node):
                continue  # donation inside another trace is a no-op
            starred_at = next((i for i, a in enumerate(node.args)
                               if isinstance(a, ast.Starred)),
                              len(node.args))
            fn = enclosing_function(node)
            for p in positions:
                if p >= len(node.args):
                    continue  # passed by keyword — jit binds it itself
                if p >= starred_at:
                    # The donated position hides behind a *splat: the
                    # lint cannot see which expression lands there, so
                    # it cannot prove no-use-after.  Demand a human
                    # annotation instead of staying silent.
                    problems.append(Finding(
                        check=self.id, path=rel, line=node.lineno,
                        message=(f"donated position {p} of {callee}() "
                                 "is hidden behind a *splat — "
                                 "use-after-donate cannot be audited "
                                 "statically"),
                        hint="pass the donated argument positionally, "
                             "or annotate '# al-lint: donated-ok "
                             "<why the donated value is not reused>'"))
                    continue
                path = _path_of(node.args[p])
                if path is None:
                    continue  # fresh temporary — unobservable after
                self._check_use_after(fn, node, rel, callee, p, path,
                                      parents, problems)

    def _check_use_after(self, fn, call, rel, callee, pos, path, parents,
                         problems):
        # The chain of (parent, block, index) block positions from the
        # call's innermost containing statement out to ``fn``.
        chain = []
        cur = call
        while True:
            parent = parents.get(id(cur))
            if parent is None:
                break
            for field in ("body", "orelse", "finalbody"):
                block = getattr(parent, field, None)
                if isinstance(block, list) and cur in block:
                    chain.append((parent, block, block.index(cur)))
                    break
            if parent is fn:
                break
            cur = parent
        if not chain:
            return
        stmt = chain[0][1][chain[0][2]]

        # Same-statement rebind (state, ... = step(state, ...)): safe —
        # every later read sees the call's RESULT, not the dead buffer.
        if any(ap == path for ap in _assigned_paths(stmt)):
            return

        def report(line, where):
            label = path[0] + "".join(
                p if p.startswith("[") else "." + p for p in path[1:])
            problems.append(Finding(
                check=self.id, path=rel, line=line,
                message=(label
                         + f" is donated at position {pos} of "
                         f"{callee}() (line {call.lineno}) and read "
                         f"again {where} — use-after-donate of a "
                         "deleted device buffer"),
                hint="rebind the result over the donated name, copy "
                     "before donating, or annotate "
                     "'# al-lint: donated-ok <reason>'"))

        # Walk outward: later statements in each enclosing block; loop
        # ancestors taint their whole body (the next iteration re-reads
        # the donated buffer).
        for parent, block, idx in chain:
            if isinstance(parent, (ast.For, ast.AsyncFor, ast.While)):
                for n in ast.walk(parent):
                    if not _is_load(n):
                        continue
                    p = _path_of(n)
                    if p is not None and p[:len(path)] == path \
                            and not _contains(stmt, n):
                        report(n.lineno, "inside the enclosing loop "
                                         "(next iteration)")
                        return
            for later in block[idx + 1:]:
                if any(ap == path for ap in _assigned_paths(later)):
                    # A rebind kills the taint for everything AFTER it —
                    # but its own right-hand side still executes against
                    # the dead buffer: ``state = state.replace(...)``
                    # after donating ``state`` is a use-after-donate
                    # dressed as the fix.
                    if _reads_path(later, path):
                        report(later.lineno, "by the statement that "
                                             "rebinds it")
                    return
                if _reads_path(later, path):
                    report(later.lineno, "after the call")
                    return
