"""diagnostics-inert: the experiment-truth layer may never touch the
device, and hot paths may only reach it through a flag gate.

The diagnostics layer (telemetry/diagnostics.py, DESIGN.md §13) rides
numbers that already exist on host — acquisition scores, pick
distances, eval counts.  Its whole off-path contract (disabled = one
None check per site, <2.5µs/call; enabled = zero extra device syncs in
strategy hot paths) holds only as long as two properties stay true, so
this checker proves them statically instead of trusting review:

  1. **Host purity.**  A module declaring ``_DIAGNOSTICS_HOST_PURE =
     True`` (the diagnostics module's marker) may not import jax in any
     form, reference the ``jax`` name, or call a device-sync primitive
     (``block_until_ready`` / ``device_get`` / ``device_put`` /
     ``copy_to_host_async``).  numpy + stdlib only: the module can only
     consume arrays that are ALREADY host arrays — it is structurally
     incapable of adding a hidden device round-trip.

  2. **Gated call sites.**  Any function that reads a ``.diagnostics``
     attribute (the strategy/driver hook surface) must contain an
     ``if``/ternary/``while`` whose test mentions a ``diag``-named
     value — the single flag check the off-path cost bound pins.  An
     ungated read is a hook that runs unconditionally on the hot path.
     ``__init__``/``__new__`` are exempt (construction is the one place
     the attribute is ASSIGNED, not consumed).

Like lock-discipline, the walk is LEXICAL: a gate anywhere in the
function satisfies rule 2 even for code before it (the early-return
``if self.diagnostics is None: return`` idiom), and aliases hoisted
across functions are not tracked — the same cheap trade every
annotation-based checker makes.

Suppression: ``# al-lint: diag-ok <reason>`` on the flagged line.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Checker, Context
from ..findings import Finding

# Device-sync attribute calls forbidden inside a host-pure module.
_SYNC_CALLS = {"block_until_ready", "device_get", "device_put",
               "copy_to_host_async"}
_EXEMPT_FNS = {"__init__", "__new__"}


def _declares_host_pure(tree: ast.Module) -> bool:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            names = {t.id for t in node.targets
                     if isinstance(t, ast.Name)}
            if "_DIAGNOSTICS_HOST_PURE" in names:
                return (isinstance(node.value, ast.Constant)
                        and node.value.value is True)
    return False


def _mentions_diag(expr: ast.AST) -> bool:
    """Whether an expression references a diag-named value (``diag``,
    ``self.diagnostics``, ``strategy.diagnostics``, ...)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and "diag" in node.id:
            return True
        if isinstance(node, ast.Attribute) and "diag" in node.attr:
            return True
    return False


class DiagnosticsInertChecker(Checker):
    id = "diagnostics-inert"
    title = ("the diagnostics layer is host-pure and its hot-path hooks "
             "are flag-gated")
    suppress_token = "diag-ok"

    def check(self, ctx: Context) -> List[Finding]:
        problems: List[Finding] = []
        for path in ctx.files:
            tree, err = ctx.tree(path)
            if err is not None:
                continue  # parse failures are the legacy checks' finding
            rel = ctx.rel(path)
            if _declares_host_pure(tree):
                self._check_host_pure(tree, rel, problems)
            self._check_gated_access(tree, rel, problems)
        return problems

    # -- rule 1: host purity ----------------------------------------------

    def _check_host_pure(self, tree, rel, problems):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] == "jax":
                        problems.append(self._pure_finding(
                            rel, node.lineno,
                            "imports jax — the host-pure diagnostics "
                            "module must stay numpy+stdlib (it can only "
                            "consume arrays already on host)"))
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "jax":
                    problems.append(self._pure_finding(
                        rel, node.lineno,
                        "imports from jax — the host-pure diagnostics "
                        "module must stay numpy+stdlib"))
            elif isinstance(node, ast.Name) and node.id == "jax":
                problems.append(self._pure_finding(
                    rel, node.lineno,
                    "references the jax name inside a host-pure "
                    "diagnostics module"))
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in _SYNC_CALLS):
                problems.append(self._pure_finding(
                    rel, node.lineno,
                    f"calls {node.func.attr}() — a device sync/transfer "
                    "inside the host-pure diagnostics module"))

    def _pure_finding(self, rel, line, message):
        return Finding(
            check=self.id, path=rel, line=line,
            message=f"host-purity violation: {message}",
            hint="move device work to the caller (hand host arrays in), "
                 "or annotate '# al-lint: diag-ok <reason>'")

    # -- rule 2: gated hook sites -----------------------------------------

    def _check_gated_access(self, tree, rel, problems):
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name in _EXEMPT_FNS:
                continue
            gated = any(
                isinstance(node, (ast.If, ast.IfExp, ast.While))
                and _mentions_diag(node.test)
                for node in ast.walk(fn))
            if gated:
                continue
            for node in ast.walk(fn):
                if (isinstance(node, ast.Attribute)
                        and node.attr == "diagnostics"
                        and isinstance(node.ctx, ast.Load)):
                    problems.append(Finding(
                        check=self.id, path=rel, line=node.lineno,
                        message=(f"'{fn.name}' reads .diagnostics with "
                                 "no flag gate anywhere in the function "
                                 "— an unconditional hook on a hot "
                                 "path (the off-path contract is one "
                                 "None/flag check per site)"),
                        hint="guard with 'if ...diagnostics is None: "
                             "return' (or an if/ternary naming the "
                             "flag), or annotate "
                             "'# al-lint: diag-ok <reason>'"))
