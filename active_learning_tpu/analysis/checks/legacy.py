"""The 10 legacy trace_lint checks, ported verbatim onto the engine.

Each function here is a line-for-line port of one check from the old
773-line ``scripts/trace_lint.py`` monolith: same inputs, same verdicts,
same message TEXT (tests/test_telemetry.py asserts on those substrings
and fragment counts), now reading every tree through the shared
``AstCache`` instead of re-parsing per check.  ``scripts/trace_lint.py``
survives as a thin compatibility shim over these functions, so its
import surface (check(), check_resident_feed(), _registered_fault_sites,
the FN-tuple constants) keeps working unchanged.

The check numbering (1-10) and the invariant each enforces are
documented in the shim's module docstring and DESIGN.md §12; ids here:

  1  phase-timer-span      phase_timer derives its seconds from a span
  2  phase-timer-fork      nobody else defines a phase_timer
  3  phase-timer-import    call sites import it from utils.tracing
  4  trace-annotation      TraceAnnotation stays behind tracing.annotate
  5  resident-feed         zero-host-copy resident train feed
  6  sharded-selection     row-sharded selection never un-shards
  7  pipeline-coordinator  speculative scorer never syncs the train stream
  8  fault-sites           closed fault registry, classify= at retries
  9  backward-registry     custom VJPs registered + parity-tested
  10 profiler-confinement  jax.profiler confined to the gate module

No suppressions: the ported checks must produce IDENTICAL verdicts to
the monolith they replace (the acceptance contract of the port), so the
``# al-lint:`` annotation machinery deliberately does not apply here.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional

from ..engine import AstCache, Checker, Context, PKG, REPO, default_files
from ..findings import Finding

TRACING = os.path.join(PKG, "utils", "tracing.py")
PROFILER = os.path.join(PKG, "telemetry", "profiler.py")

# The one module allowed to touch jax.profiler (TraceAnnotation included):
# the device-truth layer.  tracing.annotate delegates here.
ANNOTATION_WHITELIST = {PROFILER}

_CAPTURE_CALLS = {"start_trace", "stop_trace"}
_PROFILER_GATE_FNS = ("start_capture", "finish_capture", "capture_window",
                      "trace_annotation")

TRAINER = os.path.join(PKG, "train", "trainer.py")
RESIDENT_FEED_FNS = ("_resident_feed_arrays", "_build_resident_batch_step")
_HOST_COPY_CALLS = {"gather", "asarray", "concatenate", "ascontiguousarray",
                    "stack", "copy"}

KCENTER = os.path.join(PKG, "strategies", "kcenter.py")
SHARDED_DEVICE_FNS = ("_build_sharded_fns",)
SHARDED_ORCHESTRATOR_FNS = ("_kcenter_greedy_sharded",)
_SHARDED_HOST_CALLS = {"device_get", "asarray"}
_SHARDED_REPLICATE_CALLS = {"replicate", "replicated_sharding"}

PIPELINE = os.path.join(PKG, "experiment", "pipeline.py")
PIPELINE_COORDINATOR_FNS = ("_worker", "_worker_loop", "_score_slice",
                            "_score_chunk", "publish_best", "finalize",
                            "consume")
_PIPELINE_SYNC_CALLS = {"block_until_ready", "device_get"}

FAULTS_REGISTRY = os.path.join(PKG, "faults", "registry.py")

OPS_BACKWARD = os.path.join(PKG, "ops", "backward.py")
OPTIM = os.path.join(PKG, "train", "optim.py")
BACKWARD_TESTS = os.path.join(REPO, "tests", "test_backward.py")
_FUSED_HOST_CALLS = {"asarray", "device_get", "block_until_ready",
                     "gather"}


def _rel(path: str) -> str:
    return os.path.relpath(os.path.abspath(path), REPO)


def _mk(check_id: str, path: str, line: int, message: str) -> Finding:
    return Finding(check=check_id, path=_rel(path), line=line,
                   message=message)


def _tree(cache: Optional[AstCache], path: str):
    """(tree, error) through the shared cache (a private one when the
    caller runs a fragment outside an engine run)."""
    return (cache or AstCache()).get(path)


# -- checks 1-3: phase_timer is ONE measurement ------------------------------

def check_phase_timer_span(tracing_path: str = TRACING,
                           cache: Optional[AstCache] = None
                           ) -> List[Finding]:
    """Check 1: ``phase_timer`` itself opens a tracer span and reports
    the span's own seconds (two clocks = metric/trace drift)."""
    cache = cache or AstCache()
    problems: List[Finding] = []
    src = cache.source(tracing_path)
    if not src:
        tree, err = cache.get(tracing_path)
        if err is not None:
            return [_mk("phase-timer-span", tracing_path, 0,
                        f"unreadable for the phase-timer check ({err})")]
    timer_body = src.split("def phase_timer", 1)
    if len(timer_body) != 2:
        problems.append(_mk("phase-timer-span", tracing_path, 0,
                            "phase_timer not found"))
        timer_src = ""
    else:
        # Up to the next top-level def.
        timer_src = re.split(r"\n@|\ndef ", timer_body[1], maxsplit=1)[0]
    if ".span(" not in timer_src:
        problems.append(_mk(
            "phase-timer-span", tracing_path, 0,
            "phase_timer does not open a tracer span — phase metrics "
            "would fork from the trace"))
    if "duration_s" not in timer_src:
        problems.append(_mk(
            "phase-timer-span", tracing_path, 0,
            "phase_timer does not take its seconds from the span (two "
            "clocks = metric/trace drift)"))
    return problems


def check_phase_timer_fork(files=None, tracing_path: str = TRACING,
                           cache: Optional[AstCache] = None
                           ) -> List[Finding]:
    """Check 2: no competing ``phase_timer`` definitions anywhere.  This
    check also owns the one 'unparseable' finding per broken file (the
    legacy per-file loop emitted it once for checks 2-4 together)."""
    cache = cache or AstCache()
    problems: List[Finding] = []
    for path in (files if files is not None else default_files()):
        if os.path.abspath(path) == os.path.abspath(tracing_path):
            continue
        tree, err = cache.get(path)
        if err is not None:
            problems.append(_mk("phase-timer-fork", path, 0,
                                f"unparseable ({err})"))
            continue
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == "phase_timer":
                problems.append(_mk(
                    "phase-timer-fork", path, node.lineno,
                    "defines its own phase_timer — route through "
                    "utils.tracing"))
    return problems


def _imports_phase_timer_from_tracing(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.endswith("tracing") and any(
                    a.name == "phase_timer" for a in node.names):
                return True
    return False


def check_phase_timer_import(files=None, tracing_path: str = TRACING,
                             cache: Optional[AstCache] = None
                             ) -> List[Finding]:
    """Check 3: every ``phase_timer(`` call site imports it from
    utils.tracing — no copies, no local re-implementations."""
    cache = cache or AstCache()
    problems: List[Finding] = []
    for path in (files if files is not None else default_files()):
        if os.path.abspath(path) == os.path.abspath(tracing_path):
            continue
        tree, err = cache.get(path)
        if err is not None:
            continue  # check 2 already reported the parse failure
        calls = [n for n in ast.walk(tree)
                 if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Name)
                 and n.func.id == "phase_timer"]
        if calls and not _imports_phase_timer_from_tracing(tree):
            problems.append(_mk(
                "phase-timer-import", path, calls[0].lineno,
                "calls phase_timer without importing it from "
                "utils.tracing"))
    return problems


def check_trace_annotation(files=None, whitelist=None,
                           cache: Optional[AstCache] = None
                           ) -> List[Finding]:
    """Check 4: jax.profiler.TraceAnnotation stays behind
    tracing.annotate (AST-level: docstring mentions are fine, attribute
    uses are not)."""
    cache = cache or AstCache()
    whitelist = ({os.path.abspath(p) for p in whitelist}
                 if whitelist is not None
                 else {os.path.abspath(p) for p in ANNOTATION_WHITELIST})
    problems: List[Finding] = []
    for path in (files if files is not None else default_files()):
        if os.path.abspath(path) in whitelist:
            continue
        tree, err = cache.get(path)
        if err is not None:
            continue  # check 2 already reported the parse failure
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr == "TraceAnnotation":
                problems.append(_mk(
                    "trace-annotation", path, node.lineno,
                    "uses jax.profiler.TraceAnnotation directly — use "
                    "utils.tracing.annotate so device spans keep one "
                    "naming convention"))
    return problems


# -- check 5: the resident train feed stays zero-host-copy -------------------

def check_resident_feed(trainer_path: str = TRAINER,
                        cache: Optional[AstCache] = None) -> List[Finding]:
    """The zero-host-copy invariant, statically: the trainer functions in
    RESIDENT_FEED_FNS may look up the shared device cache and do index
    math, but any ``np.`` reference or host-materializing call
    (``.gather``/``.asarray``/``.concatenate``/...) inside them means an
    image array crossed back to the host on the resident feed path."""
    problems: List[Finding] = []
    tree, err = _tree(cache, trainer_path)
    if err is not None:
        return [_mk("resident-feed", trainer_path, 0,
                    f"unreadable for the resident-feed check ({err})")]
    fns = {node.name: node for node in ast.walk(tree)
           if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in RESIDENT_FEED_FNS:
        fn = fns.get(name)
        if fn is None:
            problems.append(_mk(
                "resident-feed", trainer_path, 0,
                f"resident-feed function {name} not found — the "
                "zero-host-copy enforcement has nothing to check"))
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == "np":
                problems.append(_mk(
                    "resident-feed", trainer_path, node.lineno,
                    f"{name} references np — the resident train feed "
                    "must never materialize image arrays on the host"))
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _HOST_COPY_CALLS:
                problems.append(_mk(
                    "resident-feed", trainer_path, node.lineno,
                    f"{name} calls .{node.func.attr}() — host "
                    "materialization on the resident train feed path"))
    return problems


# -- check 6: the sharded selection backend never un-shards ------------------

def check_sharded_selection(kcenter_path: str = KCENTER,
                            cache: Optional[AstCache] = None
                            ) -> List[Finding]:
    """The sharded pool's scale-out invariant, statically (check 6): the
    row-sharded selection backend may move O(N) vectors and O(q) rows,
    but a ``jax.device_get``/``np.asarray`` of the pool, an ``np.``
    reference in the device tier, or a ``replicate``/
    ``replicated_sharding`` call means the [N, D] factor matrix came
    back whole onto one host or chip."""
    problems: List[Finding] = []
    tree, err = _tree(cache, kcenter_path)
    if err is not None:
        return [_mk("sharded-selection", kcenter_path, 0,
                    f"unreadable for the sharded-selection check ({err})")]
    fns = {node.name: node for node in ast.walk(tree)
           if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def call_name(node) -> str:
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                return node.func.attr
            if isinstance(node.func, ast.Name):
                return node.func.id
        return ""

    for name in SHARDED_DEVICE_FNS + SHARDED_ORCHESTRATOR_FNS:
        fn = fns.get(name)
        if fn is None:
            problems.append(_mk(
                "sharded-selection", kcenter_path, 0,
                f"sharded-selection function {name} not found — the "
                "scale-out enforcement has nothing to check"))
            continue
        device_tier = name in SHARDED_DEVICE_FNS
        for node in ast.walk(fn):
            if device_tier and isinstance(node, ast.Name) \
                    and node.id == "np":
                problems.append(_mk(
                    "sharded-selection", kcenter_path, node.lineno,
                    f"{name} references np — the sharded selection "
                    "backend must never materialize pool state on the "
                    "host"))
            called = call_name(node)
            if device_tier and called in _SHARDED_HOST_CALLS:
                problems.append(_mk(
                    "sharded-selection", kcenter_path, node.lineno,
                    f"{name} calls .{called}() — host materialization "
                    "inside the sharded selection backend"))
            if not device_tier and called == "device_get":
                problems.append(_mk(
                    "sharded-selection", kcenter_path, node.lineno,
                    f"{name} calls device_get — the sharded pool must "
                    "never round-trip to host"))
            if called in _SHARDED_REPLICATE_CALLS:
                problems.append(_mk(
                    "sharded-selection", kcenter_path, node.lineno,
                    f"{name} calls {called}() — replicating a "
                    "row-sharded array rebuilds the single-chip ceiling "
                    "the sharded pool removes"))
    return problems


# -- check 7: the pipeline coordinator never syncs the train stream ----------

def check_pipeline_coordinator(pipeline_path: str = PIPELINE,
                               cache: Optional[AstCache] = None
                               ) -> List[Finding]:
    """The pipelined round's overlap invariant, statically (check 7):
    the speculative-scoring coordinator functions may enqueue device
    work and wait on host-side conditions, but a ``block_until_ready``
    or ``device_get`` call inside them would sync the train stream's
    arrays."""
    problems: List[Finding] = []
    tree, err = _tree(cache, pipeline_path)
    if err is not None:
        return [_mk("pipeline-coordinator", pipeline_path, 0,
                    "unreadable for the pipeline-coordinator check "
                    f"({err})")]
    fns = {node.name: node for node in ast.walk(tree)
           if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in PIPELINE_COORDINATOR_FNS:
        fn = fns.get(name)
        if fn is None:
            problems.append(_mk(
                "pipeline-coordinator", pipeline_path, 0,
                f"pipeline coordinator function {name} not found — the "
                "never-sync enforcement has nothing to check"))
            continue
        for node in ast.walk(fn):
            called = ""
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute):
                    called = node.func.attr
                elif isinstance(node.func, ast.Name):
                    called = node.func.id
            if called in _PIPELINE_SYNC_CALLS:
                problems.append(_mk(
                    "pipeline-coordinator", pipeline_path, node.lineno,
                    f"{name} calls {called} — the speculative-scoring "
                    "coordinator must never sync the train stream "
                    "(DESIGN.md §8)"))
    return problems


# -- check 8: the fault registry is closed, wired, and classified ------------

def registered_fault_sites(registry_path: str, problems: List[Finding],
                           cache: Optional[AstCache] = None):
    """Parse faults/registry.py's ``SITES`` tuple; duplicate names are a
    finding (each site registered EXACTLY once)."""
    tree, err = _tree(cache, registry_path)
    if err is not None:
        problems.append(_mk("fault-sites", registry_path, 0,
                            f"unreadable for the fault-site check ({err})"))
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "SITES"
                for t in node.targets):
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                break
            names = []
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                                str):
                    names.append(elt.value)
                else:
                    problems.append(_mk(
                        "fault-sites", registry_path, elt.lineno,
                        "SITES holds a non-literal entry — the registry "
                        "must be statically checkable"))
            for name in set(names):
                if names.count(name) > 1:
                    problems.append(_mk(
                        "fault-sites", registry_path, 0,
                        f"site {name!r} registered more than once in "
                        "SITES — each site is registered exactly once"))
            return names
    problems.append(_mk("fault-sites", registry_path, 0,
                        "SITES tuple not found — the fault-site registry "
                        "has nothing to check against"))
    return None


def check_fault_sites(files=None, registry_path: str = FAULTS_REGISTRY,
                      cache: Optional[AstCache] = None,
                      full_tree: Optional[bool] = None) -> List[Finding]:
    """The failure model's closed-registry invariant, statically
    (check 8): every ``faults.site()``/``site()`` call names a
    registered site as a string literal, every registered site is wired
    at ≥1 call site (full-tree mode only — ``files`` given means a
    negative-case unit test on a fragment), and every ``RetryPolicy``
    construction passes ``classify=`` explicitly.  ``full_tree`` lets
    the trace_lint shim pass an explicit (possibly monkeypatched) file
    list while keeping full-tree semantics."""
    cache = cache or AstCache()
    problems: List[Finding] = []
    registered = registered_fault_sites(registry_path, problems,
                                        cache=cache)
    if registered is None:
        return problems
    if full_tree is None:
        full_tree = files is None
    paths = list(files) if files is not None else list(default_files())
    wired = set()
    for path in paths:
        if os.path.abspath(path) == os.path.abspath(registry_path):
            continue  # the definition site, not a call site
        tree, err = cache.get(path)
        if err is not None:
            problems.append(_mk(
                "fault-sites", path, 0,
                f"unreadable for the fault-site check ({err})"))
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_site = (
                (isinstance(fn, ast.Attribute) and fn.attr == "site"
                 and isinstance(fn.value, ast.Name)
                 and fn.value.id == "faults")
                or (isinstance(fn, ast.Name) and fn.id == "site"))
            is_retry = ((isinstance(fn, ast.Attribute)
                         and fn.attr == "RetryPolicy")
                        or (isinstance(fn, ast.Name)
                            and fn.id == "RetryPolicy"))
            if is_site:
                arg = node.args[0] if node.args else None
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    problems.append(_mk(
                        "fault-sites", path, node.lineno,
                        "faults.site() with a non-literal site name — "
                        "the closed registry cannot be checked"))
                elif arg.value not in registered:
                    problems.append(_mk(
                        "fault-sites", path, node.lineno,
                        f"faults.site({arg.value!r}) names an "
                        "unregistered site (registry: faults/registry.py "
                        "SITES)"))
                else:
                    wired.add(arg.value)
            if is_retry and not any(kw.arg == "classify"
                                    for kw in node.keywords):
                problems.append(_mk(
                    "fault-sites", path, node.lineno,
                    "RetryPolicy(...) without an explicit classify= — "
                    "every retry call site states its transient-vs-fatal "
                    "rule (no bare retries)"))
    if full_tree:
        for name in registered:
            if name not in wired:
                problems.append(Finding(
                    check="fault-sites", path="faults/registry.py", line=0,
                    message=(f"site {name!r} is registered but wired at "
                             "no call site — chaos coverage for it is "
                             "vacuous")))
    return problems


# -- check 9: every custom VJP is registered and parity-tested ---------------

def _str_tuple(tree: ast.AST, name: str, rel: str,
               problems: List[Finding], check_id: str):
    """Parse a module-level ``NAME = ("a", "b", ...)`` tuple of string
    literals; returns None (with a finding) when absent/non-literal."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                break
            names = []
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                                str):
                    names.append(elt.value)
                else:
                    problems.append(Finding(
                        check=check_id, path=rel, line=elt.lineno,
                        message=(f"{name} holds a non-literal entry — "
                                 "the registry must be statically "
                                 "checkable")))
            return names
    problems.append(Finding(
        check=check_id, path=rel, line=0,
        message=(f"{name} tuple not found — the backward registry has "
                 "nothing to check against")))
    return None


def check_backward_registry(files=None, ops_path: str = OPS_BACKWARD,
                            optim_path: str = OPTIM,
                            tests_path: str = BACKWARD_TESTS,
                            cache: Optional[AstCache] = None,
                            full_tree: Optional[bool] = None
                            ) -> List[Finding]:
    """The gradient path's proven-backward invariant, statically
    (check 9): custom VJPs only in ops/backward.py, every one named in
    its ``TRAIN_PATH_VJPS`` and matched by ``PARITY_TESTED_VJPS`` in
    tests/test_backward.py, and the fused optimizer-update functions
    free of host materialization.  ``files`` given = a negative-case
    unit test on a fragment (the custom_vjp location scan only);
    ``full_tree`` lets the shim pass an explicit file list while keeping
    full-tree semantics."""
    cache = cache or AstCache()
    problems: List[Finding] = []

    # a) custom_vjp usage is confined to ops/backward.py.
    if full_tree is None:
        full_tree = files is None
    paths = list(files) if files is not None else list(default_files())
    for path in paths:
        if os.path.abspath(path) == os.path.abspath(ops_path):
            continue
        tree, err = cache.get(path)
        if err is not None:
            problems.append(_mk(
                "backward-registry", path, 0,
                f"unreadable for the backward-registry check ({err})"))
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr == "custom_vjp":
                problems.append(_mk(
                    "backward-registry", path, node.lineno,
                    "jax.custom_vjp outside ops/backward.py — "
                    "hand-written backwards live in the closed registry "
                    "(TRAIN_PATH_VJPS) so each one carries a "
                    "gradient-parity test"))
    if not full_tree:
        return problems

    # b) the registry itself: TRAIN_PATH_VJPS names exist as defs and
    # the module really uses custom_vjp.
    rel_ops = _rel(ops_path)
    ops_tree, err = cache.get(ops_path)
    if err is not None:
        return problems + [_mk(
            "backward-registry", ops_path, 0,
            f"unreadable for the backward-registry check ({err})")]
    registered = _str_tuple(ops_tree, "TRAIN_PATH_VJPS", rel_ops, problems,
                            "backward-registry")
    if registered is None:
        return problems
    defs = {n.name for n in ast.walk(ops_tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in registered:
        if name not in defs:
            problems.append(_mk(
                "backward-registry", ops_path, 0,
                f"TRAIN_PATH_VJPS names {name!r} but no such function is "
                "defined — the registry drifted from the module"))
    if not any(isinstance(n, ast.Attribute) and n.attr == "custom_vjp"
               for n in ast.walk(ops_tree)):
        problems.append(_mk(
            "backward-registry", ops_path, 0,
            "no jax.custom_vjp usage found — TRAIN_PATH_VJPS registers "
            "backwards that do not exist"))

    # c) every registered VJP has a registered parity test.
    rel_tests = _rel(tests_path)
    tests_tree, err = cache.get(tests_path)
    if err is not None:
        return problems + [_mk(
            "backward-registry", tests_path, 0,
            f"unreadable — every custom VJP must carry a parity test "
            f"({err})")]
    tested = _str_tuple(tests_tree, "PARITY_TESTED_VJPS", rel_tests,
                        problems, "backward-registry")
    if tested is not None and set(tested) != set(registered):
        problems.append(_mk(
            "backward-registry", tests_path, 0,
            f"PARITY_TESTED_VJPS {sorted(tested)} != TRAIN_PATH_VJPS "
            f"{sorted(registered)} — a custom backward without a "
            "registered gradient-parity test (or a stale test "
            "registration) can never land"))

    # d) the fused update functions never touch the host.
    optim_tree, err = cache.get(optim_path)
    if err is not None:
        return problems + [_mk(
            "backward-registry", optim_path, 0,
            f"unreadable for the fused-update check ({err})")]
    fused = _str_tuple(optim_tree, "FUSED_UPDATE_FNS", _rel(optim_path),
                       problems, "backward-registry")
    if fused is None:
        return problems
    fns = {n.name: n for n in ast.walk(optim_tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in fused:
        fn = fns.get(name)
        if fn is None:
            problems.append(_mk(
                "backward-registry", optim_path, 0,
                f"FUSED_UPDATE_FNS names {name!r} but no such function "
                "is defined"))
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == "np":
                problems.append(_mk(
                    "backward-registry", optim_path, node.lineno,
                    f"{name} references np — the fused update traces "
                    "inside the donated train step and must never "
                    "materialize state on the host"))
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _FUSED_HOST_CALLS:
                problems.append(_mk(
                    "backward-registry", optim_path, node.lineno,
                    f"{name} calls .{node.func.attr}() — host "
                    "materialization inside the fused optimizer update"))
    return problems


# -- check 10: jax.profiler stays confined to the gate -----------------------

def check_profiler_confinement(files=None, profiler_path: str = PROFILER,
                               cache: Optional[AstCache] = None,
                               full_tree: Optional[bool] = None
                               ) -> List[Finding]:
    """The device-truth layer's one-gate invariant, statically
    (check 10): ``jax.profiler`` imports/attribute access and
    ``start_trace``/``stop_trace`` calls are confined to
    telemetry/profiler.py, and that module really defines the gated API
    and touches jax.profiler.  ``files`` given = a negative-case unit
    test on a fragment (the confinement scan only); ``full_tree`` lets
    the shim pass an explicit file list while keeping full-tree
    semantics."""
    cache = cache or AstCache()
    problems: List[Finding] = []
    if full_tree is None:
        full_tree = files is None
    paths = list(files) if files is not None else list(default_files())
    for path in paths:
        if os.path.abspath(path) == os.path.abspath(profiler_path):
            continue
        tree, err = cache.get(path)
        if err is not None:
            problems.append(_mk(
                "profiler-confinement", path, 0,
                f"unreadable for the profiler-confinement check ({err})"))
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax.profiler" \
                            or alias.name.startswith("jax.profiler."):
                        problems.append(_mk(
                            "profiler-confinement", path, node.lineno,
                            "imports jax.profiler outside telemetry/"
                            "profiler.py — capture windows and device "
                            "annotations go through the gated API "
                            "(DESIGN.md §11)"))
            if isinstance(node, ast.ImportFrom) and node.module:
                if (node.module == "jax"
                        and any(a.name == "profiler"
                                for a in node.names)) \
                        or node.module.startswith("jax.profiler"):
                    problems.append(_mk(
                        "profiler-confinement", path, node.lineno,
                        "imports jax's profiler outside telemetry/"
                        "profiler.py — use the gated API"))
            if isinstance(node, ast.Attribute) \
                    and node.attr == "profiler" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "jax":
                problems.append(_mk(
                    "profiler-confinement", path, node.lineno,
                    "touches jax.profiler outside telemetry/profiler.py "
                    "— the device-truth layer is the one gate"))
            if isinstance(node, ast.Call):
                fn = node.func
                called = (fn.attr if isinstance(fn, ast.Attribute)
                          else fn.id if isinstance(fn, ast.Name) else "")
                if called in _CAPTURE_CALLS:
                    problems.append(_mk(
                        "profiler-confinement", path, node.lineno,
                        f"calls {called}() outside telemetry/profiler.py "
                        "— every capture window goes through the gated "
                        "API (capture_window/start_capture/"
                        "finish_capture)"))
    if not full_tree:
        return problems

    # The gate module itself: the API exists and jax.profiler is really
    # touched (otherwise the confinement above confines nothing).
    gate_tree, err = cache.get(profiler_path)
    if err is not None:
        return problems + [_mk(
            "profiler-confinement", profiler_path, 0,
            f"unreadable for the profiler-gate check ({err})")]
    defs = {n.name for n in ast.walk(gate_tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name in _PROFILER_GATE_FNS:
        if name not in defs:
            problems.append(_mk(
                "profiler-confinement", profiler_path, 0,
                f"gated API function {name} not found — the "
                "capture-window enforcement has nothing to point at"))
    touches = any(
        isinstance(n, ast.Import) and any(
            a.name == "jax.profiler" for a in n.names)
        for n in ast.walk(gate_tree))
    if not touches:
        problems.append(_mk(
            "profiler-confinement", profiler_path, 0,
            "never imports jax.profiler — the gate module is not "
            "actually the gate"))
    return problems


# -- Checker plugins over the functions above --------------------------------

class _LegacyChecker(Checker):
    """Bind one ported function into the plugin registry.  ``files_arg``
    True = the function takes the engine's file set (the package-wide
    scans); False = it targets fixed module paths only.
    ``full_tree_arg`` True = the function distinguishes fragment mode
    from whole-tree mode (the registry-level sub-checks: unwired fault
    sites, VJP parity, the profiler gate module) — the engine's file
    set IS the whole tree, so the plugin passes full_tree=True; without
    it those sub-checks would silently not run on the al_lint path."""

    def __init__(self, check_id: str, title: str, fn, files_arg: bool,
                 full_tree_arg: bool = False):
        self.id = check_id
        self.title = title
        self._fn = fn
        self._files_arg = files_arg
        self._full_tree_arg = full_tree_arg

    def check(self, ctx: Context) -> List[Finding]:
        if self._full_tree_arg:
            return self._fn(files=ctx.files, cache=ctx.cache,
                            full_tree=True)
        if self._files_arg:
            return self._fn(files=ctx.files, cache=ctx.cache)
        return self._fn(cache=ctx.cache)


LEGACY_CHECKERS = (
    _LegacyChecker("phase-timer-span",
                   "phase_timer derives its seconds from ONE tracer span",
                   check_phase_timer_span, files_arg=False),
    _LegacyChecker("phase-timer-fork",
                   "no competing phase_timer definitions",
                   check_phase_timer_fork, files_arg=True),
    _LegacyChecker("phase-timer-import",
                   "phase_timer call sites import it from utils.tracing",
                   check_phase_timer_import, files_arg=True),
    _LegacyChecker("trace-annotation",
                   "jax.profiler.TraceAnnotation stays behind "
                   "tracing.annotate",
                   check_trace_annotation, files_arg=True),
    _LegacyChecker("resident-feed",
                   "resident train feed never materializes images on host",
                   check_resident_feed, files_arg=False),
    _LegacyChecker("sharded-selection",
                   "row-sharded selection never un-shards the pool",
                   check_sharded_selection, files_arg=False),
    _LegacyChecker("pipeline-coordinator",
                   "speculative-scoring coordinator never syncs the train "
                   "stream",
                   check_pipeline_coordinator, files_arg=False),
    _LegacyChecker("fault-sites",
                   "closed fault-site registry, explicit classify= at "
                   "every RetryPolicy",
                   check_fault_sites, files_arg=True,
                   full_tree_arg=True),
    _LegacyChecker("backward-registry",
                   "custom VJPs registered in ops/backward.py and "
                   "parity-tested",
                   check_backward_registry, files_arg=True,
                   full_tree_arg=True),
    _LegacyChecker("profiler-confinement",
                   "jax.profiler confined to the telemetry/profiler.py "
                   "gate",
                   check_profiler_confinement, files_arg=True,
                   full_tree_arg=True),
)
