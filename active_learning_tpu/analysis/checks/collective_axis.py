"""collective-axis: every collective names a registered mesh axis, and
the owner-gather idiom has ONE spelling.

A ``psum`` over a typo'd axis name raises at trace time *on the path
that traces it* — which for rarely-taken branches (a fallback leg, a
pod-only path) is a multi-host incident, not a unit-test red.  And PR
6's review had to impose by hand that the masked-psum owner-gather
idiom (select rows by ownership mask, psum the zeros-or-value result)
is spelled exactly once, in ``parallel/mesh.owner_rows`` — a second
hand-rolled copy is where the exactness contract (non-owners contribute
exact zeros) silently erodes.

Rules:

  * the axis registry is ``parallel/mesh.py``'s module-level
    ``*_AXIS = "literal"`` constants (today: ``DATA_AXIS = "data"``);
  * every call to a named collective (``psum``/``psum_scatter``/
    ``pmax``/``pmin``/``pmean``/``ppermute``/``all_gather``/
    ``all_to_all``/``axis_index``) must name its axis as: a registered
    string literal; a reference to a registered constant
    (``DATA_AXIS``/``mesh_lib.DATA_AXIS``); a local name bound (param
    default or assignment in an enclosing function) to one of those; a
    pass-through parameter named ``axis``/``axis_name`` (forwarding
    helpers like ``owner_rows`` — their call sites are checked
    instead); or a configured ``*.axis_name`` attribute (flax modules
    carry the axis as a field, threaded from the step builder).
    Anything else — an unregistered literal, an unresolvable
    expression — is a finding;
  * a ``psum`` (or ``psum_scatter``) whose operand is a name assigned
    from ``jnp.where(...)`` in the same function is the masked
    owner-gather idiom: allowed only inside its one mesh_lib home —
    ``parallel/mesh.owner_rows`` for the psum broadcast form,
    ``parallel/mesh.owner_rows_scattered`` for the reduce-scatter form
    (the ring column feed's block seeding) — everywhere else the fix
    hint is to call the home;
  * ``ppermute`` is the ring-feed idiom (rotate blocks device-to-
    device around the mesh) and has exactly ONE home:
    ``parallel/mesh.ring_shift``.  A second hand-rolled ring is where
    the every-block-seen-exactly-once contract (and with it the
    bit-identity of the k-center column scans) silently erodes —
    anywhere else, the fix is to call ``mesh_lib.ring_shift``.

Suppression: ``# al-lint: axis-ok <reason>``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ..engine import Checker, Context, PKG
from ..findings import Finding

MESH_PATH = os.path.join(PKG, "parallel", "mesh.py")

COLLECTIVES = ("psum", "psum_scatter", "pmax", "pmin", "pmean",
               "ppermute", "all_gather", "all_to_all", "axis_index")

# Which positional argument carries the axis name, per primitive.
_AXIS_ARG_POS = {name: 1 for name in COLLECTIVES}
_AXIS_ARG_POS["axis_index"] = 0
_AXIS_KEYWORDS = ("axis_name", "axis")

_FORWARD_PARAM_NAMES = {"axis", "axis_name"}


def load_axis_registry(tree) -> Tuple[Set[str], Set[str]]:
    """(registered axis values, registered constant names) from
    parallel/mesh.py's module body: ``NAME_AXIS = "literal"``."""
    values: Set[str] = set()
    names: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id.endswith("_AXIS"):
                    values.add(node.value.value)
                    names.add(t.id)
    return values, names


class CollectiveAxisChecker(Checker):
    id = "collective-axis"
    title = ("collectives name a registered mesh axis; owner-gather is "
             "spelled via mesh_lib.owner_rows")
    suppress_token = "axis-ok"

    def check(self, ctx: Context) -> List[Finding]:
        problems: List[Finding] = []
        mesh_tree, err = ctx.tree(MESH_PATH)
        if err is not None:
            return [Finding(
                check=self.id, path=ctx.rel(MESH_PATH), line=0,
                message=f"unreadable axis registry ({err})")]
        values, const_names = load_axis_registry(mesh_tree)
        if not values:
            problems.append(Finding(
                check=self.id, path=ctx.rel(MESH_PATH), line=0,
                message="no *_AXIS = \"...\" constants found — the axis "
                        "registry is empty, every collective would be "
                        "unresolvable",
                hint="declare the mesh axes as module-level *_AXIS "
                     "string constants in parallel/mesh.py"))
            return problems
        for path in ctx.files:
            tree, perr = ctx.tree(path)
            if perr is not None:
                continue
            self._check_module(tree, ctx.rel(path), path, values,
                               const_names, problems)
        return problems

    # -- axis resolution --------------------------------------------------

    def _resolves(self, expr, values, const_names, fn_stack) -> bool:
        """Can ``expr`` be shown to denote a registered axis?"""
        if isinstance(expr, ast.Constant):
            return isinstance(expr.value, str) and expr.value in values
        if isinstance(expr, ast.Name):
            if expr.id in const_names:
                return True
            # A local binding or parameter default in any enclosing
            # function scope.
            for fn in reversed(fn_stack):
                res = self._name_binding(fn, expr.id, values, const_names)
                if res is not None:
                    return res
            return False
        if isinstance(expr, ast.Attribute):
            if expr.attr in const_names:
                return True  # mesh_lib.DATA_AXIS
            # Configured forwarding: flax modules carry the axis as a
            # field (self.axis_name), threaded from the step builder.
            return expr.attr in _FORWARD_PARAM_NAMES
        if isinstance(expr, (ast.Tuple, ast.List)):
            return all(self._resolves(e, values, const_names, fn_stack)
                       for e in expr.elts)
        return False

    def _name_binding(self, fn, name, values, const_names):
        """True/False when ``name`` is bindable inside ``fn``: a
        parameter (default decides; no default = forwarding param —
        allowed only for axis/axis_name spellings), or an assignment
        from a resolvable expression.  None when ``fn`` says nothing."""
        args = fn.args
        params = args.args + args.kwonlyargs
        defaults = ([None] * (len(args.args) - len(args.defaults))
                    + list(args.defaults) + list(args.kw_defaults))
        for param, default in zip(params, defaults):
            if param.arg != name:
                continue
            if default is not None:
                return self._resolves(default, values, const_names, [fn])
            return name in _FORWARD_PARAM_NAMES
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return self._resolves(node.value, values,
                                              const_names, [fn])
        return None

    # -- the walk ---------------------------------------------------------

    def _check_module(self, tree, rel, abspath, values, const_names,
                      problems):
        in_mesh = os.path.abspath(abspath) == os.path.abspath(MESH_PATH)

        def visit(node, fn_stack):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_stack = fn_stack + [node]
            elif isinstance(node, ast.Call):
                self._check_call(node, rel, in_mesh, values, const_names,
                                 fn_stack, problems)
            for child in ast.iter_child_nodes(node):
                visit(child, fn_stack)

        visit(tree, [])

    def _check_call(self, node, rel, in_mesh, values, const_names,
                    fn_stack, problems):
        fn = node.func
        called = (fn.attr if isinstance(fn, ast.Attribute)
                  else fn.id if isinstance(fn, ast.Name) else "")
        if called not in COLLECTIVES:
            return
        axis_expr = None
        pos = _AXIS_ARG_POS[called]
        if len(node.args) > pos \
                and not any(isinstance(a, ast.Starred)
                            for a in node.args[:pos + 1]):
            axis_expr = node.args[pos]
        else:
            for kw in node.keywords:
                if kw.arg in _AXIS_KEYWORDS:
                    axis_expr = kw.value
                    break
        if axis_expr is None:
            problems.append(Finding(
                check=self.id, path=rel, line=node.lineno,
                message=f"{called}() with no statically visible axis "
                        "argument — the collective's axis cannot be "
                        "audited",
                hint="pass the axis positionally or as axis_name=, "
                     "naming a registered *_AXIS constant"))
            return
        if not self._resolves(axis_expr, values, const_names, fn_stack):
            lit = (f"{axis_expr.value!r}"
                   if isinstance(axis_expr, ast.Constant)
                   else ast.dump(axis_expr)[:60])
            problems.append(Finding(
                check=self.id, path=rel, line=node.lineno,
                message=(f"{called}() over unregistered/unresolvable "
                         f"axis {lit} — collectives must name an axis "
                         "registered in parallel/mesh.py (*_AXIS "
                         "constants)"),
                hint="use DATA_AXIS / mesh_lib.DATA_AXIS (or register "
                     "the new axis constant in parallel/mesh.py)"))
            return
        # The one-spelling owner-gather rule: psum of a where-masked
        # select is mesh_lib.owner_rows' job; its reduce-scatter twin
        # (psum_scatter of the same masked pick — the ring feed's block
        # seeding) is owner_rows_scattered's.
        _MASKED_HOMES = {"psum": "owner_rows",
                         "psum_scatter": "owner_rows_scattered"}
        if called in _MASKED_HOMES and fn_stack \
                and self._is_masked_operand(node, fn_stack[-1]) \
                and not (in_mesh
                         and fn_stack[-1].name == _MASKED_HOMES[called]):
            home = _MASKED_HOMES[called]
            problems.append(Finding(
                check=self.id, path=rel, line=node.lineno,
                message=f"masked-{called} owner-gather idiom spelled by "
                        f"hand ({called} of a jnp.where-masked operand) "
                        f"— the one spelling lives in "
                        f"parallel/mesh.{home}",
                hint=f"call mesh_lib.{home}(arr, idxs, axis) instead "
                     f"of re-deriving the masked {called}"))
        # The one-home ring-feed rule: a bare ppermute IS the ring
        # idiom, and its every-block-seen-exactly-once contract lives
        # in exactly one place.
        if called == "ppermute" and not (
                in_mesh and any(fn.name == "ring_shift"
                                for fn in fn_stack)):
            problems.append(Finding(
                check=self.id, path=rel, line=node.lineno,
                message="ring-permute feed spelled by hand (bare "
                        "ppermute) — the ring-feed idiom's one home is "
                        "parallel/mesh.ring_shift",
                hint="call mesh_lib.ring_shift(tree, ndev, axis) "
                     "instead of re-deriving the ring ppermute"))

    @staticmethod
    def _is_masked_operand(call, fn) -> bool:
        """True when the psum's operand is a local name assigned from a
        ``where(...)`` call inside ``fn``."""
        if not call.args or not isinstance(call.args[0], ast.Name):
            return False
        target = call.args[0].id
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == target
                    for t in node.targets):
                v = node.value
                if isinstance(v, ast.Call):
                    f = v.func
                    name = (f.attr if isinstance(f, ast.Attribute)
                            else f.id if isinstance(f, ast.Name) else "")
                    if name == "where":
                        return True
        return False
