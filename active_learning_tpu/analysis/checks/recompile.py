"""recompile-hazard: the zero-recompile warm-round invariant, statically.

The whole performance story of warm AL rounds rests on "round N+1 adds
zero XLA compiles" (tests/test_compile_reuse.py pins it dynamically; the
``jit_cache_miss_delta`` metric watches it in production).  The two ways
the invariant historically eroded are (a) a ``jax.jit`` sprouting in a
hot-path module outside the step-builder discipline — per-call or
per-round jits whose signatures drift with round state — and (b) a
static operand that is a fresh object every call (an f-string, a
dict/list literal, a lambda): jit hashes statics by value or identity,
so each call is a new cache entry, i.e. a silent recompile per step.

Rules, per hot-path module (train/, strategies/, parallel/, serve/):

  * a module that calls ``jax.jit`` anywhere must declare

        _STEP_BUILDERS = ("_build_train_step", "get_runner", ...)

    and every ``jax.jit`` use must be lexically inside one of those
    functions (module-level jitted defs register their OWN def name —
    they compile once per shape by construction, the registry makes
    them enumerable).  Registry names that match nothing are drift.
  * ``static_argnames``/``static_argnums`` must be literal — a computed
    static set cannot be audited;
  * at same-module call sites of a jitted def, arguments bound to its
    static parameters must not be f-strings (JoinedStr), dict/list/set
    literals or comprehensions, ``dict()``/``list()``/``set()`` calls,
    or lambdas — each is a fresh unhashable/identity-hashed object per
    call: a guaranteed per-call recompile (or TypeError) on a hot path.

Modules outside the hot paths (bench.py, scripts/) may jit freely —
they are measurement tools, not round code.

Suppression: ``# al-lint: recompile-ok <reason>``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from ..engine import Checker, Context, PKG
from ..findings import Finding

# The hot-path tree: every module under these package dirs is round/
# request code — a stray jit there is a warm-round hazard.
HOT_PATH_DIRS = ("train", "strategies", "parallel", "serve", "experiment",
                 "models", "data", "ops")

_FRESH_OBJECT_CALLS = {"dict", "list", "set"}


def _is_load(node) -> bool:
    ctx = getattr(node, "ctx", None)
    return ctx is None or isinstance(ctx, ast.Load)


def _is_hot_path(path: str) -> bool:
    ap = os.path.abspath(path)
    return any(ap.startswith(os.path.join(PKG, d) + os.sep)
               for d in HOT_PATH_DIRS)


def _jit_call_in(node) -> Optional[ast.Call]:
    """The jit(...) call inside a decorator/assignment expression:
    ``jax.jit`` mentioned anywhere in a Call's func or args."""
    if not isinstance(node, ast.Call):
        return None
    for n in ast.walk(node):
        if (isinstance(n, ast.Attribute) and n.attr == "jit") \
                or (isinstance(n, ast.Name) and n.id == "jit"):
            return node
    return None


def _literal_statics(call: ast.Call, rel: str, problems: List[Finding]
                     ) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
    """(static names, static positions) from a jit call's keywords;
    non-literal specs are findings."""
    names: Tuple[str, ...] = ()
    nums: Tuple[int, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names = (v.value,)
            elif isinstance(v, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, str) for e in v.elts):
                names = tuple(e.value for e in v.elts)
            else:
                problems.append(Finding(
                    check="recompile-hazard", path=rel, line=call.lineno,
                    message="static_argnames is not a literal str/tuple "
                            "— the static operand set must be "
                            "statically auditable",
                    hint="spell the statics as a literal tuple of "
                         "strings"))
        elif kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = (v.value,)
            elif isinstance(v, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, int) for e in v.elts):
                nums = tuple(e.value for e in v.elts)
            else:
                problems.append(Finding(
                    check="recompile-hazard", path=rel, line=call.lineno,
                    message="static_argnums is not a literal int/tuple "
                            "— the static operand set must be "
                            "statically auditable",
                    hint="spell the statics as a literal tuple of ints"))
    return names, nums


def _fresh_object(node) -> Optional[str]:
    """A fresh-per-call object that can never hash stably as a jit
    static: returns a short description or None."""
    if isinstance(node, ast.JoinedStr):
        return "an f-string"
    if isinstance(node, ast.Lambda):
        return "a lambda"
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return "a dict literal"
    if isinstance(node, (ast.List, ast.ListComp)):
        return "a list literal"
    if isinstance(node, (ast.Set, ast.SetComp, ast.GeneratorExp)):
        return "a set/generator literal"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in _FRESH_OBJECT_CALLS:
        return f"a fresh {node.func.id}() object"
    return None


class _JitDef:
    def __init__(self, fn: ast.FunctionDef, statics: Tuple[str, ...],
                 nums: Tuple[int, ...]):
        self.fn = fn
        self.params = [a.arg for a in fn.args.args]
        self.static_names = set(statics)
        self.static_positions = set(nums) | {
            i for i, a in enumerate(self.params) if a in self.static_names}


class RecompileHazardChecker(Checker):
    id = "recompile-hazard"
    title = ("jax.jit confined to registered step-builders; no "
             "fresh-object static operands")
    suppress_token = "recompile-ok"

    def check(self, ctx: Context) -> List[Finding]:
        problems: List[Finding] = []
        for path in ctx.files:
            tree, err = ctx.tree(path)
            if err is not None:
                continue
            self._check_module(tree, ctx.rel(path),
                               _is_hot_path(path), problems)
        return problems

    def _check_module(self, tree, rel, hot, problems):
        builders = self._builders(tree, rel, problems)
        # Scope: the package hot paths are mandatory; any other module
        # (bench.py, scripts/) opts IN by declaring _STEP_BUILDERS —
        # measurement tools may jit freely, but a module that declares
        # the discipline gets it enforced.
        if not hot and builders is None:
            return

        # Function defs that carry a jit decorator, with their statics.
        jit_defs: Dict[str, _JitDef] = {}
        # Walk with the enclosing-builder-fn stack to enforce confinement.
        matched_builders = set()

        handled: set = set()  # jit mentions already reported via a def

        def visit(node, fn_stack):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_stack = fn_stack + [node.name]
                for dec in node.decorator_list:
                    call = _jit_call_in(dec)
                    if call is None and not (
                            isinstance(dec, ast.Attribute)
                            and dec.attr == "jit") and not (
                            isinstance(dec, ast.Name) and dec.id == "jit"):
                        continue
                    for n in ast.walk(dec):
                        handled.add(id(n))
                    statics, nums = ((), ())
                    if call is not None:
                        statics, nums = _literal_statics(call, rel,
                                                         problems)
                    jit_defs[node.name] = _JitDef(node, statics, nums)
                    self._confine(node.lineno, node.name, fn_stack,
                                  builders, matched_builders, rel,
                                  problems)
            elif ((isinstance(node, ast.Attribute) and node.attr == "jit")
                  or (isinstance(node, ast.Name) and node.id == "jit"
                      and _is_load(node))) \
                    and id(node) not in handled:
                # Any other jit touch — jax.jit, or a bare aliased name
                # (``from jax import jit``) — must also sit inside a
                # registered builder; the import alias is the cheapest
                # evasion of the discipline otherwise.
                self._confine(node.lineno, None, fn_stack, builders,
                              matched_builders, rel, problems)
            for child in ast.iter_child_nodes(node):
                visit(child, fn_stack)

        visit(tree, [])

        if builders is not None:
            for name in sorted(set(builders) - matched_builders):
                problems.append(Finding(
                    check=self.id, path=rel, line=0,
                    message=f"_STEP_BUILDERS names {name!r} but no "
                            "jax.jit use sits inside it — the registry "
                            "drifted from the module",
                    hint="remove the stale entry or restore the builder"))

        self._check_static_call_sites(tree, rel, jit_defs, problems)

    def _builders(self, tree, rel, problems) -> Optional[Tuple[str, ...]]:
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "_STEP_BUILDERS"
                    for t in node.targets):
                if isinstance(node.value, (ast.Tuple, ast.List)) and all(
                        isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                        for e in node.value.elts):
                    return tuple(e.value for e in node.value.elts)
                problems.append(Finding(
                    check=self.id, path=rel, line=node.lineno,
                    message="_STEP_BUILDERS must be a literal tuple of "
                            "function-name strings"))
                return ()
        return None

    def _confine(self, lineno, def_name, fn_stack, builders,
                 matched_builders, rel, problems):
        if builders is None:
            problems.append(Finding(
                check=self.id, path=rel, line=lineno,
                message="jax.jit in a hot-path module with no "
                        "_STEP_BUILDERS registry — warm-round compile "
                        "discipline cannot be audited",
                hint="declare _STEP_BUILDERS = (...) naming the "
                     "step-builder functions (or the jitted def itself)"))
            return
        hits = [n for n in fn_stack if n in builders]
        if hits:
            matched_builders.update(hits)
            return
        problems.append(Finding(
            check=self.id, path=rel, line=lineno,
            message=("jax.jit outside the registered step-builders "
                     f"({', '.join(builders) or 'none declared'}) — "
                     "every hot-path jit flows through a registered "
                     "builder so warm rounds provably add zero compiles"),
            hint="move the jit into a registered builder or add the "
                 "containing function to _STEP_BUILDERS"))

    def _check_static_call_sites(self, tree, rel, jit_defs, problems):
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in jit_defs):
                continue
            jd = jit_defs[node.func.id]
            starred = next((i for i, a in enumerate(node.args)
                            if isinstance(a, ast.Starred)),
                           len(node.args))
            for i, arg in enumerate(node.args):
                if i >= starred:
                    break
                if i in jd.static_positions:
                    desc = _fresh_object(arg)
                    if desc:
                        self._static_finding(node, rel, jd, i, desc,
                                             problems)
            for kw in node.keywords:
                if kw.arg in jd.static_names:
                    desc = _fresh_object(kw.value)
                    if desc:
                        self._static_finding(node, rel, jd, kw.arg, desc,
                                             problems)

    def _static_finding(self, call, rel, jd, which, desc, problems):
        problems.append(Finding(
            check=self.id, path=rel, line=call.lineno,
            message=(f"{jd.fn.name}() receives {desc} as static operand "
                     f"{which!r} — a fresh object per call means a "
                     "recompile per call on a hot path"),
            hint="pass a hashable, value-stable static (str/int/bool/"
                 "frozen config) or make the operand traced"))
