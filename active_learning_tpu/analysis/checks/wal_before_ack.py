"""wal-before-ack: ingest handlers are durable-before-promise and
host-pure.

The streaming subsystem's one durability claim — "an acked row survives
a kill at any point" — reduces to two properties of the closed handler
registry in stream/ingest.py (``_INGEST_HANDLERS``), so this checker
proves them statically instead of trusting review:

  1. **WAL before ack.**  Inside every registered handler, no
     ack-construction call (a call whose name matches the ``ack`` word
     — ``ack_response``, ``make_ack``, ``ack`` ...) may appear lexically
     before the WAL append (a ``.append(...)`` call on a wal-named
     receiver).  The fsync inside ``IngestWAL.append`` is the promise;
     an ack built first could be delivered by a code path that skipped
     the write.  A handler that acks without ANY wal append is flagged
     too.

  2. **Host purity.**  A module declaring an ``_INGEST_HANDLERS``
     registry may not import jax or reference the ``jax`` name: the ack
     path must never wait on a device — admission, validation, the
     fsync, and the queue push are numpy + stdlib (the same structural
     incapability argument as diagnostics-inert's rule 1).

Like the other annotation-based checkers the walk is LEXICAL: an
ack-call textually after the append satisfies rule 1 even if control
flow could skip the append (don't write that), and aliases of the wal
object are recognized by name shape (``wal``, ``self.wal``,
``ingest_wal``), not dataflow.

The registry is closed: every name in ``_INGEST_HANDLERS`` must resolve
to a module-level function — a registered-but-missing handler means the
HTTP front end routes to something this checker never saw.

Suppression: ``# al-lint: wal-ok <reason>`` on the flagged line.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from ..engine import Checker, Context
from ..findings import Finding

_ACK_WORD = re.compile(r"(^|_)ack(_|$)")


def _handler_registry(tree: ast.Module) -> Optional[List[str]]:
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "_INGEST_HANDLERS"
                for t in node.targets):
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                return []
            return [elt.value for elt in node.value.elts
                    if isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)]
    return None


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def _is_wal_append(node: ast.Call) -> bool:
    """``<wal-named>.append(...)`` — the receiver's terminal name must
    carry the wal word (``wal``, ``self.wal``, ``ingest_wal``)."""
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr == "append"):
        return False
    recv = node.func.value
    name = ""
    if isinstance(recv, ast.Name):
        name = recv.id
    elif isinstance(recv, ast.Attribute):
        name = recv.attr
    return "wal" in name.lower()


class WalBeforeAckChecker(Checker):
    id = "wal-before-ack"
    title = ("ingest handlers append to the WAL before any ack and "
             "stay host-pure (no jax)")
    suppress_token = "wal-ok"

    def check(self, ctx: Context) -> List[Finding]:
        problems: List[Finding] = []
        for path in ctx.files:
            tree, err = ctx.tree(path)
            if err is not None:
                continue  # parse failures are the legacy checks' finding
            registry = _handler_registry(tree)
            if registry is None:
                continue
            rel = ctx.rel(path)
            self._check_host_pure(tree, rel, problems)
            fns = {node.name: node for node in tree.body
                   if isinstance(node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
            for name in registry:
                fn = fns.get(name)
                if fn is None:
                    problems.append(Finding(
                        check=self.id, path=rel, line=0,
                        message=(f"_INGEST_HANDLERS names {name!r} but "
                                 "no module-level function defines it — "
                                 "the closed registry drifted from the "
                                 "code"),
                        hint="define the handler or fix the registry"))
                    continue
                self._check_ordering(fn, rel, problems)
        return problems

    # -- rule 1: WAL before ack -------------------------------------------

    def _check_ordering(self, fn, rel, problems):
        first_append: Optional[int] = None
        acks = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _is_wal_append(node):
                if first_append is None or node.lineno < first_append:
                    first_append = node.lineno
            elif _ACK_WORD.search(_call_name(node)):
                acks.append(node)
        for node in acks:
            if first_append is None:
                problems.append(Finding(
                    check=self.id, path=rel, line=node.lineno,
                    message=(f"'{fn.name}' acks "
                             f"({_call_name(node)}) with NO WAL append "
                             "anywhere in the handler — the ack is a "
                             "durability promise nothing backs"),
                    hint="append the record to the wal (fsync'd) before "
                         "constructing the ack, or annotate "
                         "'# al-lint: wal-ok <reason>'"))
            elif node.lineno < first_append:
                problems.append(Finding(
                    check=self.id, path=rel, line=node.lineno,
                    message=(f"'{fn.name}' constructs its ack "
                             f"({_call_name(node)}) at line "
                             f"{node.lineno}, BEFORE the WAL append at "
                             f"line {first_append} — an ack must never "
                             "exist until the record is durable"),
                    hint="move the wal.append(...) above every "
                         "ack-construction call, or annotate "
                         "'# al-lint: wal-ok <reason>'"))

    # -- rule 2: host purity ----------------------------------------------

    def _check_host_pure(self, tree, rel, problems):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] == "jax":
                        problems.append(self._pure_finding(
                            rel, node.lineno,
                            "imports jax — the ingest-handler module "
                            "must stay numpy+stdlib (the ack path never "
                            "waits on a device)"))
            elif isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "jax":
                    problems.append(self._pure_finding(
                        rel, node.lineno,
                        "imports from jax — the ingest-handler module "
                        "must stay numpy+stdlib"))
            elif isinstance(node, ast.Name) and node.id == "jax":
                problems.append(self._pure_finding(
                    rel, node.lineno,
                    "references the jax name inside the ingest-handler "
                    "module"))

    def _pure_finding(self, rel, line, message):
        return Finding(
            check=self.id, path=rel, line=line,
            message=f"host-purity violation: {message}",
            hint="move device work to the service thread (the handlers "
                 "only validate, WAL-append, and queue), or annotate "
                 "'# al-lint: wal-ok <reason>'")
